"""Device-memory arbiter: one HBM budget every subsystem leases from.

``BENCH_CANDIDATE.json`` showed why this exists: the flat decode path
is healthy at 2709 tok/s/chip while the prefix-cache, engine and
speculative arms die with RESOURCE_EXHAUSTED and the paged engine OOMs
at every batch size — each subsystem allocated HBM assuming it owned
the whole device, and the first one to be wrong killed the process.
This module is the arbitration point that makes the subsystems
coexist: ONE budget, leased out per subsystem, with demand-driven
reclaim and a shed path so an allocation failure degrades the
*request* instead of killing the *process*.

Three layers, lowest first:

  **Accounting** (the PR-6 substrate, unchanged contract): every
  persistent device buffer is declared via :func:`account`, keyed
  ``(subsystem, owner, tag)`` with SET semantics (recovery realloc /
  mesh re-placement replace instead of double-count); gofrlint GL202
  enforces the discipline statically and ``pytest --hbmwatch``
  reconciles declared bytes against ``jax.live_arrays()`` ground
  truth. ``release(owner=self)`` in ``close()``; a weakref finalizer
  backstops owners that die without it.

  **Leases** (the arbiter): :func:`lease` reserves bytes against the
  budget BEFORE an allocation, tagged with a priority class —
  ``PRI_SERVING`` (live serving state, never auto-reclaimed),
  ``PRI_CACHE`` (performance caches that shrink toward lower tiers),
  ``PRI_SCRATCH`` (workspace, dropped first) — and an optional
  **reclaim callback** ``(need_bytes) -> freed_bytes``. When a lease
  would exceed the budget the arbiter runs reclaim over the registered
  callbacks (highest priority class first: scratch, then caches), then
  re-checks; if the deficit survives it raises :class:`HBMExhausted`.
  :func:`alloc` is the one-call form serving code uses: lease (sized
  by ``jax.eval_shape`` when a budget is set), run the allocation
  thunk, catch a REAL device OOM (``XlaRuntimeError`` /
  RESOURCE_EXHAUSTED — :func:`is_oom_error`), reclaim, retry ONCE,
  and account the result. :func:`check` is the zero-byte request-path
  checkpoint the generation admission loop calls per admission.

  **Shed** (the degradation contract): :class:`HBMExhausted` IS a
  ``TooManyRequests`` — it carries ``Retry-After`` and maps to
  429/RESOURCE_EXHAUSTED at both transports, so an uncoverable lease
  routes through the existing AdmissionGate shed surface
  (``AdmissionGate.shed_memory``, ``tpu.shed`` span,
  ``app_tpu_hbm_shed_total``) and the process keeps serving. The
  seeded chaos seam ``HBM_ALLOC`` fires at every lease point, so
  fault schedules can kill allocation N deterministically
  (tests/test_hbm_arbiter.py, tools/hbm_report.py --pressure).

The budget: ``TPU_HBM_BUDGET_MB`` (config; 0/unset = resolve from the
device) minus nothing, else on accelerator backends the device's
reported ``bytes_limit`` minus the ``TPU_HBM_HEADROOM`` fraction
(default 0.1 — XLA needs workspace the registry can't see). On the
CPU backend the budget stays OFF unless set explicitly
(``set_budget``) — tests opt in with a tiny synthetic budget.

**Per-shard leases** (multi-chip tensor-parallel serving,
docs/advanced-guide/multichip-serving.md): lease keys carry a DEVICE
axis — ``(subsystem, owner, tag, device)`` — so a mesh engine's
sharded buffers settle one entry per device. :func:`account` splits a
sharded tree automatically (per-device figures amortize each leaf's
LOGICAL bytes over its shards, so global totals are bit-identical to
the unsharded accounting and a replicated leaf never double-counts);
:func:`alloc_sharded` is the budgeted persist-point form for sharded
thunks (pre-leases an even per-device share, allocates, accounts the
real shard figures — gofrlint GL202 blesses it like ``hbm.alloc``).
With a per-device budget set (``set_device_budget`` /
``TPU_HBM_DEVICE_BUDGET_MB``, auto-resolved per device on accelerator
backends) the arbiter checks each shard's device against ITS budget
and reclaim runs PER-DEVICE: a hot shard's deficit asks only the
leases on that device to spill, never flushing the whole mesh.

Observability: ``app_tpu_device_bytes{subsystem=}`` gauges on every
accounting change, ``app_tpu_hbm_budget_bytes``,
``app_tpu_hbm_device_in_use_bytes{device=}`` /
``app_tpu_hbm_device_budget_bytes`` per-shard gauges,
``app_tpu_hbm_reclaims_total{subsystem=}`` /
``app_tpu_hbm_shed_total{subsystem=}`` counters, ``hbm:*`` counter
tracks plus reclaim/shed instants on the serving timeline, the
``hbm_arbiter`` section of ``/debug/vars`` and
``TPUEngine.health_check`` (both break out per-device in-use and
headroom), and ``tools/hbm_report.py``'s lease table. Subsystem
vocabulary: ``engine`` (serving KV cache + chunk scratch),
``kvcache-t0`` (prefix-pool rows), ``lora`` (adapter stacks),
``spec-decode``/``batcher`` (when they grow device state).
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Callable

from .. import chaos
from ..errors import TooManyRequests

__all__ = ["HBMExhausted", "PRI_CACHE", "PRI_SCRATCH", "PRI_SERVING",
           "account", "alloc", "alloc_sharded", "arbiter_stats", "budget",
           "check", "configure", "device_budget", "device_bytes",
           "is_oom_error", "lease", "live_bytes", "note_shed", "reclaim",
           "release", "reset", "set_budget", "set_device_budget",
           "set_metrics", "set_timeline", "shard_breakdown", "snapshot",
           "tree_nbytes"]

GAUGE = "app_tpu_device_bytes"
BUDGET_GAUGE = "app_tpu_hbm_budget_bytes"
DEVICE_GAUGE = "app_tpu_hbm_device_in_use_bytes"
DEVICE_BUDGET_GAUGE = "app_tpu_hbm_device_budget_bytes"
RECLAIMS_COUNTER = "app_tpu_hbm_reclaims_total"
SHED_COUNTER = "app_tpu_hbm_shed_total"

# Lease priority classes — the RECLAIM order, highest value first:
# scratch/workspace is dropped before caches shrink, and live serving
# state is never auto-reclaimed (a lease may still attach a callback
# at PRI_SERVING — e.g. the paged engine's cold-block release — but it
# runs last).
PRI_SERVING = 0
PRI_CACHE = 1
PRI_SCRATCH = 2


class HBMExhausted(TooManyRequests):
    """A lease the budget cannot cover even after reclaim (or a real
    device OOM that survived the reclaim-then-retry pass). A
    ``TooManyRequests`` subclass on purpose: the failure is SERVED —
    429 with ``Retry-After`` on HTTP, RESOURCE_EXHAUSTED with the
    retry trailer on gRPC — through the same shed surface queue
    overload uses (resilience.AdmissionGate), instead of killing the
    process the way an unhandled allocation failure did in
    BENCH_CANDIDATE.json."""

    def __init__(self, subsystem: str, nbytes: int, *,
                 budget: int | None = None, in_use: int | None = None,
                 retry_after: float = 1.0):
        detail = ""
        if budget is not None:
            detail = (f" (budget {budget >> 20} MiB, "
                      f"in use {(in_use or 0) >> 20} MiB)")
        super().__init__(
            f"hbm arbiter: cannot cover {subsystem!r} lease of "
            f"{int(nbytes)} bytes after reclaim{detail}",
            retry_after=retry_after, reason="hbm")
        self.subsystem = subsystem
        self.nbytes = int(nbytes)


_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OutOfMemory")


def is_oom_error(e: BaseException) -> bool:
    """Is this the allocation-failure class the arbiter owns? Covers
    our own :class:`HBMExhausted`, the chaos harness's injected
    ``ResourceExhausted``, and the runtime's ``XlaRuntimeError`` (or
    any RuntimeError) whose message carries the RESOURCE_EXHAUSTED /
    out-of-memory markers — jaxlib raises different concrete types
    across versions, so the classifier is name+message based rather
    than an isinstance check against a moving target."""
    if isinstance(e, HBMExhausted):
        return True
    name = type(e).__name__
    if "ResourceExhausted" in name or "OutOfMemory" in name:
        return True
    if "XlaRuntimeError" in name or isinstance(e, (RuntimeError,
                                                   MemoryError)):
        msg = str(e)
        return any(m in msg for m in _OOM_MARKERS)
    return False


def tree_nbytes(tree: Any) -> int:
    """Total bytes of the array leaves of ``tree`` (jax or numpy —
    anything with ``nbytes``). None leaves (e.g. absent scale planes)
    contribute nothing."""
    import jax

    return sum(int(getattr(leaf, "nbytes", 0) or 0)
               for leaf in jax.tree.leaves(tree))


def _estimate_nbytes(fn: Callable[[], Any]) -> int:
    """Size an allocation thunk WITHOUT allocating: ``jax.eval_shape``
    traces it abstractly and the ShapeDtypeStruct leaves give exact
    byte figures. 0 when the thunk resists tracing (device_put of
    host data, side effects) — the lease then reserves nothing and
    enforcement falls to the post-hoc OOM retry."""
    try:
        import jax
        import numpy as np

        spec = jax.eval_shape(fn)
        total = 0
        for leaf in jax.tree.leaves(spec):
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is None or dtype is None:
                continue
            total += int(np.prod(shape, dtype=np.int64)) \
                * np.dtype(dtype).itemsize
        return total
    except Exception:
        return 0


def shard_breakdown(tree: Any) -> dict[str, int]:
    """Per-device byte breakdown of ``tree``'s multi-device leaves,
    keyed by device id (str). Each leaf's LOGICAL ``nbytes`` is
    amortized over its shards proportionally to the per-shard physical
    bytes, so the breakdown's total equals :func:`tree_nbytes` of the
    sharded leaves exactly: a fully partitioned leaf attributes each
    shard's own bytes, a replicated leaf attributes 1/N per device
    instead of N full copies — global accounting invariants (hbmwatch
    reconciliation, leak gates) see the same totals whether a buffer
    is sharded or not. Single-device leaves contribute nothing (they
    stay on the device-less axis)."""
    import jax

    out: dict[str, int] = {}
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        nbytes = int(getattr(leaf, "nbytes", 0) or 0)
        if not shards or len(shards) <= 1 or nbytes <= 0:
            continue
        raw: dict[str, int] = {}
        try:
            for sh in shards:
                d = str(sh.device.id)
                raw[d] = raw.get(d, 0) + int(sh.data.nbytes)
        except Exception:
            continue  # exotic backend: leaf stays device-less
        total = sum(raw.values())
        if total <= 0:
            continue
        for d, b in raw.items():
            out[d] = out.get(d, 0) + (b * nbytes) // total
    return out


class _Registry:
    def __init__(self) -> None:
        self._mu = threading.Lock()
        # (subsystem, owner_id, tag, device) -> bytes. The DEVICE axis
        # ("" = device-less / whole-process) is what per-shard leases
        # settle on: a mesh engine's cache is one entry per device, so
        # per-device budgets, reclaim and headroom all see real
        # figures. SET semantics hold per (subsystem, owner, tag)
        # GROUP: re-accounting replaces every device's entry for the
        # group at once (recovery/re-placement re-settles, never
        # double-counts — even across a mesh-shape change).
        self._entries: dict[tuple[str, int, str, str], int] = {}
        # lease metadata per key: (priority, reclaim-callable-or-ref).
        # Bound-method callbacks are held via weakref.WeakMethod so a
        # registered reclaimer never pins its engine alive; account()
        # preserves the lease group's meta across re-accounts (moving
        # it to the new device keys), so a recovery re-account keeps
        # the lease's class and callback.
        self._meta: dict[tuple[str, int, str, str], tuple[int, Any]] = {}
        self._budget: int | None = None
        # per-device budget (bytes each device's leases may hold): the
        # multi-chip half of the arbiter. None = per-device checks off
        # (single-device processes never key entries by device anyway).
        self._dev_budget: int | None = None
        # single-flight reclaim: one pass at a time process-wide.
        # Concurrent requesters return 0 and judge the budget as-is —
        # which also breaks any cross-engine lock cycle a nested
        # reclaim chain could otherwise build (engine A's callback
        # holds A's device lock while B's callback wants B's).
        self._reclaim_mu = threading.Lock()
        self._reclaims: dict[str, int] = {}
        # device labels with a live app_tpu_hbm_device_in_use_bytes
        # series: vanished devices push an explicit 0 at the next
        # _push instead of leaving a stale last value; _push_mu
        # serializes snapshot+export so a stale snapshot can never
        # land after fresher zeros
        self._pushed_devs: set[str] = set()
        self._push_mu = threading.Lock()
        self._reclaimed_bytes = 0
        self._sheds: dict[str, int] = {}
        self._oom_retries: dict[str, int] = {}
        # gauge sinks, weakly held: the registry outlives any Manager
        # and must neither pin one alive nor stop pushing to A because
        # B registered later (two engines, two Managers — both see the
        # same process-truth figures)
        self._sinks: "weakref.WeakSet[Any]" = weakref.WeakSet()
        # serving timelines (observe/timeline.py), same weak fan-out:
        # every accounting change lands a counter sample so the
        # exported Perfetto trace carries an HBM track per subsystem
        self._timelines: "weakref.WeakSet[Any]" = weakref.WeakSet()

    # -- accounting (PR-6 contract; sharded trees split per device) ----------
    def account(self, subsystem: str, tree: Any, *, owner: Any = None,
                tag: str = "") -> Any:
        base = (subsystem, id(owner) if owner is not None else 0, tag)
        n = tree_nbytes(tree)
        dev = shard_breakdown(tree)
        with self._mu:
            # SET semantics over the whole lease GROUP: drop every
            # device's entry for (subsystem, owner, tag) before writing
            # the new figures — a re-placement onto a DIFFERENT mesh
            # shape must not strand stale per-device entries. The
            # group's lease meta (priority, reclaim cb) survives onto
            # the new keys.
            meta = None
            for key in [k for k in self._entries if k[:3] == base]:
                self._entries.pop(key)
                m = self._meta.pop(key, None)
                if m is not None:
                    meta = m
            for key in [k for k in self._meta if k[:3] == base]:
                meta = self._meta.pop(key)
            if dev:
                rem = n - sum(dev.values())
                for d, b in sorted(dev.items()):
                    self._entries[base + (d,)] = b
                    if meta is not None:
                        self._meta[base + (d,)] = meta
                if rem > 0:  # single-device leaves riding a sharded tree
                    self._entries[base + ("",)] = rem
                    if meta is not None:
                        self._meta[base + ("",)] = meta
            else:
                self._entries[base + ("",)] = n
                if meta is not None:
                    self._meta[base + ("",)] = meta
        if owner is not None:
            # safety net for owners that die WITHOUT close() — an
            # __init__ that OOMs after its first account() (exactly
            # the regime this registry exists for) must not leave
            # phantom bytes behind, and a reused id() must not alias a
            # dead owner's entries. Idempotent with close()'s explicit
            # release; runs at the owner's collection.
            try:
                weakref.finalize(owner, self._release_owner_id,
                                 id(owner))
            except TypeError:
                pass  # non-weakrefable owner: explicit release only
        self._push(subsystem)
        return tree

    def _release_owner_id(self, oid: int) -> None:
        touched: set[str] = set()
        with self._mu:
            for key in list(self._entries):
                if key[1] == oid:
                    self._entries.pop(key)
                    self._meta.pop(key, None)
                    touched.add(key[0])
            for key in list(self._meta):
                if key[1] == oid:
                    self._meta.pop(key)
        for sub in touched:
            self._push(sub)

    def release(self, subsystem: str | None = None, *,
                owner: Any = None, tag: str | None = None) -> int:
        """Drop entries by subsystem and/or owner (and optionally an
        exact tag; all devices of each matched lease group); returns
        the bytes released. ``release(owner=self)`` in ``close()``
        drops every subsystem the instance accounted — leases and
        their reclaim callbacks die with the entries."""
        oid = None if owner is None else id(owner)
        dropped = 0
        touched: set[str] = set()
        with self._mu:
            for key in list(self._entries):
                sub, key_oid, key_tag, _ = key
                if subsystem is not None and sub != subsystem:
                    continue
                if oid is not None and key_oid != oid:
                    continue
                if tag is not None and key_tag != tag:
                    continue
                dropped += self._entries.pop(key)
                self._meta.pop(key, None)
                touched.add(sub)
        for sub in touched:
            self._push(sub)
        return dropped

    def live_bytes(self) -> dict[str, int]:
        """Accounted bytes aggregated by subsystem (zero-byte
        subsystems with live keys included — a released-to-zero
        subsystem disappears)."""
        out: dict[str, int] = {}
        with self._mu:
            for (sub, _, _, _), n in self._entries.items():
                out[sub] = out.get(sub, 0) + n
        return dict(sorted(out.items()))

    def device_bytes(self) -> dict[str, int]:
        """Accounted bytes aggregated by device id ("" = device-less
        entries: single-device processes and unsharded leaves)."""
        with self._mu:
            out = self._device_bytes_locked()
        return dict(sorted(out.items()))

    def _device_in_use_locked(self, dev: str) -> int:
        return sum(n for (_, _, _, d), n in self._entries.items()
                   if d == dev)

    def snapshot(self) -> dict[tuple[str, int, str, str], int]:
        with self._mu:
            return dict(self._entries)

    # -- the arbiter ---------------------------------------------------------
    def set_budget(self, nbytes: int | None) -> None:
        """Install (or clear, with None/0) the process HBM budget in
        bytes. Tests use this directly with tiny synthetic budgets;
        production resolves it via :func:`configure`."""
        self._budget = int(nbytes) if nbytes else None
        for m in list(self._sinks):
            try:
                m.set_gauge(BUDGET_GAUGE, float(self._budget or 0))
            except Exception:
                pass

    def budget(self) -> int | None:
        return self._budget

    def set_device_budget(self, nbytes: int | None) -> None:
        """Install (or clear) the PER-DEVICE budget: bytes each
        device's leases may hold. Sharded mesh buffers key by their
        device; device-less entries (single-device processes — their
        whole footprint sits on the default chip) are checked as one
        "" group, so on a multi-chip host a non-mesh engine is still
        bounded by its one chip's budget rather than the process-wide
        per_dev * n_local figure."""
        self._dev_budget = int(nbytes) if nbytes else None
        for m in list(self._sinks):
            try:
                m.set_gauge(DEVICE_BUDGET_GAUGE,
                            float(self._dev_budget or 0))
            except Exception:
                pass

    def device_budget(self) -> int | None:
        return self._dev_budget

    def configure(self, budget_mb: int | None = None,
                  headroom: float = 0.1,
                  device_budget_mb: int | None = None) -> int | None:
        """Resolve and install the budgets. An explicit ``budget_mb``
        / ``device_budget_mb`` wins its OWN axis; any axis left unset
        resolves, on accelerator backends, from each local device's
        reported ``bytes_limit`` minus the ``headroom`` fraction (XLA
        keeps workspace the registry can't see): that figure is the
        PER-DEVICE budget and the process budget is it times the
        LOCAL device count — a mesh process honestly owns its own
        chips' HBM, not the pod's. Setting TPU_HBM_BUDGET_MB alone
        therefore still arms per-device arbitration. The CPU backend
        leaves unset axes off — there is no meaningful device limit
        to enforce, and every existing test would suddenly arbitrate
        against host RAM. Returns the active budget."""
        if device_budget_mb:
            self.set_device_budget(int(device_budget_mb) << 20)
        if budget_mb:
            self.set_budget(int(budget_mb) << 20)
        if budget_mb and device_budget_mb:
            return self._budget
        try:
            import jax

            # LOCAL devices: under the distributed runtime
            # jax.devices() is the global pod list, but this process
            # only owns (and only accounts) its local chips' HBM — a
            # pod-wide budget would never bind.
            devices = jax.local_devices()
            dev = devices[0]
            if dev.platform != "cpu":
                stats = dev.memory_stats() or {}
                limit = stats.get("bytes_limit")
                if limit:
                    frac = min(max(float(headroom), 0.0), 0.9)
                    per_dev = int(limit * (1.0 - frac))
                    # an explicit knob wins its own axis, but never
                    # disables the OTHER one: TPU_HBM_BUDGET_MB alone
                    # still resolves the per-device bound (and vice
                    # versa) — per-device arbitration must not turn
                    # off because the global knob predates it
                    if not device_budget_mb:
                        self.set_device_budget(per_dev)
                    if not budget_mb:
                        self.set_budget(per_dev * len(devices))
        except Exception:
            pass  # no backend yet / stats unsupported: budget stays off
        return self._budget

    def _in_use_locked(self) -> int:
        return sum(self._entries.values())

    def tenant_lease(self, subsystem: str, nbytes: int, *, tenant: str,
                     owner: Any = None, priority: int = PRI_SCRATCH,
                     reclaim: Callable[[int], int] | None = None,
                     device: str = "") -> int:
        """A per-tenant cache-quota lease: :func:`lease` with the tag
        fixed to ``tenant:{id}`` so the tenant's footprint is visible
        in ``snapshot()``/``check()`` under its own key. Registered at
        PRI_SCRATCH (most-reclaimable) with a reclaim callback that
        evicts THAT tenant's cache blocks — under memory pressure the
        arbiter asks the over-budget tenant to give back its own rows
        BEFORE the PRI_CACHE pool shrink flushes everyone's. Usually
        zero-byte: the pool's own lease already accounts the bytes;
        this one exists for its reclaim ordering (the same convention
        as the paged pool's zero-byte reclaim hooks)."""
        return self.lease(subsystem, int(nbytes), owner=owner,
                          tag=f"tenant:{tenant}", priority=priority,
                          reclaim=reclaim, device=device)

    def lease(self, subsystem: str, nbytes: int, *, owner: Any = None,
              tag: str = "", priority: int = PRI_CACHE,
              reclaim: Callable[[int], int] | None = None,
              device: str = "", _seam: bool = True) -> int:
        """Reserve ``nbytes`` against the budget BEFORE allocating.
        Fires the seeded ``HBM_ALLOC`` chaos seam (an injected
        ResourceExhausted sheds deterministically), runs reclaim when
        the budget can't cover the request, and raises
        :class:`HBMExhausted` on a surviving deficit. On success the
        reservation is recorded under ``(subsystem, owner, tag,
        device)`` — the later :func:`account` of the real tree
        replaces the figure (SET semantics over the lease group),
        while the priority class and ``reclaim`` callback stay
        attached to the lease. ``device`` is the per-shard axis: a
        device-keyed lease is additionally checked against the
        per-device budget, and ITS deficit reclaims only that
        device's leases. Returns ``nbytes``."""
        if _seam:  # _alloc_impl fires once for its whole share split
            self._fire_seam(subsystem, int(nbytes))
        need = int(nbytes)
        dev = str(device or "")
        key = (subsystem, id(owner) if owner is not None else 0, tag, dev)
        wrapped = self._wrap_reclaim(reclaim)

        def shortfalls() -> "tuple[int, int]":
            # (global deficit, this device's deficit), net of any bytes
            # the key itself already holds (SET semantics)
            with self._mu:
                held = self._entries.get(key, 0)
                g = 0
                if self._budget:
                    g = self._in_use_locked() - held + need - self._budget
                d = 0
                if self._dev_budget:
                    # "" is a real group: a single-device process's
                    # whole footprint sits on its default chip, so the
                    # per-device bound applies to it exactly as to a
                    # shard — without this a multi-chip host's auto
                    # budget (per_dev * n_local) would never bind a
                    # non-mesh engine
                    d = self._device_in_use_locked(dev) - held + need \
                        - self._dev_budget
                return g, d

        def try_reserve() -> bool:
            # budget check and reservation insert under ONE lock hold:
            # two concurrent leases (e.g. two engines constructing in
            # one process) must not both pass a check neither has
            # reserved against yet — that would jointly over-commit
            # the budget with no reclaim and no shed
            with self._mu:
                held = self._entries.get(key, 0)
                b = self._budget
                if b and self._in_use_locked() - held + need > b:
                    return False
                db = self._dev_budget
                if db and \
                        self._device_in_use_locked(dev) - held + need > db:
                    return False
                self._entries[key] = need
                self._meta[key] = (int(priority), wrapped)
                return True

        if not try_reserve():
            g, d = shortfalls()
            if g > 0:
                self._reclaim(g, requester=subsystem)
                # the global pass may have spilled bytes on this very
                # device (a pool shrink touches every shard) — recompute
                # so the per-device pass doesn't over-reclaim a deficit
                # that is already covered
                g, d = shortfalls()
            if d > 0:
                # the hot shard's deficit: ask only ITS device's leases
                # to spill — one overcommitted device must not flush
                # every shard's caches across the mesh
                self._reclaim(d, requester=subsystem, device=dev)
            if not try_reserve():
                g, d = shortfalls()
                self.note_shed(subsystem)
                if d > 0 and g <= 0:
                    # only the per-device bound failed: attribute the
                    # shed to THAT device with ITS figures (check()'s
                    # "sub@devN" convention) — the global budget may
                    # be unset or healthy, and a 429 naming it would
                    # hide which shard overflowed
                    with self._mu:
                        dev_use = self._device_in_use_locked(dev) \
                            - self._entries.get(key, 0)
                    raise HBMExhausted(
                        f"{subsystem}@dev{dev}" if dev else subsystem,
                        need, budget=self._dev_budget, in_use=dev_use)
                with self._mu:
                    in_use = self._in_use_locked() \
                        - self._entries.get(key, 0)
                raise HBMExhausted(subsystem, need, budget=self._budget,
                                   in_use=in_use)
        if owner is not None:
            try:
                weakref.finalize(owner, self._release_owner_id, id(owner))
            except TypeError:
                pass
        self._push(subsystem)
        return need

    def alloc(self, subsystem: str, fn: Callable[[], Any], *,
              owner: Any = None, tag: str = "",
              priority: int = PRI_CACHE,
              reclaim: Callable[[int], int] | None = None) -> Any:
        """Reclaim-then-retry allocation: the one call serving code
        wraps its persist-point allocations in (gofrlint GL202 accepts
        it as the accounting API). Leases the thunk's ``eval_shape``
        size when a budget is set, runs the thunk, and on a REAL
        device OOM (:func:`is_oom_error`) runs demand-driven reclaim
        and retries ONCE; a second failure raises
        :class:`HBMExhausted` (ruling the 429/RESOURCE_EXHAUSTED shed
        path) instead of letting the raw runtime error escape. The
        result is accounted under ``(subsystem, owner, tag)``. A
        failed allocation rolls the reservation back to the lease
        group's pre-lease state — no phantom bytes stay registered
        eating headroom for a buffer that never materialized."""
        return self._alloc_impl(subsystem, fn, owner=owner, tag=tag,
                                priority=priority, reclaim=reclaim,
                                devices=None)

    def alloc_sharded(self, subsystem: str, fn: Callable[[], Any], *,
                      owner: Any = None, tag: str = "",
                      priority: int = PRI_CACHE,
                      reclaim: Callable[[int], int] | None = None,
                      devices=()) -> Any:
        """:func:`alloc` for SHARDED persist points (gofrlint GL202
        blesses this form too): ``fn`` returns a tree placed across
        ``devices`` (mesh device ids), the pre-allocation lease splits
        an even share per device — each checked against the per-device
        budget, each reclaiming per-device on a deficit — and the
        account records the REAL per-shard figures (replacing the even
        estimate; SET semantics over the lease group). The one-call
        form a mesh engine's cache/pool/scratch persist points use."""
        labels = [str(getattr(d, "id", d)) for d in devices]
        return self._alloc_impl(subsystem, fn, owner=owner, tag=tag,
                                priority=priority, reclaim=reclaim,
                                devices=labels or None)

    def _alloc_impl(self, subsystem: str, fn: Callable[[], Any], *,
                    owner: Any, tag: str, priority: int,
                    reclaim: Callable[[int], int] | None,
                    devices: "list[str] | None") -> Any:
        base = (subsystem, id(owner) if owner is not None else 0, tag)
        with self._mu:
            prior = {k: self._entries[k] for k in self._entries
                     if k[:3] == base}
            prior_meta = {k: self._meta[k] for k in self._meta
                          if k[:3] == base}

        def rollback() -> None:
            with self._mu:
                for k in [k for k in self._entries if k[:3] == base]:
                    self._entries.pop(k)
                for k in [k for k in self._meta if k[:3] == base]:
                    self._meta.pop(k)
                self._entries.update(prior)
                self._meta.update(prior_meta)
            self._push(subsystem)

        # device-less allocs are bounded too (the "" group vs the
        # per-device budget), so the estimate must be real whenever
        # EITHER budget is armed — not only for sharded thunks
        gated = bool(self._budget or self._dev_budget)
        need = _estimate_nbytes(fn) if gated else 0
        # ONE chaos-seam firing per allocation, however many per-device
        # shares the lease splits into — schedules stay comparable
        # between single-device and mesh engines
        self._fire_seam(subsystem, need)
        try:
            if devices:
                share = -(-need // len(devices))
                for d in devices:
                    self.lease(subsystem, share, owner=owner, tag=tag,
                               priority=priority, reclaim=reclaim,
                               device=d, _seam=False)
            else:
                self.lease(subsystem, need, owner=owner, tag=tag,
                           priority=priority, reclaim=reclaim,
                           _seam=False)
        except BaseException:
            rollback()
            raise
        try:
            tree = fn()
        except BaseException as e:
            if not is_oom_error(e):
                rollback()
                raise
            with self._mu:
                self._oom_retries[subsystem] = \
                    self._oom_retries.get(subsystem, 0) + 1
            # with no budget configured the lease skipped the size
            # estimate — compute it NOW so the reclaim pass frees the
            # allocation's worth, not one token byte (a real OOM is
            # exactly the no-budget regime's enforcement path). NB
            # eval_shape traces the thunk abstractly: pure allocation
            # thunks (the contract) are side-effect free under it
            self._reclaim(max(need or _estimate_nbytes(fn), 1),
                          requester=subsystem)
            try:
                tree = fn()
            except BaseException as e2:
                rollback()
                if is_oom_error(e2):
                    self.note_shed(subsystem)
                    raise HBMExhausted(subsystem, need,
                                       budget=self._budget) from e2
                raise
        return self.account(subsystem, tree, owner=owner, tag=tag)

    def check(self, subsystem: str) -> None:
        """Zero-byte lease for request-path admission points (the
        generation loop calls it once per admission): fires the
        ``HBM_ALLOC`` seam and, when the process sits OVER its budget
        (budget lowered at runtime, or actuals outgrew estimates),
        runs reclaim and raises :class:`HBMExhausted` if the overshoot
        survives — the caller sheds THAT request and keeps serving.
        With a per-device budget set, each overcommitted device runs
        its OWN reclaim pass (one hot shard spills without flushing
        the mesh) and a surviving per-device overshoot sheds too."""
        self._fire_seam(subsystem, 0)
        b = self._budget
        if b:
            with self._mu:
                in_use = self._in_use_locked()
            if in_use > b:
                self._reclaim(in_use - b, requester=subsystem)
                with self._mu:
                    in_use = self._in_use_locked()
                if in_use > b:
                    self.note_shed(subsystem)
                    raise HBMExhausted(subsystem, 0, budget=b,
                                       in_use=in_use)
        db = self._dev_budget
        if db:
            with self._mu:
                # "" included: device-less entries are one group too
                # (a single-device process's default chip)
                over = [d for d, n in
                        self._device_bytes_locked().items() if n > db]
            for d in over:
                # re-read THIS device's deficit: an earlier device's
                # pass may have spilled on EVERY shard (a sharded pool
                # shrink), already covering this one — reclaiming the
                # stale figure would cascade pool shrinks and flush
                # the mesh-wide T0 the per-device design protects
                with self._mu:
                    deficit = self._device_in_use_locked(d) - db
                if deficit <= 0:
                    continue
                self._reclaim(deficit, requester=subsystem, device=d)
                with self._mu:
                    in_use = self._device_in_use_locked(d)
                if in_use > db:
                    self.note_shed(subsystem)
                    raise HBMExhausted(
                        f"{subsystem}@dev{d}" if d else subsystem, 0,
                        budget=db, in_use=in_use)

    def _device_bytes_locked(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for (_, _, _, dev), n in self._entries.items():
            out[dev] = out.get(dev, 0) + n
        return out

    def _fire_seam(self, subsystem: str, nbytes: int) -> None:
        try:
            chaos.fire(chaos.HBM_ALLOC)
        except BaseException as e:
            if is_oom_error(e):
                # an injected allocation failure models one that
                # survived retry: exercise the reclaim machinery (the
                # recovery coverage the schedule is reproducing), then
                # shed deterministically
                self._reclaim(max(int(nbytes), 1), requester=subsystem)
                self.note_shed(subsystem)
                raise HBMExhausted(subsystem, nbytes,
                                   budget=self._budget) from e
            raise

    def reclaim(self, nbytes: int | None = None) -> int:
        """Manually run one demand-driven reclaim pass for ``nbytes``.
        ``None`` asks EVERY registered reclaimer (need = the sum of
        all reclaimable leases): the last-ditch form the batcher's OOM
        retry uses, where the transient deficit is unknowable and the
        alternative is shedding the whole batch. Returns bytes freed;
        lease/alloc/check run sized passes implicitly."""
        if nbytes is None:
            with self._mu:
                need = sum(self._entries.get(k, 0)
                           for k in self._meta
                           if self._meta[k][1] is not None) or 1
        else:
            need = max(int(nbytes), 1)
        return self._reclaim(need, requester="manual")

    def _wrap_reclaim(self, cb):
        if cb is None:
            return None
        try:
            return weakref.WeakMethod(cb)
        except TypeError:
            return cb  # plain function/lambda: held strongly

    def _deref_reclaim(self, wrapped):
        if isinstance(wrapped, weakref.WeakMethod):
            return wrapped()
        return wrapped

    def _reclaim(self, need: int, requester: str = "",
                 device: str | None = None) -> int:
        """Run registered reclaim callbacks, highest priority class
        first (PRI_SCRATCH before PRI_CACHE before PRI_SERVING), until
        ``need`` bytes are freed or the candidates run out.
        ``device``: a per-shard pass — only leases holding bytes ON
        that device are asked, and each callback's (global) freed
        figure counts toward the deficit scaled by the lease group's
        share on that device, so one overcommitted shard never flushes
        the whole mesh. Single-flight: a pass already in progress
        makes this a no-op returning 0 (the concurrent requester
        re-checks the budget as-is)."""
        if not self._reclaim_mu.acquire(blocking=False):
            return 0
        try:
            with self._mu:
                # one candidate per lease GROUP (a sharded lease holds
                # N device keys sharing one callback — calling it once
                # per shard would over-reclaim N-fold); per-device
                # passes keep only groups with bytes on that device
                groups: dict[tuple, dict] = {}
                for key, meta in self._meta.items():
                    if meta[1] is None:
                        continue
                    g = groups.setdefault(key[:3], {
                        "meta": meta, "bytes": 0, "dev_bytes": 0,
                        "keys": []})
                    n = self._entries.get(key, 0)
                    g["bytes"] += n
                    g["keys"].append(key)
                    if device is not None and key[3] == device:
                        g["dev_bytes"] += n
                candidates = sorted(
                    (g for g in groups.values()
                     if device is None or g["dev_bytes"] > 0),
                    key=lambda g: (-g["meta"][0],
                                   -(g["dev_bytes"] if device is not None
                                     else g["bytes"])))
            freed = 0
            for g in candidates:
                if freed >= need:
                    break
                cb = self._deref_reclaim(g["meta"][1])
                if cb is None:
                    with self._mu:  # owner died: drop the dead callback
                        for key in g["keys"]:
                            self._meta.pop(key, None)
                    continue
                # ask for the GLOBAL equivalent of the remaining
                # per-device deficit: a lease whose bytes spread over
                # nd devices frees ~1/nd of each reclaimed row here
                frac = (g["dev_bytes"] / g["bytes"]
                        if device is not None and g["bytes"] else 1.0)
                ask = need - freed
                if device is not None and frac > 0:
                    ask = int(ask / frac) + 1
                try:
                    got = int(cb(ask) or 0)
                except Exception:
                    got = 0  # a failing reclaimer must never take the
                    # requesting allocation down with it
                if got > 0:
                    freed += max(int(got * frac), 1) \
                        if device is not None else got
                    sub = g["keys"][0][0]
                    with self._mu:
                        self._reclaims[sub] = self._reclaims.get(sub, 0) + 1
                        self._reclaimed_bytes += got
                    self._count_metric(RECLAIMS_COUNTER, subsystem=sub)
                    self._event_timeline(sub, "reclaim", got)
            return freed
        finally:
            self._reclaim_mu.release()

    def note_shed(self, subsystem: str) -> None:
        """Record one request degraded (429/RESOURCE_EXHAUSTED) because
        the arbiter could not cover an allocation:
        ``app_tpu_hbm_shed_total{subsystem=}`` plus a timeline instant
        on the subsystem's ``hbm:*`` track. Every :class:`HBMExhausted`
        raise site in this module counts itself — a caller that merely
        catches and re-routes one must NOT count it again (the
        batcher's own persistent-OOM shed, which raises a plain
        TooManyRequests, is the one external caller)."""
        with self._mu:
            self._sheds[subsystem] = self._sheds.get(subsystem, 0) + 1
        self._count_metric(SHED_COUNTER, subsystem=subsystem)
        self._event_timeline(subsystem, "shed", 0)

    def arbiter_stats(self) -> dict:
        """The lease/reclaim table: budget, in-use, per-lease rows
        (subsystem/tag/bytes/priority/reclaimable), and the reclaim/
        shed/retry counters — what /debug/vars, health_check and
        tools/hbm_report.py render."""
        with self._mu:
            entries = dict(self._entries)
            meta = dict(self._meta)
            reclaims = dict(self._reclaims)
            sheds = dict(self._sheds)
            retries = dict(self._oom_retries)
            reclaimed = self._reclaimed_bytes
        in_use = sum(entries.values())
        pri_names = {PRI_SERVING: "serving", PRI_CACHE: "cache",
                     PRI_SCRATCH: "scratch"}
        leases = []
        per_dev: dict[str, int] = {}
        for (sub, oid, tag, dev), n in sorted(entries.items()):
            pri, cb = meta.get((sub, oid, tag, dev), (PRI_CACHE, None))
            row = {
                "subsystem": sub, "owner": oid, "tag": tag, "bytes": n,
                "priority": pri_names.get(pri, str(pri)),
                "reclaimable": self._deref_reclaim(cb) is not None,
            }
            if dev:
                row["device"] = dev
                per_dev[dev] = per_dev.get(dev, 0) + n
            leases.append(row)
        out = {
            "budget_bytes": self._budget,
            "in_use_bytes": in_use,
            "headroom_bytes": (self._budget - in_use
                               if self._budget else None),
            "leases": leases,
            "reclaims": reclaims,
            "reclaimed_bytes": reclaimed,
            "sheds": sheds,
            "oom_retries": retries,
        }
        if per_dev or self._dev_budget:
            db = self._dev_budget
            out["device_budget_bytes"] = db
            out["devices"] = {
                d: {"in_use_bytes": n,
                    "headroom_bytes": (db - n) if db else None}
                for d, n in sorted(per_dev.items())}
        return out

    # -- fan-out sinks -------------------------------------------------------
    def set_metrics(self, metrics: Any) -> None:
        """Attach a metrics Manager (weakly held; every attached
        Manager receives every later change as
        ``app_tpu_device_bytes{subsystem=...}``). ``None`` detaches
        all sinks."""
        if metrics is None:
            self._sinks.clear()
            return
        self._sinks.add(metrics)
        for sub in self.live_bytes():
            self._push(sub)
        try:
            metrics.set_gauge(BUDGET_GAUGE, float(self._budget or 0))
            metrics.set_gauge(DEVICE_BUDGET_GAUGE,
                              float(self._dev_budget or 0))
        except Exception:
            pass

    def reset(self) -> None:
        """Test hook: forget everything — entries, leases, budget,
        counters (and zero pushed gauges)."""
        with self._mu:
            subs = {sub for (sub, _, _, _) in self._entries}
            self._entries.clear()
            self._meta.clear()
            self._reclaims.clear()
            self._sheds.clear()
            self._oom_retries.clear()
            self._reclaimed_bytes = 0
        self.set_budget(None)
        self.set_device_budget(None)
        for sub in subs:
            self._push(sub)

    def set_timeline(self, timeline: Any) -> None:
        """Attach a serving timeline (weakly held) that receives an
        ``hbm`` counter sample on every accounting change plus
        reclaim/shed instants. ``None`` detaches all timelines."""
        if timeline is None:
            self._timelines.clear()
            return
        self._timelines.add(timeline)
        for sub, n in self.live_bytes().items():
            try:
                timeline.hbm(sub, float(n))
            except Exception:
                pass

    def _count_metric(self, name: str, **labels) -> None:
        for m in list(self._sinks):
            try:
                m.increment_counter(name, **labels)
            except Exception:
                pass  # accounting must never take the serving path down

    def _event_timeline(self, subsystem: str, what: str,
                        nbytes: int) -> None:
        for tl in list(self._timelines):
            try:
                fn = getattr(tl, "hbm_event", None)
                if fn is not None:
                    fn(subsystem, what, float(nbytes))
            except Exception:
                pass

    def _push(self, subsystem: str) -> None:
        sinks = list(self._sinks)
        timelines = list(self._timelines)
        if not sinks and not timelines:
            return
        # _push_mu serializes whole pushes: without it, a thread
        # holding a pre-release snapshot could write its stale nonzero
        # per-device values AFTER another thread's explicit zeros —
        # re-creating exactly the phantom-in-use the zeros prevent
        with self._push_mu:
            value = float(self.live_bytes().get(subsystem, 0))
            # devices whose entries vanished (engine closed, mesh
            # shrank) must push an explicit 0 — a gauge series that
            # just stops updating reads as phantom in-use forever (the
            # subsystem gauge's zero-on-release contract, per device)
            with self._mu:
                per_dev = {d: n for d, n in
                           self._device_bytes_locked().items() if d}
                gone = self._pushed_devs - set(per_dev)
                self._pushed_devs = set(per_dev)
            for m in sinks:
                try:
                    m.set_gauge(GAUGE, value, subsystem=subsystem)
                    # per-shard in-use (only when entries carry a
                    # device axis — single-device processes export no
                    # series)
                    for d, n in per_dev.items():
                        m.set_gauge(DEVICE_GAUGE, float(n), device=d)
                    for d in gone:
                        m.set_gauge(DEVICE_GAUGE, 0.0, device=d)
                except Exception:
                    pass  # accounting must never take the serving
                    # path down
        for tl in timelines:
            try:
                tl.hbm(subsystem, value)
            except Exception:
                pass


_registry = _Registry()

account = _registry.account
alloc = _registry.alloc
alloc_sharded = _registry.alloc_sharded
arbiter_stats = _registry.arbiter_stats
budget = _registry.budget
check = _registry.check
configure = _registry.configure
device_budget = _registry.device_budget
device_bytes = _registry.device_bytes
lease = _registry.lease
live_bytes = _registry.live_bytes
note_shed = _registry.note_shed
reclaim = _registry.reclaim
release = _registry.release
reset = _registry.reset
set_budget = _registry.set_budget
set_device_budget = _registry.set_device_budget
set_metrics = _registry.set_metrics
set_timeline = _registry.set_timeline
snapshot = _registry.snapshot
tenant_lease = _registry.tenant_lease
