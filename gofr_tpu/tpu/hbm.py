"""Device-memory arbiter: one HBM budget every subsystem leases from.

``BENCH_CANDIDATE.json`` showed why this exists: the flat decode path
is healthy at 2709 tok/s/chip while the prefix-cache, engine and
speculative arms die with RESOURCE_EXHAUSTED and the paged engine OOMs
at every batch size — each subsystem allocated HBM assuming it owned
the whole device, and the first one to be wrong killed the process.
This module is the arbitration point that makes the subsystems
coexist: ONE budget, leased out per subsystem, with demand-driven
reclaim and a shed path so an allocation failure degrades the
*request* instead of killing the *process*.

Three layers, lowest first:

  **Accounting** (the PR-6 substrate, unchanged contract): every
  persistent device buffer is declared via :func:`account`, keyed
  ``(subsystem, owner, tag)`` with SET semantics (recovery realloc /
  mesh re-placement replace instead of double-count); gofrlint GL202
  enforces the discipline statically and ``pytest --hbmwatch``
  reconciles declared bytes against ``jax.live_arrays()`` ground
  truth. ``release(owner=self)`` in ``close()``; a weakref finalizer
  backstops owners that die without it.

  **Leases** (the arbiter): :func:`lease` reserves bytes against the
  budget BEFORE an allocation, tagged with a priority class —
  ``PRI_SERVING`` (live serving state, never auto-reclaimed),
  ``PRI_CACHE`` (performance caches that shrink toward lower tiers),
  ``PRI_SCRATCH`` (workspace, dropped first) — and an optional
  **reclaim callback** ``(need_bytes) -> freed_bytes``. When a lease
  would exceed the budget the arbiter runs reclaim over the registered
  callbacks (highest priority class first: scratch, then caches), then
  re-checks; if the deficit survives it raises :class:`HBMExhausted`.
  :func:`alloc` is the one-call form serving code uses: lease (sized
  by ``jax.eval_shape`` when a budget is set), run the allocation
  thunk, catch a REAL device OOM (``XlaRuntimeError`` /
  RESOURCE_EXHAUSTED — :func:`is_oom_error`), reclaim, retry ONCE,
  and account the result. :func:`check` is the zero-byte request-path
  checkpoint the generation admission loop calls per admission.

  **Shed** (the degradation contract): :class:`HBMExhausted` IS a
  ``TooManyRequests`` — it carries ``Retry-After`` and maps to
  429/RESOURCE_EXHAUSTED at both transports, so an uncoverable lease
  routes through the existing AdmissionGate shed surface
  (``AdmissionGate.shed_memory``, ``tpu.shed`` span,
  ``app_tpu_hbm_shed_total``) and the process keeps serving. The
  seeded chaos seam ``HBM_ALLOC`` fires at every lease point, so
  fault schedules can kill allocation N deterministically
  (tests/test_hbm_arbiter.py, tools/hbm_report.py --pressure).

The budget: ``TPU_HBM_BUDGET_MB`` (config; 0/unset = resolve from the
device) minus nothing, else on accelerator backends the device's
reported ``bytes_limit`` minus the ``TPU_HBM_HEADROOM`` fraction
(default 0.1 — XLA needs workspace the registry can't see). On the
CPU backend the budget stays OFF unless set explicitly
(``set_budget``) — tests opt in with a tiny synthetic budget.

Observability: ``app_tpu_device_bytes{subsystem=}`` gauges on every
accounting change, ``app_tpu_hbm_budget_bytes``,
``app_tpu_hbm_reclaims_total{subsystem=}`` /
``app_tpu_hbm_shed_total{subsystem=}`` counters, ``hbm:*`` counter
tracks plus reclaim/shed instants on the serving timeline, the
``hbm_arbiter`` section of ``/debug/vars`` and
``TPUEngine.health_check``, and ``tools/hbm_report.py``'s lease
table. Subsystem vocabulary: ``engine`` (serving KV cache + chunk
scratch), ``kvcache-t0`` (prefix-pool rows), ``lora`` (adapter
stacks), ``spec-decode``/``batcher`` (when they grow device state).
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Callable

from .. import chaos
from ..errors import TooManyRequests

__all__ = ["HBMExhausted", "PRI_CACHE", "PRI_SCRATCH", "PRI_SERVING",
           "account", "alloc", "arbiter_stats", "budget", "check",
           "configure", "is_oom_error", "lease", "live_bytes",
           "note_shed", "reclaim", "release", "reset", "set_budget",
           "set_metrics", "set_timeline", "snapshot", "tree_nbytes"]

GAUGE = "app_tpu_device_bytes"
BUDGET_GAUGE = "app_tpu_hbm_budget_bytes"
RECLAIMS_COUNTER = "app_tpu_hbm_reclaims_total"
SHED_COUNTER = "app_tpu_hbm_shed_total"

# Lease priority classes — the RECLAIM order, highest value first:
# scratch/workspace is dropped before caches shrink, and live serving
# state is never auto-reclaimed (a lease may still attach a callback
# at PRI_SERVING — e.g. the paged engine's cold-block release — but it
# runs last).
PRI_SERVING = 0
PRI_CACHE = 1
PRI_SCRATCH = 2


class HBMExhausted(TooManyRequests):
    """A lease the budget cannot cover even after reclaim (or a real
    device OOM that survived the reclaim-then-retry pass). A
    ``TooManyRequests`` subclass on purpose: the failure is SERVED —
    429 with ``Retry-After`` on HTTP, RESOURCE_EXHAUSTED with the
    retry trailer on gRPC — through the same shed surface queue
    overload uses (resilience.AdmissionGate), instead of killing the
    process the way an unhandled allocation failure did in
    BENCH_CANDIDATE.json."""

    def __init__(self, subsystem: str, nbytes: int, *,
                 budget: int | None = None, in_use: int | None = None,
                 retry_after: float = 1.0):
        detail = ""
        if budget is not None:
            detail = (f" (budget {budget >> 20} MiB, "
                      f"in use {(in_use or 0) >> 20} MiB)")
        super().__init__(
            f"hbm arbiter: cannot cover {subsystem!r} lease of "
            f"{int(nbytes)} bytes after reclaim{detail}",
            retry_after=retry_after)
        self.subsystem = subsystem
        self.nbytes = int(nbytes)


_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OutOfMemory")


def is_oom_error(e: BaseException) -> bool:
    """Is this the allocation-failure class the arbiter owns? Covers
    our own :class:`HBMExhausted`, the chaos harness's injected
    ``ResourceExhausted``, and the runtime's ``XlaRuntimeError`` (or
    any RuntimeError) whose message carries the RESOURCE_EXHAUSTED /
    out-of-memory markers — jaxlib raises different concrete types
    across versions, so the classifier is name+message based rather
    than an isinstance check against a moving target."""
    if isinstance(e, HBMExhausted):
        return True
    name = type(e).__name__
    if "ResourceExhausted" in name or "OutOfMemory" in name:
        return True
    if "XlaRuntimeError" in name or isinstance(e, (RuntimeError,
                                                   MemoryError)):
        msg = str(e)
        return any(m in msg for m in _OOM_MARKERS)
    return False


def tree_nbytes(tree: Any) -> int:
    """Total bytes of the array leaves of ``tree`` (jax or numpy —
    anything with ``nbytes``). None leaves (e.g. absent scale planes)
    contribute nothing."""
    import jax

    return sum(int(getattr(leaf, "nbytes", 0) or 0)
               for leaf in jax.tree.leaves(tree))


def _estimate_nbytes(fn: Callable[[], Any]) -> int:
    """Size an allocation thunk WITHOUT allocating: ``jax.eval_shape``
    traces it abstractly and the ShapeDtypeStruct leaves give exact
    byte figures. 0 when the thunk resists tracing (device_put of
    host data, side effects) — the lease then reserves nothing and
    enforcement falls to the post-hoc OOM retry."""
    try:
        import jax
        import numpy as np

        spec = jax.eval_shape(fn)
        total = 0
        for leaf in jax.tree.leaves(spec):
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is None or dtype is None:
                continue
            total += int(np.prod(shape, dtype=np.int64)) \
                * np.dtype(dtype).itemsize
        return total
    except Exception:
        return 0


class _Registry:
    def __init__(self) -> None:
        self._mu = threading.Lock()
        # (subsystem, owner_id, tag) -> bytes
        self._entries: dict[tuple[str, int, str], int] = {}
        # lease metadata per key: (priority, reclaim-callable-or-ref).
        # Bound-method callbacks are held via weakref.WeakMethod so a
        # registered reclaimer never pins its engine alive; account()
        # never touches this table, so a recovery re-account keeps the
        # lease's class and callback.
        self._meta: dict[tuple[str, int, str], tuple[int, Any]] = {}
        self._budget: int | None = None
        # single-flight reclaim: one pass at a time process-wide.
        # Concurrent requesters return 0 and judge the budget as-is —
        # which also breaks any cross-engine lock cycle a nested
        # reclaim chain could otherwise build (engine A's callback
        # holds A's device lock while B's callback wants B's).
        self._reclaim_mu = threading.Lock()
        self._reclaims: dict[str, int] = {}
        self._reclaimed_bytes = 0
        self._sheds: dict[str, int] = {}
        self._oom_retries: dict[str, int] = {}
        # gauge sinks, weakly held: the registry outlives any Manager
        # and must neither pin one alive nor stop pushing to A because
        # B registered later (two engines, two Managers — both see the
        # same process-truth figures)
        self._sinks: "weakref.WeakSet[Any]" = weakref.WeakSet()
        # serving timelines (observe/timeline.py), same weak fan-out:
        # every accounting change lands a counter sample so the
        # exported Perfetto trace carries an HBM track per subsystem
        self._timelines: "weakref.WeakSet[Any]" = weakref.WeakSet()

    # -- accounting (PR-6 contract, unchanged) -------------------------------
    def account(self, subsystem: str, tree: Any, *, owner: Any = None,
                tag: str = "") -> Any:
        key = (subsystem, id(owner) if owner is not None else 0, tag)
        n = tree_nbytes(tree)
        with self._mu:
            self._entries[key] = n
        if owner is not None:
            # safety net for owners that die WITHOUT close() — an
            # __init__ that OOMs after its first account() (exactly
            # the regime this registry exists for) must not leave
            # phantom bytes behind, and a reused id() must not alias a
            # dead owner's entries. Idempotent with close()'s explicit
            # release; runs at the owner's collection.
            try:
                weakref.finalize(owner, self._release_owner_id,
                                 id(owner))
            except TypeError:
                pass  # non-weakrefable owner: explicit release only
        self._push(subsystem)
        return tree

    def _release_owner_id(self, oid: int) -> None:
        touched: set[str] = set()
        with self._mu:
            for key in list(self._entries):
                if key[1] == oid:
                    self._entries.pop(key)
                    self._meta.pop(key, None)
                    touched.add(key[0])
            for key in list(self._meta):
                if key[1] == oid:
                    self._meta.pop(key)
        for sub in touched:
            self._push(sub)

    def release(self, subsystem: str | None = None, *,
                owner: Any = None, tag: str | None = None) -> int:
        """Drop entries by subsystem and/or owner (and optionally an
        exact tag); returns the bytes released. ``release(owner=self)``
        in ``close()`` drops every subsystem the instance accounted —
        leases and their reclaim callbacks die with the entries."""
        oid = None if owner is None else id(owner)
        dropped = 0
        touched: set[str] = set()
        with self._mu:
            for key in list(self._entries):
                sub, key_oid, key_tag = key
                if subsystem is not None and sub != subsystem:
                    continue
                if oid is not None and key_oid != oid:
                    continue
                if tag is not None and key_tag != tag:
                    continue
                dropped += self._entries.pop(key)
                self._meta.pop(key, None)
                touched.add(sub)
        for sub in touched:
            self._push(sub)
        return dropped

    def live_bytes(self) -> dict[str, int]:
        """Accounted bytes aggregated by subsystem (zero-byte
        subsystems with live keys included — a released-to-zero
        subsystem disappears)."""
        out: dict[str, int] = {}
        with self._mu:
            for (sub, _, _), n in self._entries.items():
                out[sub] = out.get(sub, 0) + n
        return dict(sorted(out.items()))

    def snapshot(self) -> dict[tuple[str, int, str], int]:
        with self._mu:
            return dict(self._entries)

    # -- the arbiter ---------------------------------------------------------
    def set_budget(self, nbytes: int | None) -> None:
        """Install (or clear, with None/0) the process HBM budget in
        bytes. Tests use this directly with tiny synthetic budgets;
        production resolves it via :func:`configure`."""
        self._budget = int(nbytes) if nbytes else None
        for m in list(self._sinks):
            try:
                m.set_gauge(BUDGET_GAUGE, float(self._budget or 0))
            except Exception:
                pass

    def budget(self) -> int | None:
        return self._budget

    def configure(self, budget_mb: int | None = None,
                  headroom: float = 0.1) -> int | None:
        """Resolve and install the budget: an explicit ``budget_mb``
        wins; otherwise, on accelerator backends, the device's
        reported ``bytes_limit`` minus the ``headroom`` fraction (XLA
        keeps workspace the registry can't see). The CPU backend
        leaves the budget as-is — there is no meaningful device limit
        to enforce, and every existing test would suddenly arbitrate
        against host RAM. Returns the active budget."""
        if budget_mb:
            self.set_budget(int(budget_mb) << 20)
            return self._budget
        try:
            import jax

            dev = jax.devices()[0]
            if dev.platform != "cpu":
                stats = dev.memory_stats() or {}
                limit = stats.get("bytes_limit")
                if limit:
                    frac = min(max(float(headroom), 0.0), 0.9)
                    self.set_budget(int(limit * (1.0 - frac)))
        except Exception:
            pass  # no backend yet / stats unsupported: budget stays off
        return self._budget

    def _in_use_locked(self) -> int:
        return sum(self._entries.values())

    def lease(self, subsystem: str, nbytes: int, *, owner: Any = None,
              tag: str = "", priority: int = PRI_CACHE,
              reclaim: Callable[[int], int] | None = None) -> int:
        """Reserve ``nbytes`` against the budget BEFORE allocating.
        Fires the seeded ``HBM_ALLOC`` chaos seam (an injected
        ResourceExhausted sheds deterministically), runs reclaim when
        the budget can't cover the request, and raises
        :class:`HBMExhausted` on a surviving deficit. On success the
        reservation is recorded under ``(subsystem, owner, tag)`` —
        the later :func:`account` of the real tree replaces the figure
        (SET semantics), while the priority class and ``reclaim``
        callback stay attached to the lease. Returns ``nbytes``."""
        self._fire_seam(subsystem, int(nbytes))
        need = int(nbytes)
        key = (subsystem, id(owner) if owner is not None else 0, tag)
        wrapped = self._wrap_reclaim(reclaim)

        def try_reserve() -> bool:
            # budget check and reservation insert under ONE lock hold:
            # two concurrent leases (e.g. two engines constructing in
            # one process) must not both pass a check neither has
            # reserved against yet — that would jointly over-commit
            # the budget with no reclaim and no shed
            with self._mu:
                b = self._budget
                if b:
                    effective = self._in_use_locked() \
                        - self._entries.get(key, 0) + need
                    if effective > b:
                        return False
                self._entries[key] = need
                self._meta[key] = (int(priority), wrapped)
                return True

        if not try_reserve():
            with self._mu:
                deficit = self._in_use_locked() \
                    - self._entries.get(key, 0) + need \
                    - (self._budget or 0)
            self._reclaim(max(deficit, 1), requester=subsystem)
            if not try_reserve():
                with self._mu:
                    in_use = self._in_use_locked() \
                        - self._entries.get(key, 0)
                self.note_shed(subsystem)
                raise HBMExhausted(subsystem, need, budget=self._budget,
                                   in_use=in_use)
        if owner is not None:
            try:
                weakref.finalize(owner, self._release_owner_id, id(owner))
            except TypeError:
                pass
        self._push(subsystem)
        return need

    def alloc(self, subsystem: str, fn: Callable[[], Any], *,
              owner: Any = None, tag: str = "",
              priority: int = PRI_CACHE,
              reclaim: Callable[[int], int] | None = None) -> Any:
        """Reclaim-then-retry allocation: the one call serving code
        wraps its persist-point allocations in (gofrlint GL202 accepts
        it as the accounting API). Leases the thunk's ``eval_shape``
        size when a budget is set, runs the thunk, and on a REAL
        device OOM (:func:`is_oom_error`) runs demand-driven reclaim
        and retries ONCE; a second failure raises
        :class:`HBMExhausted` (ruling the 429/RESOURCE_EXHAUSTED shed
        path) instead of letting the raw runtime error escape. The
        result is accounted under ``(subsystem, owner, tag)``. A
        failed allocation rolls the reservation back to the key's
        pre-lease state — no phantom bytes stay registered eating
        headroom for a buffer that never materialized."""
        key = (subsystem, id(owner) if owner is not None else 0, tag)
        with self._mu:
            had = key in self._entries
            prior_bytes = self._entries.get(key)
            prior_meta = self._meta.get(key)

        def rollback() -> None:
            with self._mu:
                if had:
                    self._entries[key] = prior_bytes
                    if prior_meta is not None:
                        self._meta[key] = prior_meta
                    else:
                        self._meta.pop(key, None)
                else:
                    self._entries.pop(key, None)
                    self._meta.pop(key, None)
            self._push(subsystem)

        need = _estimate_nbytes(fn) if self._budget else 0
        self.lease(subsystem, need, owner=owner, tag=tag,
                   priority=priority, reclaim=reclaim)
        try:
            tree = fn()
        except BaseException as e:
            if not is_oom_error(e):
                rollback()
                raise
            with self._mu:
                self._oom_retries[subsystem] = \
                    self._oom_retries.get(subsystem, 0) + 1
            # with no budget configured the lease skipped the size
            # estimate — compute it NOW so the reclaim pass frees the
            # allocation's worth, not one token byte (a real OOM is
            # exactly the no-budget regime's enforcement path). NB
            # eval_shape traces the thunk abstractly: pure allocation
            # thunks (the contract) are side-effect free under it
            self._reclaim(max(need or _estimate_nbytes(fn), 1),
                          requester=subsystem)
            try:
                tree = fn()
            except BaseException as e2:
                rollback()
                if is_oom_error(e2):
                    self.note_shed(subsystem)
                    raise HBMExhausted(subsystem, need,
                                       budget=self._budget) from e2
                raise
        return self.account(subsystem, tree, owner=owner, tag=tag)

    def check(self, subsystem: str) -> None:
        """Zero-byte lease for request-path admission points (the
        generation loop calls it once per admission): fires the
        ``HBM_ALLOC`` seam and, when the process sits OVER its budget
        (budget lowered at runtime, or actuals outgrew estimates),
        runs reclaim and raises :class:`HBMExhausted` if the overshoot
        survives — the caller sheds THAT request and keeps serving."""
        self._fire_seam(subsystem, 0)
        b = self._budget
        if not b:
            return
        with self._mu:
            in_use = self._in_use_locked()
        if in_use > b:
            self._reclaim(in_use - b, requester=subsystem)
            with self._mu:
                in_use = self._in_use_locked()
            if in_use > b:
                self.note_shed(subsystem)
                raise HBMExhausted(subsystem, 0, budget=b, in_use=in_use)

    def _fire_seam(self, subsystem: str, nbytes: int) -> None:
        try:
            chaos.fire(chaos.HBM_ALLOC)
        except BaseException as e:
            if is_oom_error(e):
                # an injected allocation failure models one that
                # survived retry: exercise the reclaim machinery (the
                # recovery coverage the schedule is reproducing), then
                # shed deterministically
                self._reclaim(max(int(nbytes), 1), requester=subsystem)
                self.note_shed(subsystem)
                raise HBMExhausted(subsystem, nbytes,
                                   budget=self._budget) from e
            raise

    def reclaim(self, nbytes: int | None = None) -> int:
        """Manually run one demand-driven reclaim pass for ``nbytes``.
        ``None`` asks EVERY registered reclaimer (need = the sum of
        all reclaimable leases): the last-ditch form the batcher's OOM
        retry uses, where the transient deficit is unknowable and the
        alternative is shedding the whole batch. Returns bytes freed;
        lease/alloc/check run sized passes implicitly."""
        if nbytes is None:
            with self._mu:
                need = sum(self._entries.get(k, 0)
                           for k in self._meta
                           if self._meta[k][1] is not None) or 1
        else:
            need = max(int(nbytes), 1)
        return self._reclaim(need, requester="manual")

    def _wrap_reclaim(self, cb):
        if cb is None:
            return None
        try:
            return weakref.WeakMethod(cb)
        except TypeError:
            return cb  # plain function/lambda: held strongly

    def _deref_reclaim(self, wrapped):
        if isinstance(wrapped, weakref.WeakMethod):
            return wrapped()
        return wrapped

    def _reclaim(self, need: int, requester: str = "") -> int:
        """Run registered reclaim callbacks, highest priority class
        first (PRI_SCRATCH before PRI_CACHE before PRI_SERVING), until
        ``need`` bytes are freed or the candidates run out.
        Single-flight: a pass already in progress makes this a no-op
        returning 0 (the concurrent requester re-checks the budget
        as-is)."""
        if not self._reclaim_mu.acquire(blocking=False):
            return 0
        try:
            with self._mu:
                candidates = sorted(
                    ((key, meta) for key, meta in self._meta.items()
                     if meta[1] is not None),
                    key=lambda kv: (-kv[1][0],
                                    -self._entries.get(kv[0], 0)))
            freed = 0
            for key, (_, wrapped) in candidates:
                if freed >= need:
                    break
                cb = self._deref_reclaim(wrapped)
                if cb is None:
                    with self._mu:  # owner died: drop the dead callback
                        self._meta.pop(key, None)
                    continue
                try:
                    got = int(cb(need - freed) or 0)
                except Exception:
                    got = 0  # a failing reclaimer must never take the
                    # requesting allocation down with it
                if got > 0:
                    freed += got
                    sub = key[0]
                    with self._mu:
                        self._reclaims[sub] = self._reclaims.get(sub, 0) + 1
                        self._reclaimed_bytes += got
                    self._count_metric(RECLAIMS_COUNTER, subsystem=sub)
                    self._event_timeline(sub, "reclaim", got)
            return freed
        finally:
            self._reclaim_mu.release()

    def note_shed(self, subsystem: str) -> None:
        """Record one request degraded (429/RESOURCE_EXHAUSTED) because
        the arbiter could not cover an allocation:
        ``app_tpu_hbm_shed_total{subsystem=}`` plus a timeline instant
        on the subsystem's ``hbm:*`` track. Every :class:`HBMExhausted`
        raise site in this module counts itself — a caller that merely
        catches and re-routes one must NOT count it again (the
        batcher's own persistent-OOM shed, which raises a plain
        TooManyRequests, is the one external caller)."""
        with self._mu:
            self._sheds[subsystem] = self._sheds.get(subsystem, 0) + 1
        self._count_metric(SHED_COUNTER, subsystem=subsystem)
        self._event_timeline(subsystem, "shed", 0)

    def arbiter_stats(self) -> dict:
        """The lease/reclaim table: budget, in-use, per-lease rows
        (subsystem/tag/bytes/priority/reclaimable), and the reclaim/
        shed/retry counters — what /debug/vars, health_check and
        tools/hbm_report.py render."""
        with self._mu:
            entries = dict(self._entries)
            meta = dict(self._meta)
            reclaims = dict(self._reclaims)
            sheds = dict(self._sheds)
            retries = dict(self._oom_retries)
            reclaimed = self._reclaimed_bytes
        in_use = sum(entries.values())
        pri_names = {PRI_SERVING: "serving", PRI_CACHE: "cache",
                     PRI_SCRATCH: "scratch"}
        leases = []
        for (sub, oid, tag), n in sorted(entries.items()):
            pri, cb = meta.get((sub, oid, tag), (PRI_CACHE, None))
            leases.append({
                "subsystem": sub, "owner": oid, "tag": tag, "bytes": n,
                "priority": pri_names.get(pri, str(pri)),
                "reclaimable": self._deref_reclaim(cb) is not None,
            })
        return {
            "budget_bytes": self._budget,
            "in_use_bytes": in_use,
            "headroom_bytes": (self._budget - in_use
                               if self._budget else None),
            "leases": leases,
            "reclaims": reclaims,
            "reclaimed_bytes": reclaimed,
            "sheds": sheds,
            "oom_retries": retries,
        }

    # -- fan-out sinks -------------------------------------------------------
    def set_metrics(self, metrics: Any) -> None:
        """Attach a metrics Manager (weakly held; every attached
        Manager receives every later change as
        ``app_tpu_device_bytes{subsystem=...}``). ``None`` detaches
        all sinks."""
        if metrics is None:
            self._sinks.clear()
            return
        self._sinks.add(metrics)
        for sub in self.live_bytes():
            self._push(sub)
        try:
            metrics.set_gauge(BUDGET_GAUGE, float(self._budget or 0))
        except Exception:
            pass

    def reset(self) -> None:
        """Test hook: forget everything — entries, leases, budget,
        counters (and zero pushed gauges)."""
        with self._mu:
            subs = {sub for (sub, _, _) in self._entries}
            self._entries.clear()
            self._meta.clear()
            self._reclaims.clear()
            self._sheds.clear()
            self._oom_retries.clear()
            self._reclaimed_bytes = 0
        self.set_budget(None)
        for sub in subs:
            self._push(sub)

    def set_timeline(self, timeline: Any) -> None:
        """Attach a serving timeline (weakly held) that receives an
        ``hbm`` counter sample on every accounting change plus
        reclaim/shed instants. ``None`` detaches all timelines."""
        if timeline is None:
            self._timelines.clear()
            return
        self._timelines.add(timeline)
        for sub, n in self.live_bytes().items():
            try:
                timeline.hbm(sub, float(n))
            except Exception:
                pass

    def _count_metric(self, name: str, **labels) -> None:
        for m in list(self._sinks):
            try:
                m.increment_counter(name, **labels)
            except Exception:
                pass  # accounting must never take the serving path down

    def _event_timeline(self, subsystem: str, what: str,
                        nbytes: int) -> None:
        for tl in list(self._timelines):
            try:
                fn = getattr(tl, "hbm_event", None)
                if fn is not None:
                    fn(subsystem, what, float(nbytes))
            except Exception:
                pass

    def _push(self, subsystem: str) -> None:
        sinks = list(self._sinks)
        timelines = list(self._timelines)
        if not sinks and not timelines:
            return
        value = float(self.live_bytes().get(subsystem, 0))
        for m in sinks:
            try:
                m.set_gauge(GAUGE, value, subsystem=subsystem)
            except Exception:
                pass  # accounting must never take the serving path down
        for tl in timelines:
            try:
                tl.hbm(subsystem, value)
            except Exception:
                pass


_registry = _Registry()

account = _registry.account
alloc = _registry.alloc
arbiter_stats = _registry.arbiter_stats
budget = _registry.budget
check = _registry.check
configure = _registry.configure
lease = _registry.lease
live_bytes = _registry.live_bytes
note_shed = _registry.note_shed
reclaim = _registry.reclaim
release = _registry.release
reset = _registry.reset
set_budget = _registry.set_budget
set_metrics = _registry.set_metrics
set_timeline = _registry.set_timeline
snapshot = _registry.snapshot
