"""Device-memory accounting: the choke point every persistent HBM
allocation flows through.

The ROADMAP's unified memory arbiter needs one thing before it can
exist: visibility. ``BENCH_CANDIDATE.json`` shows the prefix-cache,
engine and speculative arms dying with RESOURCE_EXHAUSTED and the
paged engine OOMing at every batch size because each subsystem
allocates HBM blindly — nobody can SEE device memory, so nobody can
rebalance it. This module is the accounting substrate: every
persistent device buffer a serving subsystem creates is declared here
(gofrlint GL202 enforces it statically), so the registry always knows
how many bytes each subsystem holds. The arbiter refactor will grow
lease/rebalance semantics on top of exactly this table; today it
feeds:

  - the ``app_tpu_device_bytes{subsystem=...}`` Prometheus gauges
    (register a metrics Manager via :func:`set_metrics` — the engine
    wiring does) and the ``device_memory`` section of ``/debug/vars``;
  - ``gofr_tpu/testutil/hbmwatch.py``, which reconciles these declared
    bytes against ``jax.live_arrays()`` ground truth under
    ``pytest --hbmwatch``;
  - ``tools/hbm_report.py``, the operator's attribution table.

Usage — wrap the allocation at its persist point; ``account`` RETURNS
the tree so it composes inline::

    self.cache = hbm.account("engine", llama.init_cache(...),
                             owner=self, tag="cache")

Entries are keyed ``(subsystem, owner, tag)`` with SET semantics:
re-accounting the same key (recovery reallocation, mesh re-placement
via ``device_put``) replaces the figure instead of double-counting —
the old buffer was consumed/freed by whatever produced the new one.
``owner`` scopes entries to an engine instance so two engines in one
process (tests, A/B serving) attribute independently; an owner's
``close()`` must call :func:`release`, which is how hbmwatch proves a
closed engine actually let go of its bytes.

Subsystem names are free-form but the serving stack uses a fixed
vocabulary so dashboards line up: ``engine`` (serving KV cache +
chunk scratch row), ``kvcache-t0`` (prefix-pool rows), ``lora``
(adapter weight stacks), ``spec-decode`` (verify buffers, when they
grow device state), ``batcher`` (coalesced staging, likewise).
"""

from __future__ import annotations

import threading
import weakref
from typing import Any

__all__ = ["account", "release", "live_bytes", "set_metrics",
           "set_timeline", "tree_nbytes", "reset", "snapshot"]

GAUGE = "app_tpu_device_bytes"


def tree_nbytes(tree: Any) -> int:
    """Total bytes of the array leaves of ``tree`` (jax or numpy —
    anything with ``nbytes``). None leaves (e.g. absent scale planes)
    contribute nothing."""
    import jax

    return sum(int(getattr(leaf, "nbytes", 0) or 0)
               for leaf in jax.tree.leaves(tree))


class _Registry:
    def __init__(self) -> None:
        self._mu = threading.Lock()
        # (subsystem, owner_id, tag) -> bytes
        self._entries: dict[tuple[str, int, str], int] = {}
        # gauge sinks, weakly held: the registry outlives any Manager
        # and must neither pin one alive nor stop pushing to A because
        # B registered later (two engines, two Managers — both see the
        # same process-truth figures)
        self._sinks: "weakref.WeakSet[Any]" = weakref.WeakSet()
        # serving timelines (observe/timeline.py), same weak fan-out:
        # every accounting change lands a counter sample so the
        # exported Perfetto trace carries an HBM track per subsystem
        self._timelines: "weakref.WeakSet[Any]" = weakref.WeakSet()

    def account(self, subsystem: str, tree: Any, *, owner: Any = None,
                tag: str = "") -> Any:
        key = (subsystem, id(owner) if owner is not None else 0, tag)
        n = tree_nbytes(tree)
        with self._mu:
            self._entries[key] = n
        if owner is not None:
            # safety net for owners that die WITHOUT close() — an
            # __init__ that OOMs after its first account() (exactly
            # the regime this registry exists for) must not leave
            # phantom bytes behind, and a reused id() must not alias a
            # dead owner's entries. Idempotent with close()'s explicit
            # release; runs at the owner's collection.
            try:
                weakref.finalize(owner, self._release_owner_id,
                                 id(owner))
            except TypeError:
                pass  # non-weakrefable owner: explicit release only
        self._push(subsystem)
        return tree

    def _release_owner_id(self, oid: int) -> None:
        touched: set[str] = set()
        with self._mu:
            for key in list(self._entries):
                if key[1] == oid:
                    self._entries.pop(key)
                    touched.add(key[0])
        for sub in touched:
            self._push(sub)

    def release(self, subsystem: str | None = None, *,
                owner: Any = None) -> int:
        """Drop entries by subsystem and/or owner; returns the bytes
        released. ``release(owner=self)`` in ``close()`` drops every
        subsystem the instance accounted."""
        oid = None if owner is None else id(owner)
        dropped = 0
        touched: set[str] = set()
        with self._mu:
            for key in list(self._entries):
                sub, key_oid, _ = key
                if subsystem is not None and sub != subsystem:
                    continue
                if oid is not None and key_oid != oid:
                    continue
                dropped += self._entries.pop(key)
                touched.add(sub)
        for sub in touched:
            self._push(sub)
        return dropped

    def live_bytes(self) -> dict[str, int]:
        """Accounted bytes aggregated by subsystem (zero-byte
        subsystems with live keys included — a released-to-zero
        subsystem disappears)."""
        out: dict[str, int] = {}
        with self._mu:
            for (sub, _, _), n in self._entries.items():
                out[sub] = out.get(sub, 0) + n
        return dict(sorted(out.items()))

    def snapshot(self) -> dict[tuple[str, int, str], int]:
        with self._mu:
            return dict(self._entries)

    def set_metrics(self, metrics: Any) -> None:
        """Attach a metrics Manager (weakly held; every attached
        Manager receives every later change as
        ``app_tpu_device_bytes{subsystem=...}``). ``None`` detaches
        all sinks."""
        if metrics is None:
            self._sinks.clear()
            return
        self._sinks.add(metrics)
        for sub in self.live_bytes():
            self._push(sub)

    def reset(self) -> None:
        """Test hook: forget everything (and zero pushed gauges)."""
        with self._mu:
            subs = {sub for (sub, _, _) in self._entries}
            self._entries.clear()
        for sub in subs:
            self._push(sub)

    def set_timeline(self, timeline: Any) -> None:
        """Attach a serving timeline (weakly held) that receives an
        ``hbm`` counter sample on every accounting change. ``None``
        detaches all timelines."""
        if timeline is None:
            self._timelines.clear()
            return
        self._timelines.add(timeline)
        for sub, n in self.live_bytes().items():
            try:
                timeline.hbm(sub, float(n))
            except Exception:
                pass

    def _push(self, subsystem: str) -> None:
        sinks = list(self._sinks)
        timelines = list(self._timelines)
        if not sinks and not timelines:
            return
        value = float(self.live_bytes().get(subsystem, 0))
        for m in sinks:
            try:
                m.set_gauge(GAUGE, value, subsystem=subsystem)
            except Exception:
                pass  # accounting must never take the serving path down
        for tl in timelines:
            try:
                tl.hbm(subsystem, value)
            except Exception:
                pass


_registry = _Registry()

account = _registry.account
release = _registry.release
live_bytes = _registry.live_bytes
snapshot = _registry.snapshot
set_metrics = _registry.set_metrics
set_timeline = _registry.set_timeline
reset = _registry.reset
