"""Prefix KV cache: exact-prefix reuse across requests (host-side index).

Serving workloads repeat prompt prefixes constantly — a shared system
prompt, few-shot preambles, multi-turn chats resending history. Causal
attention makes their KV reusable as-is: positions < m depend only on
tokens[:m], so a stored prefix row is valid for ANY continuation. On
TPU the trade is stark: recomputing a 512-token prefix costs a full
prefill dispatch of MXU work, while restoring it is one HBM->HBM copy
of the row (~70 MB for 8B int8 dims, ~100 µs at v5e bandwidth) — the
engine does the copy on-device (generator._pool_load) and prefills only
the remainder.

This module is the host half: an LRU index mapping stored token
prefixes to pool rows. The device half (the [L, P, Smax, KV, hd] pool
arrays and the jitted row copies) lives in the GenerationEngine, which
owns device state. The index never holds device memory and all methods
are O(pool * prefix) numpy compares — noise next to a dispatch.

The reference has no inference layer to compare against (SURVEY §2);
the design target is the standard vLLM/SGLang prefix-reuse semantics,
restricted to whole-stored-prefix LCP matching (no radix tree yet).
"""

from __future__ import annotations

import numpy as np


class PrefixIndex:
    """LRU index of ``slots`` stored prefixes. Thread-compatible: the
    engine calls it only from the serving loop thread."""

    def __init__(self, slots: int):
        self.slots = int(slots)
        self._keys: list[np.ndarray | None] = [None] * self.slots
        # KV depends on which LoRA adapter computed it (wk/wv flow
        # through the adapter), so entries are keyed by (tokens,
        # adapter) — a stored prefix never restores across adapters
        self._adapter = [0] * self.slots
        self._tick = 0
        self._used = [0] * self.slots
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return sum(1 for k in self._keys if k is not None)

    def match(self, prompt: np.ndarray, adapter: int = 0) -> tuple[int, int]:
        """(pool_row, matched_len) for the longest common prefix between
        ``prompt`` and any stored entry — a PARTIAL match of a stored
        prefix is still valid KV (a prefix of a prefix). (-1, 0) when
        nothing matches. PURE: the caller decides whether the match is
        USABLE (long enough, valid chunk window) and reports back via
        accept()/reject() — counting a hit or refreshing LRU for a match
        the engine then discards would diverge the stats from the
        Prometheus counter and keep useless entries alive at eviction."""
        best, best_len = -1, 0
        for i, key in enumerate(self._keys):
            if key is None or self._adapter[i] != adapter:
                continue
            n = min(len(key), len(prompt))
            if n <= best_len:
                continue
            neq = np.nonzero(key[:n] != prompt[:n])[0]
            m = int(neq[0]) if len(neq) else n
            if m > best_len:
                best, best_len = i, m
        return (best, best_len) if best >= 0 and best_len > 0 else (-1, 0)

    def accept(self, row: int) -> None:
        """The engine restored ``row``: count the hit, touch LRU."""
        self.hits += 1
        self._tick += 1
        self._used[row] = self._tick

    def reject(self) -> None:
        """No usable match for this admission."""
        self.misses += 1

    def covered(self, prompt: np.ndarray, adapter: int = 0) -> bool:
        """True when some stored entry (same adapter) already contains
        ``prompt`` as a prefix — storing it again would only duplicate."""
        for i, key in enumerate(self._keys):
            if key is not None and self._adapter[i] == adapter \
                    and len(key) >= len(prompt) \
                    and np.array_equal(key[:len(prompt)], prompt):
                return True
        return False

    def store_row(self, prompt: np.ndarray, adapter: int = 0) -> int:
        """Pick the row for a new entry (free row, else LRU victim),
        record the (key, adapter), return the row index."""
        victim = None
        for i, key in enumerate(self._keys):
            if key is None:
                victim = i
                break
        if victim is None:
            victim = min(range(self.slots), key=lambda i: self._used[i])
        self._tick += 1
        self._keys[victim] = np.asarray(prompt, np.int32).copy()
        self._adapter[victim] = int(adapter)
        self._used[victim] = self._tick
        return victim

    def clear(self) -> int:
        """Drop every entry. Engine recovery calls this after
        reallocating the side pool: stored keys would otherwise match
        prompts against rows of the NEW (zeroed) pool and restore
        all-zero KV."""
        n = len(self)
        self._keys = [None] * self.slots
        self._adapter = [0] * self.slots
        self._used = [0] * self.slots
        return n

    def invalidate_adapter(self, adapter: int) -> int:
        """Drop every entry stored under ``adapter`` — required when its
        LoRA weights are hot-swapped (the stored KV was computed through
        the OLD wk/wv and would serve wrong attention keys). Returns the
        number of dropped entries."""
        n = 0
        for i, key in enumerate(self._keys):
            if key is not None and self._adapter[i] == int(adapter):
                self._keys[i] = None
                self._used[i] = 0
                n += 1
        return n

    def stats(self) -> dict:
        return {"slots": self.slots, "entries": len(self),
                "hits": self.hits, "misses": self.misses}
