"""TPU datasource: engine, continuous batching, checkpoint loading.

Wired into the container the way Redis/SQL are in the reference
(pkg/gofr/container/container.go:55-126 builds each datasource from config
with graceful degradation): ``new_engine_from_config`` reads ``TPU_*``
config keys, builds the engine, registers the model family's programs, and
hands back a health-checkable datasource reachable as ``ctx.tpu``.

Config keys (reference config style, pkg/gofr/config/config.go:3):
  TPU_MODEL           model name: llama family (llama3-8b, llama-1b, tiny),
                      bert family (bert/bert-base, bert-tiny), or
                      vit family (vit/vit-l-14, vit-tiny)
  TPU_WEIGHTS         checkpoint path (.npz or orbax dir); absent = random
                      init (smoke/serving-bringup mode)
  TPU_QUANT           "int8" to quantize projection weights on load
  TPU_KV_DTYPE        KV-cache dtype for generation: "int8" (default —
                      halves decode's cache HBM stream; quantize-on-write,
                      dequant fused into attention) or "bf16"/"model" for
                      the exact dense cache
  TPU_SLOTS           decode batch slots for generation (default 48 —
                      decode streams the full weight set per step, so
                      throughput scales with tokens per weight pass until
                      HBM runs out; shrink for small-HBM chips)
  TPU_MAX_SEQ         serving KV capacity (default min(model max, 2048))
  TPU_DECODE_BLOCK    decode steps fused per device dispatch (default 4 —
                      the stream sees K tokens per roundtrip; raise on
                      high-latency links, lower toward 1 for tightest
                      per-token latency)
  TPU_DECODE_PIPELINE fused decode blocks in flight on the device
                      stream at once (default 2 — the loop dispatches
                      block N+1 before reaping block N, overlapping
                      host reap/delivery/admission with device compute;
                      on-device stop masks keep finished streams from
                      burning the extra in-flight block. 1 = the
                      serial dispatch->reap loop. Depth auto-drops to
                      1 while a latency-class admission waits, a chunk
                      lattice is deferred, or spec decode is on —
                      resilience.DecodePipelinePolicy)
  TPU_ADMIT_WINDOW_MS in-flight admission poll cadence in ms (default
                      2 — decode blocks dispatch async and new requests
                      are admitted while one runs, their prefill
                      queueing behind it on the device stream)
  TPU_PREFILL_CHUNK   chunked-prefill interleave budget in tokens
                      (docs/advanced-guide/serving-scheduler.md):
                      prompts longer than the budget admit as bounded
                      chunk dispatches with one admission pass + one
                      decode block between chunks, so a long prefill
                      neither stalls active decode streams nor
                      head-of-line-blocks a newly arrived request.
                      Unset = the largest prompt bucket; other values
                      snap UP to a prompt bucket; 0 disables the
                      interleave (chunks dispatch back-to-back — the
                      bench contrast arm)
  TPU_SLO_THROUGHPUT_FACTOR  scale on every AdmissionGate bound for
                      throughput-class requests (default 0.5): batch
                      traffic sheds and brownouts FIRST as load rises;
                      1.0 restores class-blind gating
  TPU_SLO_THROUGHPUT_SHARE   generation pending-line share guaranteed
                      to throughput-class under latency saturation
                      (default 0.25 — one pick in four); 0 drains
                      throughput only on latency idle
  TPU_SLO_LATENCY_SLOTS      decode slots throughput-class admissions
                      may never occupy (default 1, clamped below the
                      slot count): a latency request under batch-driven
                      saturation finds a slot at its uncontended wait
                      instead of queueing behind admitted batch
                      streams. Costs idle capacity only while tagged
                      throughput traffic saturates; 0 disables
  TPU_SLO_BATCH_SHARE enable SLO-class scheduling in the predict
                      batchers with this throughput reserve share
                      (default 0 = off: class lines run the Python
                      dispatcher, giving up the native GIL-released
                      wait — a measured tradeoff, not a default)
  TPU_SLO_BATCH_DELAY throughput-class flush delay for the predict
                      batchers in seconds (default 4x
                      TPU_MAX_BATCH_DELAY — batch items wait longer
                      for fuller batches)
  TPU_PREFIX_CACHE    prefix-KV pool rows (default 0 = off): stored
                      prompt prefixes restore as one HBM row copy
                      instead of prefill compute. The pool is the T0
                      tier of the hierarchical kv cache (tpu/kvcache/,
                      docs/advanced-guide/kv-cache.md); the radix
                      index, host-DRAM offload and Redis-shared tiers
                      are tuned by the TPU_KVCACHE_* keys below
  TPU_PREFIX_MIN      min prompt length stored in the pool (default:
                      the largest prompt bucket)
  TPU_KVCACHE_BLOCK   radix/content-hash block size in tokens
                      (default 16); also the Redis tier's sharing
                      granularity
  TPU_KVCACHE_HOST_MB host-DRAM offload tier budget in MiB (default 0
                      = off): LRU-evicted pool rows spill to host
                      numpy and restore via device_put on hit —
                      cache capacity beyond HBM, survives device loss.
                      On mesh engines rows spill/restore PER SHARD
                      (each tp shard's head range reads off its own
                      device; promotion lands the assembled row with
                      one sharded write)
  TPU_KVCACHE_REDIS   "true" shares quantized int8 KV blocks through
                      the framework Redis client (REDIS_HOST/PORT) so
                      replicas warm each other (default off)
  TPU_KVCACHE_REDIS_TTL_S      shared-block TTL seconds (default 300)
  TPU_KVCACHE_REDIS_TIMEOUT_S  socket timeout for the tier's dedicated
                      client (default 0.25 — fail open fast; the
                      serving loop must never stall on Redis)
  TPU_KVCACHE_EPOCH_REFRESH_S  staleness bound on the adapter-epoch
                      invalidation key (default 5)
  TPU_SPEC_DECODE     prompt-lookup speculative decoding: K draft
                      tokens per verify pass (default 0 = off). One
                      weight stream emits 1..K+1 tokens per greedy slot
                      when its history's trailing n-gram repeats
  TPU_PAGED_BLOCKS    paged KV cache: pool blocks incl. the reserved
                      trash block (default 0 = contiguous rows). Slots
                      share fixed-size blocks via a block table, so HBM
                      sizes to expected LIVE tokens and decode batch
                      scales past what [slots, max_seq] rows fit
                      (models/paged_llama.py; long prompts chunk via a
                      dense scratch row; composes with TPU_SPEC_DECODE,
                      and with TPU_PREFIX_CACHE the prefix cache
                      becomes zero-copy block sharing). Composes with
                      TPU_SHARDING: the pool shards KV-heads over tp
                      and attention runs the dense-gather reference
                      (the Pallas kernel is single-device)
  TPU_PAGED_BLOCK     block size in tokens (default 128)
  TPU_LORA_ADAPTERS   multi-LoRA serving: adapter slots (default 0 =
                      off; slot 0 is the base no-op). Per-request
                      selection via generate(adapter=i); install
                      weights with engine.generator.load_adapter
  TPU_LORA_RANK       LoRA bottleneck rank (default 16)
  TPU_HBM_BUDGET_MB   HBM arbiter budget in MiB (docs/advanced-guide/
                      memory.md): one budget every subsystem leases
                      from, with demand-driven reclaim (T0 shrinks
                      toward the host tier, cold paged blocks release)
                      and an OOM-shed path (429/RESOURCE_EXHAUSTED +
                      Retry-After) instead of process death. Unset/0 =
                      resolve from the device's reported limit minus
                      the headroom fraction on accelerator backends;
                      on CPU the budget stays off unless set
  TPU_HBM_HEADROOM    fraction of the device limit the resolved budget
                      leaves free for XLA workspace the accounting
                      registry can't see (default 0.1)
  TPU_HBM_DEVICE_BUDGET_MB  PER-DEVICE arbiter budget in MiB for mesh
                      serving (docs/advanced-guide/
                      multichip-serving.md): sharded buffers settle
                      one lease per device, each checked against this
                      bound, and a hot shard's deficit reclaims only
                      that device's leases. Unset = resolved per
                      device on accelerator backends; inert for
                      single-device engines
  TPU_MAX_QUEUE_DEPTH admission control (resilience.AdmissionGate):
                      shed with 429/RESOURCE_EXHAUSTED once this many
                      requests wait in a queue (default 0 = off)
  TPU_MAX_QUEUE_DELAY shed once the observed queue-wait EWMA exceeds
                      this many seconds (default 0 = off)
  TPU_BROWNOUT_DELAY  brownout band: cap max_new_tokens while the
                      queue-wait EWMA exceeds this (default 0 = off)
  TPU_BROWNOUT_MAX_NEW token cap applied in brownout (default 32)
  TPU_BATCH_BUCKETS   csv of predict batch buckets (default 1,2,4,8)
  TPU_SEQ_BUCKETS     csv of token-length buckets  (default 32..512)
  TPU_MAX_BATCH_DELAY coalescing window in seconds (default 0.004)
  TPU_SHARDING        "tp=8" / "tp=4,dp=2" mesh axes for sharded serving
                      (axes from gofr_tpu.parallel; weights get
                      NamedShardings, XLA inserts the ICI collectives)
  TPU_SERVING_ROLE    disaggregated prefill/decode serving
                      (docs/advanced-guide/disaggregated-serving.md):
                      "fused" (default — one process serves both
                      phases), "prefill" (this worker computes prompt
                      KV and ships checksummed int8 block frames to
                      the decode pool, relaying its token stream), or
                      "decode" (this worker listens for shipped KV,
                      owns the slot lattice and the token stream).
                      Each pool draws its own TPU_HBM_BUDGET_MB with
                      its own reclaim policy. "gateway" is the APP
                      mode that fronts N replicas with prefix-affinity
                      routing + failover (gofr_tpu/gateway,
                      docs/advanced-guide/gateway.md, TPU_GATEWAY_*
                      rows in config-reference) — it holds no model,
                      so setting it alongside TPU_MODEL fails startup
  TPU_PD_LISTEN       decode role: host:port the KV-ingest listener
                      binds (default 127.0.0.1:9400)
  TPU_PD_PEER         prefill role: the decode worker's TPU_PD_LISTEN
                      address (required)
  TPU_PD_BLOCK        KV-ship frame granularity in tokens (default 16
                      — one frame per radix-sized block, streamed as
                      prefill chunks complete)
  TPU_PD_WINDOW_MB    KV-ship backpressure window in MiB (default 8):
                      unsent bytes past this block the shipper until
                      the peer drains (typed 502 when a wedged peer
                      stalls past the request deadline)
  TPU_WARMUP          "true" to precompile all buckets at startup
  TPU_TENANTS         multi-tenant serving plane (gofr_tpu/tenancy,
                      docs/advanced-guide/multi-tenancy.md): path to a
                      hot-reloadable JSON tenant registry mapping
                      tenant id -> LoRA adapter, SLO-class default,
                      fair-share queue weight, rps/concurrency quota
                      and cache-budget share. Unset AND no
                      TPU_TENANTS_INLINE = tenancy off (anonymous
                      single-tenant serving, zero overhead)
  TPU_TENANTS_INLINE  the same registry as a literal JSON string (for
                      tests/static fleets; TPU_TENANTS wins when both
                      are set)
  TPU_TENANTS_RELOAD_S  registry-file mtime poll throttle in seconds
                      (default 0.5)
  TPU_TENANT_HEADER   HTTP header carrying the tenant id (default
                      X-Tenant-Id; gRPC always reads x-tenant-id
                      metadata)
  TPU_TENANT_TOPIC    pub/sub topic the async inference lane consumes
                      (default inference-jobs); the lane is installed
                      by tenancy.install_async_lane(app)
  TPU_TENANT_CHECKPOINT_EVERY  async-lane resume-checkpoint cadence in
                      tokens (default 8)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .batcher import BatcherClosed, ClassPolicy, CoalescingBatcher, pad_bucket
from .checkpoint import (load_npz, load_orbax, load_params, maybe_quantize,
                         placed, save_npz, save_orbax)
from .engine import DEFAULT_BATCH_BUCKETS, DEFAULT_SEQ_BUCKETS, Program, TPUEngine
from .generator import GenerationEngine, GenerationError, GenStream

__all__ = [
    "BatcherClosed", "ClassPolicy", "CoalescingBatcher", "pad_bucket",
    "load_npz", "load_orbax", "load_params", "maybe_quantize", "placed",
    "save_npz", "save_orbax",
    "DEFAULT_BATCH_BUCKETS", "DEFAULT_SEQ_BUCKETS", "Program", "TPUEngine",
    "GenerationEngine", "GenerationError", "GenStream",
    "new_engine_from_config", "parse_mesh",
]


def _opt_int(val: str | None) -> int | None:
    """Tri-state int key (unset -> None, which get_int's single default
    cannot express); malformed values fall back to None like every
    other config key degrades to its default instead of crashing
    startup."""
    if not val:
        return None
    try:
        return int(val)
    except (TypeError, ValueError):
        return None


def _csv_ints(val: str | None, default: tuple[int, ...]) -> tuple[int, ...]:
    if not val:
        return default
    return tuple(int(x) for x in val.split(",") if x.strip())


def parse_mesh(spec: str | None):
    """"tp=8" / "tp=4,dp=2" -> Mesh over the named parallel axes (the
    TPU_SHARDING row syntax). Public: tools/benches that accept the
    same rows must parse them identically to the production wiring."""
    if not spec:
        return None
    from ..parallel import make_mesh

    axes = {}
    for part in spec.split(","):
        k, _, v = part.partition("=")
        axes[k.strip()] = int(v)
    return make_mesh(**axes)


def new_engine_from_config(cfg, logger=None, metrics=None,
                           observe=None) -> TPUEngine:
    from ..models import BERT_CONFIGS, LLAMA_CONFIGS, VIT_CONFIGS

    if (cfg.get("TPU_SERVING_ROLE") or "").strip().lower() == "gateway":
        # the gateway role (gofr_tpu/gateway) is an APP mode, not an
        # engine mode: it fronts replicas and holds no model. A config
        # naming both is two deployments in one file — refuse BEFORE
        # building anything rather than guess which one was meant.
        raise ValueError(
            "TPU_SERVING_ROLE=gateway builds no engine (the gateway "
            "routes to TPU_GATEWAY_REPLICAS); unset "
            f"TPU_MODEL={cfg.get('TPU_MODEL')!r} on the gateway "
            "process, or drop the gateway role on this serving "
            "replica (docs/advanced-guide/gateway.md)")
    name = (cfg.get("TPU_MODEL") or "tiny").strip()
    mesh = parse_mesh(cfg.get("TPU_SHARDING"))
    max_delay = cfg.get_float("TPU_MAX_BATCH_DELAY", 0.004)
    batch_buckets = _csv_ints(cfg.get("TPU_BATCH_BUCKETS"), DEFAULT_BATCH_BUCKETS)
    seq_buckets = _csv_ints(cfg.get("TPU_SEQ_BUCKETS"), DEFAULT_SEQ_BUCKETS)

    from ..resilience import gate_from_config
    from . import hbm

    # the HBM arbiter budget (one per process — subsystems of every
    # engine built after this lease from it; mesh engines additionally
    # settle PER-DEVICE leases checked against the per-device budget)
    hbm.configure(budget_mb=cfg.get_int("TPU_HBM_BUDGET_MB", 0) or None,
                  headroom=cfg.get_float("TPU_HBM_HEADROOM", 0.1),
                  device_budget_mb=cfg.get_int("TPU_HBM_DEVICE_BUDGET_MB",
                                               0) or None)

    tracer = getattr(observe, "tracer", None)
    batch_share = cfg.get_float("TPU_SLO_BATCH_SHARE", 0.0)
    class_policy = None
    if batch_share > 0:
        class_policy = ClassPolicy(
            throughput_delay=cfg.get_float("TPU_SLO_BATCH_DELAY", 0.0)
            or None,
            throughput_share=batch_share)
    engine = TPUEngine(logger=logger, metrics=metrics, max_delay=max_delay,
                       mesh=mesh, model_name=name, observe=observe,
                       class_policy=class_policy,
                       gate=gate_from_config(cfg, "predict", metrics=metrics,
                                             tracer=tracer, logger=logger))

    weights = cfg.get("TPU_WEIGHTS")
    quant = (cfg.get("TPU_QUANT") or "").lower() == "int8"

    def params_for(model_cfg, init_fn):
        if weights:
            params = load_params(weights)
        else:
            params = init_fn(model_cfg, jax.random.PRNGKey(0))
        return placed(maybe_quantize(params, quant), mesh)

    if name.startswith("bert"):
        from ..models import bert

        key = {"bert": "bert-base", "bert-tiny": "tiny"}.get(name, name)
        mc = BERT_CONFIGS[key]
        params = params_for(mc, bert.init)
        seq_b = tuple(b for b in seq_buckets if b <= mc.max_seq) or (mc.max_seq,)

        def embed_fn(p, tokens, lengths):
            mask = jnp.arange(tokens.shape[1])[None, :] < lengths[:, None]
            return bert.embed(p, mc, tokens, mask)

        engine.register("embed", embed_fn, params, kind="tokens",
                        batch_buckets=batch_buckets, seq_buckets=seq_b)
    elif name.startswith("vit"):
        from ..models import vit

        key = {"vit": "vit-l-14", "vit-l14": "vit-l-14", "vit-tiny": "tiny"}.get(name, name)
        mc = VIT_CONFIGS[key]
        params = params_for(mc, vit.init)

        def classify_fn(p, images):
            return jax.nn.softmax(vit.forward(p, mc, images), axis=-1)

        import numpy as np

        engine.register("classify", classify_fn, params, kind="fixed",
                        batch_buckets=batch_buckets,
                        example_item=np.zeros(
                            (mc.image_size, mc.image_size, 3), np.float32))
    else:
        from ..models import llama

        mc = LLAMA_CONFIGS.get(name)
        if mc is None:
            raise KeyError(f"unknown TPU_MODEL {name!r}; known: "
                           f"{sorted(LLAMA_CONFIGS) + sorted(BERT_CONFIGS) + sorted(VIT_CONFIGS)}")
        params = params_for(mc, llama.init)
        max_seq = cfg.get_int("TPU_MAX_SEQ", min(mc.max_seq, 2048))
        slots = cfg.get_int("TPU_SLOTS", 48)
        kv_choice = (cfg.get("TPU_KV_DTYPE") or "int8").lower()
        kv_dtype = jnp.int8 if kv_choice == "int8" else None
        prompt_b = tuple(b for b in seq_buckets if b < max_seq) or (max_seq // 2,)
        kv_opts = None
        if cfg.get_int("TPU_PREFIX_CACHE", 0) > 0 \
                and cfg.get_int("TPU_PAGED_BLOCKS", 0) == 0:
            # paged engines keep their zero-copy SharedPrefixIndex —
            # don't open a Redis connection the engine would
            # immediately discard. Mesh engines DO take the offload
            # tiers: T1/T2 spill/restore sharded rows per shard
            # (docs/advanced-guide/multichip-serving.md)
            from .kvcache import options_from_config

            kv_opts = options_from_config(cfg, logger=logger,
                                          metrics=metrics)
        engine.generator = GenerationEngine(
            mc, params, slots=slots, max_seq=max_seq, prompt_buckets=prompt_b,
            logger=logger, metrics=metrics, observe=observe, mesh=mesh,
            gate=gate_from_config(cfg, "generate", metrics=metrics,
                                  tracer=tracer, logger=logger),
            kv_dtype=kv_dtype,
            decode_block=cfg.get_int("TPU_DECODE_BLOCK", 4),
            decode_pipeline=cfg.get_int("TPU_DECODE_PIPELINE", 2),
            admit_window_ms=cfg.get_float("TPU_ADMIT_WINDOW_MS", 2.0),
            prefill_chunk=_opt_int(cfg.get("TPU_PREFILL_CHUNK")),
            slo_throughput_share=cfg.get_float("TPU_SLO_THROUGHPUT_SHARE",
                                               0.25),
            slo_latency_slots=cfg.get_int("TPU_SLO_LATENCY_SLOTS", 1),
            prefix_cache_slots=cfg.get_int("TPU_PREFIX_CACHE", 0),
            prefix_store_min=cfg.get_int("TPU_PREFIX_MIN", 0) or None,
            kvcache=kv_opts,
            spec_decode_k=cfg.get_int("TPU_SPEC_DECODE", 0),
            lora_adapters=cfg.get_int("TPU_LORA_ADAPTERS", 0),
            lora_rank=cfg.get_int("TPU_LORA_RANK", 16),
            paged_blocks=cfg.get_int("TPU_PAGED_BLOCKS", 0),
            paged_block_size=cfg.get_int("TPU_PAGED_BLOCK", 128))

        # scoring program: next-token logits at the prompt end (the
        # non-streaming sibling of generate, e.g. for classification
        # heads). The batcher coalesces UNRELATED requests into one
        # [B, S] batch, so grouped MoE dispatch is forbidden here just
        # like at decode — request isolation (llama.py:
        # multi_request_serving_config).
        score_mc = llama.multi_request_serving_config(mc)

        def score_fn(p, tokens, lengths):
            logits = llama.forward(p, score_mc, tokens, lengths)
            idx = jnp.maximum(lengths - 1, 0)
            return jnp.take_along_axis(
                logits, idx[:, None, None], axis=1)[:, 0]

        seq_b = tuple(b for b in seq_buckets if b <= max_seq) or (max_seq,)
        engine.register("score", score_fn, params, kind="tokens",
                        batch_buckets=batch_buckets, seq_buckets=seq_b)

    # multi-tenant plane: registry + quotas + fair-share weights
    # (gofr_tpu/tenancy). Installed on the engine AND pushed into the
    # generator so the pending line fans into per-tenant DRR queues and
    # the kv cache learns its per-tenant budget shares.
    from ..tenancy import plane_from_config

    plane = plane_from_config(cfg, metrics=metrics, logger=logger)
    if plane is not None:
        engine.tenancy = plane
        if engine.generator is not None:
            engine.generator.install_tenancy(plane)

    role_key = cfg.get("TPU_SERVING_ROLE")
    if role_key:
        # disaggregated prefill/decode serving (gofr_tpu/pd/,
        # docs/advanced-guide/disaggregated-serving.md): non-fused
        # roles attach their PD half here — after the generator exists,
        # before warmup — so a misconfigured role fails startup loudly
        from ..pd import ROLE_FUSED, parse_role, wire_role

        role = parse_role(role_key)
        if role != ROLE_FUSED:
            wire_role(engine, role, cfg, logger=logger, metrics=metrics)

    if cfg.get_bool("TPU_WARMUP"):
        engine.warmup()
    if logger is not None:
        logger.info({"event": "tpu engine ready", "model": name,
                     "platform": engine.platform, "devices": len(engine.devices),
                     "quant": "int8" if quant else "none",
                     "sharding": cfg.get("TPU_SHARDING") or "single"})
    return engine
