"""Model weight loading/versioning — the framework's "checkpoint" story.

The reference's closest analogue is the migration version ledger
(pkg/gofr/migration/sql.go:142-158); for a serving framework the durable
state is model weights. Two formats:

  - Orbax checkpoint directory (the JAX-ecosystem standard; what training
    jobs emit). Restored leaf-by-leaf onto the host then placed.
  - ``.npz`` flat file with ``/``-joined pytree paths (cheap interchange:
    ``save_npz``/``load_npz`` round-trip any param tree, including int8
    ``QuantizedLinear`` leaves, without a schema).

Quantize-on-load: serving wants int8 projections (decode is HBM-bound);
checkpoints are usually bf16. ``maybe_quantize`` converts the known
projection leaves at load time so the bf16 copy never reaches the device.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.quant import QuantizedLinear, quantize_int8

# Llama projection leaves worth int8-quantizing (stacked [L, in, out]).
_QUANT_LEAVES = {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head"}


def _flatten(tree: Any, prefix: str = "") -> dict[str, np.ndarray]:
    out: dict[str, np.ndarray] = {}
    if isinstance(tree, QuantizedLinear):
        out[prefix + "/__qw"] = np.asarray(tree.w)
        out[prefix + "/__qscale"] = np.asarray(tree.scale)
    elif isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}/{k}" if prefix else k))
    else:
        out[prefix] = np.asarray(tree)
    return out


def _unflatten(flat: dict[str, np.ndarray]) -> Any:
    tree: dict = {}
    quant: dict[str, dict] = {}
    for path, arr in flat.items():
        parts = path.split("/")
        if parts[-1] in ("__qw", "__qscale"):
            q = quant.setdefault("/".join(parts[:-1]), {})
            q["w" if parts[-1] == "__qw" else "scale"] = arr
            continue
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    for path, q in quant.items():
        parts = path.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = QuantizedLinear(w=q["w"], scale=q["scale"])
    return tree


def save_npz(path: str, params: Any) -> None:
    np.savez(path, **_flatten(params))


def load_npz(path: str) -> Any:
    with np.load(path) as f:
        return _unflatten({k: f[k] for k in f.files})


def save_orbax(path: str, params: Any, *, force: bool = False) -> None:
    """``force=True`` overwrites an existing checkpoint at ``path`` —
    "save latest" semantics for resume loops saving back to their own
    output. The default stays refuse-to-overwrite so a mispointed path
    can't silently destroy existing weights."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), params, force=force)


def load_orbax(path: str, target: Any = None) -> Any:
    """``target``: optional abstract pytree (ShapeDtypeStructs, possibly
    with shardings) — restores each leaf to that shape/sharding (the
    sharded-resume path, parallel.restore_train_state)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        if target is not None:
            return ckptr.restore(os.path.abspath(path), target)
        return ckptr.restore(os.path.abspath(path))




def load_params(path: str) -> Any:
    """Dispatch on layout: .npz file or orbax directory."""
    if path.endswith(".npz"):
        return load_npz(path)
    if os.path.isdir(path):
        return load_orbax(path)
    raise FileNotFoundError(f"no checkpoint at {path!r} (expected .npz file "
                            "or orbax directory)")


def maybe_quantize(params: Any, enabled: bool) -> Any:
    """Int8-quantize known projection leaves of a llama param tree."""
    if not enabled:
        return params

    def walk(node: Any, name: str = "") -> Any:
        if isinstance(node, dict):
            return {k: walk(v, k) for k, v in node.items()}
        if (name in _QUANT_LEAVES and not isinstance(node, QuantizedLinear)
                and getattr(node, "ndim", 0) in (2, 3, 4)):
            w = jnp.asarray(node)
            # stacked layers / [L, E, in, out] MoE expert stacks: the
            # contraction axis is ndim-2 in every rank — quantize per
            # (layer[, expert], out-channel)
            axis = w.ndim - 2
            return quantize_int8(w, axis=axis)
        return node

    return walk(params)


def placed(params: Any, mesh=None) -> Any:
    """Move a host param tree onto device — sharded over ``mesh`` when
    given (specs from parallel.param_specs), else default placement."""
    if mesh is not None:
        from ..parallel import shard_params

        return shard_params(params, mesh)
    return jax.device_put(params)
