"""TPU inference engine: the framework's flagship datasource.

No reference equivalent (SURVEY §2 last rows): GoFr's container carries
Redis/SQL/PubSub clients (pkg/gofr/container/container.go:26-38); here the
accelerator is wired the same way — constructed from config with graceful
degradation, health-checked into ``/.well-known/health``, observable through
``app_tpu_*`` metrics, reachable from handlers as ``ctx.tpu``.

TPU-first design:
  - Programs are jitted callables compiled AOT per (batch, seq) BUCKET.
    XLA traces once per static shape; serving arbitrary request shapes
    means padding to a small lattice of precompiled shapes, never
    recompiling on the hot path.
  - A single dispatcher (``CoalescingBatcher``) coalesces concurrent
    handler threads into one device dispatch, so MXU utilization scales
    with offered load.
  - Results transfer device->host once per batch (one ``jax.device_get``),
    and inputs are stacked host-side then transferred once.
  - Weights live on device permanently (params are device arrays, possibly
    sharded over a mesh by the config wiring; the engine is layout-agnostic).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..datasource import Health, STATUS_DEGRADED, STATUS_DOWN, STATUS_UP
from ..errors import DeadlineExceeded, ProgramNotFound, ServiceUnavailable
from ..resilience import current_deadline, current_slo_class
from . import hbm
from .batcher import ClassPolicy, CoalescingBatcher, pad_bucket

DEFAULT_BATCH_BUCKETS = (1, 2, 4, 8)
DEFAULT_SEQ_BUCKETS = (32, 64, 128, 256, 512)


@dataclass
class Program:
    """One servable compiled function.

    kind="tokens": items are 1-D int32 token arrays of varying length;
      the runner pads to (Bb, Sb) buckets and calls
      ``fn(params, tokens[B,S], lengths[B])``.
    kind="fixed": items are pytrees of fixed-shape arrays; the runner
      stacks them on a new leading axis and calls ``fn(params, batch)``.

    ``fn`` must return an array (or pytree) with leading batch axis.
    """

    name: str
    fn: Callable
    params: Any
    kind: str = "tokens"
    batch_buckets: tuple[int, ...] = DEFAULT_BATCH_BUCKETS
    seq_buckets: tuple[int, ...] = DEFAULT_SEQ_BUCKETS
    example_item: Any = None  # fixed-kind: per-item input struct for warmup
    _jitted: Callable = field(init=False, default=None)
    _compiled_shapes: set = field(init=False, default_factory=set)

    def __post_init__(self):
        self.batch_buckets = tuple(sorted(self.batch_buckets))
        self.seq_buckets = tuple(sorted(self.seq_buckets))
        self._jitted = jax.jit(self.fn)

    @property
    def max_batch(self) -> int:
        return self.batch_buckets[-1]


class TPUEngine:
    """Registry of compiled programs + coalescing dispatch + health.

    Thread-safe: any number of handler threads may call ``predict``
    concurrently; per-program batchers serialize device dispatch.
    """

    def __init__(self, logger=None, metrics=None, max_delay: float = 0.004,
                 mesh=None, model_name: str = "", observe=None, gate=None,
                 class_policy: ClassPolicy | None = None):
        self.logger = logger
        self.metrics = metrics
        self.observe = observe  # Observe bundle (registry + flight recorder)
        # serving timeline (observe/timeline.py): None when emission is
        # off so hot paths pay one attribute test (see generator)
        tl = getattr(observe, "timeline", None) if observe is not None \
            else None
        self._tl = tl if (tl is not None and tl.enabled) else None
        # resilience.AdmissionGate TEMPLATE (None = admit everything):
        # each program gets its own clone (one gate per queue — a shared
        # wait EWMA would let a backlogged program shed a healthy one's
        # traffic), fed with that program's batch waits at dispatch
        self.gate = gate
        self._gates: dict[str, Any] = {}
        # SLO-class batching policy (None = classic FIFO): per-class
        # wait lines in every program's batcher — latency first,
        # throughput on a longer delay with a reserved pickup share.
        # Opt-in (TPU_SLO_BATCH_SHARE): the class-aware line runs the
        # Python dispatcher, giving up the native scheduler's
        # GIL-released wait.
        self.class_policy = class_policy
        self.max_delay = max_delay
        self.mesh = mesh
        self.model_name = model_name
        self.devices = jax.devices()
        self.platform = self.devices[0].platform
        self.device_kind = self.devices[0].device_kind
        self._programs: dict[str, Program] = {}
        self._batchers: dict[str, CoalescingBatcher] = {}
        self._lock = threading.Lock()
        self.generator = None  # set by config wiring for decoder models
        # disaggregated serving (gofr_tpu/pd/): the config wiring sets
        # exactly one of these for non-fused roles — a prefill worker's
        # coordinator (generate() routes through it) or a decode
        # worker's KV-ingest listener
        self.serving_role = "fused"
        self.pd_prefill = None
        self.pd_ingest = None
        # tenancy.TenantPlane, set by the config wiring when TPU_TENANTS
        # is configured; None = anonymous single-tenant serving
        self.tenancy = None
        self._closed = False
        if metrics is not None:
            # device-byte + arbiter gauges/counters (app_tpu_device_
            # bytes, app_tpu_hbm_*): attach even for engines without a
            # generator — the batcher's OOM-shed path counts through
            # the same registry
            hbm.set_metrics(metrics)
            try:
                metrics.set_gauge("app_tpu_devices", len(self.devices))
            except Exception:
                pass

    # -- registration --------------------------------------------------------
    def register(self, name: str, fn: Callable, params: Any, *,
                 kind: str = "tokens",
                 batch_buckets: Sequence[int] = DEFAULT_BATCH_BUCKETS,
                 seq_buckets: Sequence[int] = DEFAULT_SEQ_BUCKETS,
                 example_item: Any = None) -> Program:
        prog = Program(name=name, fn=fn, params=params, kind=kind,
                       batch_buckets=tuple(batch_buckets),
                       seq_buckets=tuple(seq_buckets),
                       example_item=example_item)
        with self._lock:
            self._programs[name] = prog
            if self.gate is not None:
                self._gates[name] = self.gate.clone(name)
            self._batchers[name] = CoalescingBatcher(
                runner=lambda items, p=prog: self._run_batch(p, items),
                max_batch=prog.max_batch, max_delay=self.max_delay,
                name=f"tpu-{name}", on_dispatch=self._dispatch_metrics(prog),
                on_queue_depth=self._depth_gauge(name),
                on_expired=self._expired_counter(name),
                class_policy=self.class_policy, timeline=self._tl)
        if self.logger is not None:
            self.logger.info({"event": "tpu program registered", "program": name,
                              "kind": kind, "batch_buckets": list(prog.batch_buckets)})
        return prog

    def _depth_gauge(self, program: str):
        if self.metrics is None:
            return None

        def hook(depth: int) -> None:
            self.metrics.set_gauge("app_tpu_queue_depth", float(depth),
                                   program=program)
        return hook

    def _gate_for(self, program: str):
        """The program's own gate clone; created lazily for gates
        installed after registration (tests, dynamic reconfiguration)."""
        g = self._gates.get(program)
        if g is None and self.gate is not None:
            with self._lock:
                g = self._gates.get(program)
                if g is None:
                    # (GL203 suppressed: keyed by program NAME —
                    # bounded by register() calls, not by requests)
                    g = self.gate.clone(program)
                    self._gates[program] = g  # noqa: GL203
        return g

    def _dispatch_metrics(self, prog: Program):
        def hook(batch_size: int, oldest_wait: float) -> None:
            gate = self._gates.get(prog.name)
            if gate is not None:
                # the gate's shed decision tracks what a new arrival
                # would wait — exactly this program's oldest-item wait
                gate.note_wait(oldest_wait)
            if self.metrics is None:
                return
            bucket = pad_bucket(batch_size, prog.batch_buckets)
            self.metrics.record_histogram("app_tpu_batch_wait_duration",
                                          oldest_wait, program=prog.name)
            self.metrics.set_gauge("app_tpu_batch_fill", batch_size / bucket,
                                   program=prog.name)
        return hook

    def _expired_counter(self, program: str):
        def hook(n: int) -> None:
            if self.metrics is None:
                return
            for _ in range(n):
                self.metrics.increment_counter(
                    "app_tpu_expired_dropped_total", program=program)
        return hook

    # -- the batched device dispatch ----------------------------------------
    def _run_batch(self, prog: Program, items: list) -> list:
        t0 = time.monotonic()
        if prog.kind == "tokens":
            out = self._run_tokens(prog, items)
        else:
            out = self._run_fixed(prog, items)
        if self._tl is not None:
            self._tl.predict(t0, time.monotonic(), prog.name, len(items))
        if self.metrics is not None:
            self.metrics.record_histogram("app_tpu_device_execute_duration",
                                          time.monotonic() - t0, program=prog.name)
        return out

    def _run_tokens(self, prog: Program, items: list) -> list:
        lengths = [int(np.asarray(it).shape[0]) for it in items]
        Sb = pad_bucket(max(lengths), prog.seq_buckets)
        Bb = pad_bucket(len(items), prog.batch_buckets)
        tokens = np.zeros((Bb, Sb), np.int32)
        for i, it in enumerate(items):
            tokens[i, : lengths[i]] = np.asarray(it, np.int32)
        lens = np.zeros((Bb,), np.int32)
        lens[: len(items)] = lengths
        self._note_shape(prog, (Bb, Sb))
        out = prog._jitted(prog.params, jnp.asarray(tokens), jnp.asarray(lens))
        out = jax.device_get(out)
        return [jax.tree.map(lambda a: a[i], out) for i in range(len(items))]

    def _run_fixed(self, prog: Program, items: list) -> list:
        Bb = pad_bucket(len(items), prog.batch_buckets)
        pad = [items[-1]] * (Bb - len(items))
        batch = jax.tree.map(lambda *xs: np.stack(xs), *(list(items) + pad))
        self._note_shape(prog, (Bb,))
        out = prog._jitted(prog.params, batch)
        out = jax.device_get(out)
        return [jax.tree.map(lambda a: a[i], out) for i in range(len(items))]

    def _note_shape(self, prog: Program, shape: tuple) -> None:
        if shape not in prog._compiled_shapes:
            prog._compiled_shapes.add(shape)
            if self.logger is not None:
                self.logger.debug({"event": "tpu compile", "program": prog.name,
                                   "shape": list(shape)})

    # -- public API (ctx.tpu.predict) ---------------------------------------
    def predict(self, program: str, item: Any, timeout: float | None = 60.0,
                deadline=None, slo_class: str | None = None) -> Any:
        """Run one item through a registered program, coalescing with any
        concurrent callers. Returns the un-batched result (numpy).

        ``deadline`` (resilience.Deadline) defaults to the AMBIENT one
        the transport opened from the request's wire deadline
        (grpc-timeout / X-Request-Timeout): the wait is capped to the
        remaining budget and the item is dropped unexecuted if it
        expires while queued. An admission gate, when configured, sheds
        with ``TooManyRequests`` before the item ever joins the line.
        ``slo_class`` defaults to the transport's ambient class; the
        gate degrades throughput-class first, and with a class policy
        configured the batcher schedules the classes separately."""
        if self._closed:
            raise ServiceUnavailable("TPU engine is closed")
        batcher = self._batchers.get(program)
        if batcher is None:
            raise ProgramNotFound(program, list(self._programs))
        if deadline is None:
            deadline = current_deadline()
        if slo_class is None:
            slo_class = current_slo_class()
        if deadline is not None and deadline.expired():
            if self.metrics is not None:
                self.metrics.increment_counter(
                    "app_tpu_expired_dropped_total", program=program)
            raise DeadlineExceeded(
                f"deadline expired before predict({program!r}) was queued")
        gate = self._gate_for(program)
        from .. import tracing

        span = tracing.current_span()
        trace_id = span.trace_id if span else ""
        tenant_spec = None
        if self.tenancy is not None:
            # same edge contract as generate(): resolve the ambient
            # tenant, apply its class default, consume its quota for
            # the duration of the call
            from ..tenancy.registry import current_tenant

            tenant_spec = self.tenancy.resolve(current_tenant())
            slo_class = self.tenancy.effective_class(tenant_spec, slo_class)
            try:
                self.tenancy.admit(tenant_spec, program=program,
                                   slo_class=slo_class, gate=gate)
            except BaseException:
                if self._tl is not None:
                    self._tl.shed(program, slo_class, trace_id)
                raise
        try:
            if gate is not None:
                try:
                    gate.admit(batcher.queue_depth(), program=program,
                               slo_class=slo_class,
                               tenant=tenant_spec.tenant_id
                               if tenant_spec is not None else "")
                except BaseException:
                    if self._tl is not None:
                        self._tl.shed(program, slo_class, trace_id)
                    raise
            self._validate_item(self._programs[program], item)
        except BaseException:
            if tenant_spec is not None:
                self.tenancy.release(tenant_spec.tenant_id)
            raise
        t0 = time.monotonic()
        entry = None
        if self.observe is not None:
            entry = self.observe.requests.add(
                "predict", program, trace_id, stage="batch-wait")
        failed = None
        try:
            return batcher.submit(item, timeout=timeout, deadline=deadline,
                                  slo_class=slo_class)
        except BaseException as e:
            failed = e
            raise
        finally:
            if tenant_spec is not None:
                self.tenancy.release(tenant_spec.tenant_id)
            dur = time.monotonic() - t0
            if self.observe is not None:
                self.observe.requests.remove(entry)
                if failed is not None:
                    # no request_id: that field is the generation-stream
                    # counter's namespace; a registry-entry id here would
                    # collide with it on /debug/events filters
                    self.observe.recorder.record(
                        "predict_failed",
                        trace_id=entry.trace_id, program=program,
                        duration_s=round(dur, 6), error=repr(failed))
            if self.metrics is not None:
                self.metrics.increment_counter("app_tpu_requests_total",
                                               program=program)
                self.metrics.record_histogram("app_tpu_predict_duration",
                                              dur, exemplar=trace_id or None,
                                              program=program)

    def predict_batch(self, program: str, items: list) -> list:
        """Direct batched execution, bypassing the coalescing queue (for
        subscribers that already hold a natural batch)."""
        prog = self._programs.get(program)
        if prog is None:
            raise ProgramNotFound(program)
        for it in items:
            self._validate_item(prog, it)
        out = []
        for i in range(0, len(items), prog.max_batch):
            out.extend(self._run_batch(prog, items[i : i + prog.max_batch]))
        if self.metrics is not None:
            for _ in items:  # one request per ITEM (the unit predict counts)
                self.metrics.increment_counter("app_tpu_requests_total",
                                               program=program)
        return out

    def _validate_item(self, prog: Program, item: Any) -> None:
        """Reject oversized inputs BEFORE they join a coalesced batch — a
        bad item inside the runner would fail every innocent request
        dispatched with it."""
        if prog.kind == "tokens":
            n = int(np.asarray(item).shape[0])
            limit = prog.seq_buckets[-1]
            if n == 0 or n > limit:
                raise ValueError(
                    f"program {prog.name!r}: item length {n} outside (0, {limit}]")
        elif prog.example_item is not None:
            want = jax.tree.map(lambda a: np.shape(a), prog.example_item)
            got = jax.tree.map(lambda a: np.shape(a), item)
            if want != got:
                raise ValueError(
                    f"program {prog.name!r}: item shapes {got} != expected {want}")

    def generate(self, *args, **kw):
        """Streaming token generation (decoder models). See
        ``generator.GenerationEngine.generate``. On a prefill-role
        worker (``TPU_SERVING_ROLE=prefill``) this routes through the
        P/D coordinator: local prefill-only compute, KV shipped to the
        decode pool, tokens relayed back — same signature, same
        ambient deadline/SLO pickup, the handler never knows. The
        durable-streams params (``seed``, ``continue_from``) pass
        through on both paths, so a resumed continuation admits
        identically on fused, prefill and decode workers."""
        if self.pd_prefill is not None:
            return self.pd_prefill.generate(*args, **kw)
        if self.generator is None:
            raise ServiceUnavailable(
                "no decoder model configured (TPU_MODEL must be a "
                "llama-family model for generate)")
        return self.generator.generate(*args, **kw)

    # -- warmup (compile-cache priming; BASELINE TTFT target needs this) -----
    def warmup(self, program: str | None = None) -> None:
        names = [program] if program else list(self._programs)
        for name in names:
            prog = self._programs[name]
            if prog.kind == "tokens":
                for Bb in prog.batch_buckets:
                    for Sb in prog.seq_buckets:
                        toks = jnp.zeros((Bb, Sb), jnp.int32)
                        lens = jnp.full((Bb,), Sb, jnp.int32)
                        jax.block_until_ready(prog._jitted(prog.params, toks, lens))
                        self._note_shape(prog, (Bb, Sb))
            elif prog.example_item is not None:
                for Bb in prog.batch_buckets:
                    batch = jax.tree.map(
                        lambda a: jnp.broadcast_to(jnp.asarray(a)[None], (Bb,) + np.shape(a)),
                        prog.example_item)
                    jax.block_until_ready(prog._jitted(prog.params, batch))
                    self._note_shape(prog, (Bb,))
            elif self.logger is not None:
                self.logger.warn({"event": "tpu warmup skipped",
                                  "program": name,
                                  "reason": "fixed-kind program registered "
                                            "without example_item"})
        if self.generator is not None:
            self.generator.warmup()

    # -- health (reference container/health.go:5-25 shape) -------------------
    def health_check(self) -> Health:
        details: dict[str, Any] = {
            "platform": self.platform,
            "device_kind": self.device_kind,
            "devices": len(self.devices),
            "model": self.model_name,
            "programs": {
                n: {"kind": p.kind,
                    "batch_buckets": list(p.batch_buckets),
                    "compiled_shapes": sorted(map(list, p._compiled_shapes))}
                for n, p in self._programs.items()
            },
        }
        if self._gates:
            details["admission"] = {n: g.stats()
                                    for n, g in sorted(self._gates.items())}
        if self.mesh is not None:
            details["mesh"] = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        try:
            stats = self.devices[0].memory_stats()
            if stats:
                details["hbm_bytes_in_use"] = stats.get("bytes_in_use")
                details["hbm_bytes_limit"] = stats.get("bytes_limit")
        except Exception:
            pass
        # per-subsystem declared bytes (the hbm accounting registry —
        # what the backend's opaque bytes_in_use decomposes into)
        acct = hbm.live_bytes()
        if acct:
            details["device_memory"] = acct
        # the arbiter's budget/lease/reclaim summary (full lease table
        # on /debug/vars and tools/hbm_report.py)
        arb = hbm.arbiter_stats()
        if arb["budget_bytes"] or arb["leases"]:
            details["hbm_arbiter"] = {
                k: arb[k] for k in ("budget_bytes", "in_use_bytes",
                                    "headroom_bytes", "reclaims",
                                    "sheds", "oom_retries")}
            # per-shard break-out (mesh engines settle one lease entry
            # per device): in-use + headroom per chip, so a balancer
            # can see ONE hot shard before it becomes a shed storm
            for k in ("device_budget_bytes", "devices"):
                if k in arb:
                    details["hbm_arbiter"][k] = arb[k]
        if self.generator is not None:
            details["generator"] = self.generator.stats()
        if self.tenancy is not None:
            details["tenancy"] = self.tenancy.stats()
        if self.serving_role != "fused":
            # role-aware health (disaggregated-serving.md): a decode
            # worker reports its ingest listener, a prefill worker its
            # peer path — load balancers and the gateway read THIS to
            # know which pool a replica serves and whether the
            # cross-pool path is up
            details["serving_role"] = self.serving_role
            if self.pd_ingest is not None:
                details["pd"] = self.pd_ingest.stats()
            elif self.pd_prefill is not None:
                details["pd"] = self.pd_prefill.stats()
        if self._closed:
            return Health(STATUS_DOWN, details)
        if self.generator is not None and self.generator.down is not None:
            # device loop bricked (donated cache lost and unrecoverable)
            return Health(STATUS_DOWN, details)
        if self.pd_ingest is not None and not self.pd_ingest.stats()["listening"]:
            # a decode worker that cannot accept KV is not serving its
            # role, whatever its local engine thinks
            return Health(STATUS_DOWN, details)
        # A live engine with no programs can't serve yet.
        status = STATUS_UP if (self._programs or self.generator) else STATUS_DEGRADED
        if self.pd_prefill is not None and not self.pd_prefill.connected:
            # prefill worker with no decode path: still alive (it can
            # prefill, reconnect is armed) but degraded — readiness
            # surfaces let the balancer prefer connected replicas
            status = STATUS_DEGRADED
        return Health(status, details)

    def close(self) -> None:
        self._closed = True
        # PD halves first: the ingest listener stops accepting and the
        # coordinator fails its relays typed BEFORE the generator they
        # feed shuts down
        if self.pd_ingest is not None:
            self.pd_ingest.close()
        if self.pd_prefill is not None:
            self.pd_prefill.close()
        for b in self._batchers.values():
            b.close(drain=False)
        if self.generator is not None:
            self.generator.close()
