"""CacheManager: one facade over the three-tier prefix-KV hierarchy.

The engine's serving loop talks ONLY to this class (plus its own
jitted row copies — device memory stays engine-owned):

  match()  -> best Match across T0 (HBM rows), T1 (host DRAM), T2
              (Redis). Pure w.r.t. counters: the engine decides whether
              the match is USABLE (long enough, on the chunk lattice)
              and reports back via accept()/reject(), preserving the
              flat index's stats contract.
  store()  -> claim a T0 row for a fresh prefix; hands back the LRU
              victim so the engine can spill its row to T1 first.
  offload()/store_shared() -> the T1 spill and T2 write-through.
  clear_device() -> recovery phase: T0 entries die with the pool, T1
              and T2 survive (the whole point of the hierarchy).
  invalidate_adapter() -> LoRA hot-swap: all three tiers at once.

Tier precedence on lookup: longest match wins; ties go to the cheaper
restore (T0 row copy < T1 device_put < T2 network + device_put). T2 is
only consulted — and only wins — when it could beat the local tiers by
at least one full block: its hit pays MGET + host->device upload + a
pool-row promotion, never worth less than a block of saved prefill.
"""

from __future__ import annotations

import numpy as np

from .hbm import HBMTier
from .host import HostTier
from .quant import HostKV, KVLayout
from .radix import Entry
from .redis_tier import RedisTier


def clamp_restore_len(matched: int, prompt_len: int) -> int:
    """A full-prompt hit must restore at most ``prompt_len - 1``
    positions: the final position is always prefilled so the dispatch
    has logits to sample the first generated token from (the restore
    path copies KV, not logits). Pure so the edge is unit-testable."""
    return min(int(matched), prompt_len - 1)


class Match:
    """One lookup's winner. ``row`` for T0; ``hostkv``+``key`` for
    T1/T2 promotions; ``consulted`` drives per-tier miss counters."""

    __slots__ = ("tier", "entry", "matched_len", "row", "hostkv", "key",
                 "adapter", "consulted")

    def __init__(self, tier: str, matched_len: int, adapter: int,
                 entry: Entry | None = None, row: int | None = None,
                 hostkv: HostKV | None = None,
                 key: np.ndarray | None = None, consulted=()):
        self.tier = tier
        self.entry = entry
        self.matched_len = int(matched_len)
        self.row = row
        self.hostkv = hostkv
        self.key = key
        self.adapter = int(adapter)
        self.consulted = tuple(consulted)


class CacheManager:
    def __init__(self, slots: int, layout: KVLayout, *, block: int = 16,
                 host_bytes: int = 0, redis=None, redis_ttl_s: float = 300.0,
                 epoch_refresh_s: float = 5.0, fingerprint: str = "",
                 metrics=None, logger=None, shards: int = 1):
        self.block = max(1, int(block))
        self.layout = layout
        # tensor-parallel shard count (mesh engines): T1 stores the
        # engine's per-shard snapshots verbatim; T2 frames each shard
        # through the block codec under shard-suffixed keys (the
        # fingerprint carries the mesh shape so differently-sharded
        # replicas never exchange frames)
        self.shards = max(1, int(shards))
        self.t0 = HBMTier(slots, self.block)
        self.host = HostTier(host_bytes, self.block) if host_bytes > 0 \
            else None
        self.redis = RedisTier(redis, fingerprint, layout, self.block,
                               ttl_s=redis_ttl_s,
                               epoch_refresh_s=epoch_refresh_s,
                               logger=logger,
                               shards=self.shards) if redis is not None \
            else None
        self.metrics = metrics
        self.logger = logger
        # bumped on any mutation that can change a match verdict — the
        # engine memoizes per-request lattice peeks against it (same
        # contract as paged_llama.SharedPrefixIndex.version)
        self.version = 0
        self.hits = 0
        self.misses = 0
        self._tier_hits = {"t0": 0, "t1": 0, "t2": 0}
        self._tier_misses = {"t0": 0, "t1": 0, "t2": 0}
        # -- tenancy ledger (all empty/None until set_tenancy) --------------
        # shares CALLABLE, not a snapshot: the registry hot-reloads, so
        # budgets must be read at store time
        self._shares_fn = None
        self._row_bytes = 0
        self._owned: dict[str, dict[int, Entry]] = {}  # tenant -> eid -> e
        self._eid_owner: dict[int, str] = {}

    # -- tenancy -------------------------------------------------------------
    def set_tenancy(self, shares_fn, row_bytes: int = 0) -> None:
        """Install per-tenant T0 budgets. ``shares_fn() -> {tenant:
        share}`` (fractions of the slot count); ``row_bytes`` sizes the
        ``app_tpu_tenant_cache_bytes`` gauge. Tenants without a share
        are unbudgeted; untagged entries stay plain global LRU."""
        self._shares_fn = shares_fn
        self._row_bytes = max(0, int(row_bytes))

    def _shares(self) -> dict:
        if self._shares_fn is None:
            return {}
        try:
            return self._shares_fn() or {}
        except Exception:
            return {}

    def tenant_budget(self, tenant: str) -> int | None:
        """This tenant's T0 row budget (None = unbudgeted)."""
        share = self._shares().get(tenant, 0.0)
        if share <= 0:
            return None
        return max(1, int(share * self.t0.slots))

    def tenant_rows(self) -> dict[str, int]:
        return {tid: len(d) for tid, d in self._owned.items()}

    def _prefer_eids(self, tenant) -> set | None:
        """Entry ids to victimize first: every budgeted tenant already
        OVER its share, plus the storing tenant once it is AT its share
        (the incoming row would push it over) — so the over-budget
        tenant eats its own eviction before anyone else's warm block
        goes cold."""
        shares = self._shares()
        if not shares:
            return None
        prefer: set | None = None
        for tid, owned in self._owned.items():
            share = shares.get(tid, 0.0)
            if share <= 0 or not owned:
                continue
            budget = max(1, int(share * self.t0.slots))
            rows = len(owned)
            if rows > budget or (tid == tenant and rows >= budget):
                if prefer is None:
                    prefer = set()
                prefer.update(owned)
        return prefer

    def _ledger_remove(self, entry: Entry) -> None:
        tid = self._eid_owner.pop(entry.eid, None)
        if tid is None:
            return
        owned = self._owned.get(tid)
        if owned is None:
            return
        owned.pop(entry.eid, None)
        if not owned:
            self._owned.pop(tid, None)  # no empty rows in tenant_rows()

    def evict_tenant(self, tenant: str, rows: int | None = None
                     ) -> list[Entry]:
        """Targeted per-tenant reclaim: evict ``rows`` of the tenant's
        T0 entries LRU-first (default: enough to get back under its
        budget). Returns the victims — unindexed, payloads intact — so
        the engine can spill each row to the host tier exactly like a
        store-path victim. Other tenants' entries are untouched."""
        owned = self._owned.get(tenant)
        if not owned:
            return []
        if rows is None:
            budget = self.tenant_budget(tenant)
            if budget is None:
                return []
            rows = len(owned) - budget
        if rows <= 0:
            return []
        victims = []
        for e in sorted(owned.values(), key=lambda e: e.tick)[:rows]:
            if self.t0.evict(e):
                self._ledger_remove(e)
                victims.append(e)
                self._count("app_tpu_kvcache_evictions_total", "t0",
                            tenant=tenant)
        if victims:
            self.version += 1
            self._gauges()
        return victims

    # -- engine-facing surface ----------------------------------------------
    def __len__(self) -> int:
        return len(self.t0)

    @property
    def slots(self) -> int:
        return self.t0.slots

    @property
    def wants_offload(self) -> bool:
        return self.host is not None

    @property
    def shares(self) -> bool:
        return self.redis is not None

    def match(self, prompt: np.ndarray, adapter: int = 0) -> Match | None:
        """Best match across enabled tiers; None when no tier has a
        single usable token. No counter/LRU side effects — report the
        engine's verdict via accept()/reject()."""
        prompt = np.asarray(prompt, np.int32)
        consulted = ["t0"]
        e0, m0 = self.t0.match(prompt, adapter)
        best = Match("t0", m0, adapter, entry=e0, row=e0.row,
                     key=e0.key) if e0 is not None else None
        if self.host is not None:
            consulted.append("t1")
            e1, m1 = self.host.match(prompt, adapter)
            if e1 is not None and m1 > (best.matched_len if best else 0):
                best = Match("t1", m1, adapter, entry=e1,
                             hostkv=e1.payload, key=e1.key)
        if self.redis is not None and self.redis.available:
            # the shared tier costs a network round trip and its hit
            # pays MGET + host->device upload + a pool-row promotion:
            # consult it only when it could beat the local tiers by at
            # least one FULL block (and not at all inside the
            # post-error backoff window) — winning by a token or two
            # would trade an HBM row copy for a multi-MB fetch to save
            # less than one block of prefill
            full = (len(prompt) // self.block) * self.block
            local = best.matched_len if best else 0
            if local + self.block <= full:
                consulted.append("t2")
                m2, kv2 = self.redis.match(prompt, adapter)
                if kv2 is not None and m2 >= local + self.block:
                    best = Match("t2", m2, adapter, hostkv=kv2,
                                 key=prompt[:m2].copy())
        if best is not None:
            best.consulted = tuple(consulted)
            return best
        return None

    def accept(self, match: Match, restore_s: float | None = None,
               tenant: str | None = None) -> None:
        """The engine restored this match: count the hit on the serving
        tier, a miss on every cheaper tier it had to fall through, and
        refresh the winning entry's LRU position. ``tenant`` labels the
        hit series on tenancy-enabled engines (None adds no label)."""
        self.hits += 1
        self._tier_hits[match.tier] += 1
        for tier in match.consulted:
            if tier != match.tier:
                self._tier_misses[tier] += 1
        if match.tier == "t0" and match.entry is not None:
            self.t0.touch(match.entry)
        elif match.tier == "t1" and match.entry is not None:
            self.host.touch(match.entry)
        self._count("app_tpu_kvcache_hits_total", match.tier,
                    tenant=tenant)
        for tier in match.consulted:
            if tier != match.tier:
                self._count("app_tpu_kvcache_misses_total", tier)
        if restore_s is not None and self.metrics is not None:
            try:
                self.metrics.record_histogram(
                    "app_tpu_kvcache_restore_duration", restore_s,
                    tier=match.tier)
            except Exception:
                pass

    def reject(self, match: Match | None = None,
               prompt: np.ndarray | None = None) -> None:
        """No usable match for this admission (nothing found, or the
        engine discarded it as too short / off the chunk lattice).
        Without a match, reconstruct which tiers match() consulted:
        T0 always, T1 when enabled, T2 only when the prompt had full
        blocks to look up — sub-block prompts never reach Redis and
        must not inflate its miss counter."""
        self.misses += 1
        if match is not None:
            consulted = match.consulted
        else:
            consulted = ["t0"]
            if self.host is not None:
                consulted.append("t1")
            if self.redis is not None and self.redis.available and (
                    prompt is None or len(prompt) >= self.block):
                consulted.append("t2")
        for tier in consulted:
            self._tier_misses[tier] += 1
            self._count("app_tpu_kvcache_misses_total", tier)

    def covered(self, prompt: np.ndarray, adapter: int = 0) -> bool:
        return self.t0.covered(np.asarray(prompt, np.int32), adapter)

    def store(self, key: np.ndarray, adapter: int = 0,
              tenant: str | None = None) -> tuple[int, Entry | None]:
        """Claim a T0 row (see HBMTier.store). The caller spills the
        returned victim's row via offload() BEFORE overwriting it.
        ``tenant`` charges the row to that tenant's cache budget: once
        a budgeted tenant is at/over its share, ITS blocks become the
        preferred eviction victims (LRU within the tenant)."""
        self.version += 1
        row, victim = self.t0.store(np.asarray(key, np.int32), adapter,
                                    prefer=self._prefer_eids(tenant))
        if victim is not None:
            self._ledger_remove(victim)
            self._count("app_tpu_kvcache_evictions_total", "t0")
        if tenant:
            entry = self.t0.entry_at(row)
            if entry is not None:
                self._eid_owner[entry.eid] = tenant
                self._owned.setdefault(tenant, {})[entry.eid] = entry
        self._gauges()
        return row, victim

    def offload(self, victim: Entry, kv: HostKV) -> bool:
        """Spill an evicted T0 entry's row into the host tier."""
        if self.host is None:
            return False
        before = self.host.evictions
        ok = self.host.put(victim.key, victim.adapter, kv)
        for _ in range(self.host.evictions - before):
            self._count("app_tpu_kvcache_evictions_total", "t1")
        if ok:
            self.version += 1
        self._gauges()
        return ok

    def store_shared(self, key: np.ndarray, adapter: int,
                     kv: HostKV) -> int:
        """Write-through the new prefix's full blocks to Redis."""
        if self.redis is None:
            return 0
        return self.redis.put(np.asarray(key, np.int32), adapter, kv)

    def shrink(self, new_slots: int) -> int:
        """HBM-arbiter reclaim: resize T0 to ``new_slots`` rows,
        dropping every entry (the caller — the engine's pool-reclaim
        callback — spills each entry's row to the host tier first,
        then reallocates the pool itself at the new size). The version
        bump drops memoized match verdicts that referenced dead rows;
        future hits rewarm from T1/T2 exactly like post-recovery."""
        self.version += 1
        n = self.t0.resize(new_slots)
        self._ledger_clear()
        self._gauges()
        return n

    def clear_device(self) -> int:
        """Recovery: the pool was reallocated, so T0 entries point at
        zeroed rows — drop them. T1 snapshots and T2 blocks are device-
        independent and SURVIVE: the next admission rewarns the fresh
        pool from them instead of paying a full prefill."""
        self.version += 1
        n = self.t0.clear()
        self._ledger_clear()
        self._gauges()
        return n

    def rekey(self, fingerprint: str, shards: int) -> None:
        """Mesh re-placement changed the shard layout (device-loss
        recovery onto a smaller tp): T1 survives as-is (its payloads
        assemble to the canonical dense row at promotion), but T2's
        per-shard frames must re-namespace — see RedisTier.rekey."""
        self.version += 1
        self.shards = max(1, int(shards))
        if self.redis is not None:
            self.redis.rekey(fingerprint, self.shards)

    def invalidate_adapter(self, adapter: int) -> dict:
        """LoRA hot-swap: stored KV was computed through the OLD wk/wv
        — every tier must drop the adapter's entries (T2 by epoch bump,
        which invalidates OTHER replicas' reads of this adapter too)."""
        self.version += 1
        for owned in self._owned.values():
            for e in [e for e in owned.values()
                      if e.adapter == int(adapter)]:
                owned.pop(e.eid, None)
                self._eid_owner.pop(e.eid, None)
        out = {"t0": self.t0.invalidate_adapter(adapter)}
        if self.host is not None:
            out["t1"] = self.host.invalidate_adapter(adapter)
        if self.redis is not None:
            self.redis.invalidate_adapter(adapter)
            out["t2"] = "epoch_bumped"
        self._gauges()
        return out

    def _ledger_clear(self) -> None:
        # keep tenant keys with empty row maps: their cache-bytes
        # gauges must report 0, not go stale at the last value
        for owned in self._owned.values():
            owned.clear()
        self._eid_owner.clear()

    # -- observability -------------------------------------------------------
    def _count(self, name: str, tier: str,
               tenant: str | None = None) -> None:
        if self.metrics is not None:
            try:
                if tenant:
                    self.metrics.increment_counter(name, tier=tier,
                                                   tenant=tenant)
                else:
                    self.metrics.increment_counter(name, tier=tier)
            except Exception:
                pass

    def _gauges(self) -> None:
        if self.metrics is None:
            return
        try:
            self.metrics.set_gauge("app_tpu_kvcache_entries",
                                   float(len(self.t0)), tier="t0")
            if self.host is not None:
                self.metrics.set_gauge("app_tpu_kvcache_entries",
                                       float(len(self.host)), tier="t1")
                self.metrics.set_gauge("app_tpu_kvcache_bytes",
                                       float(self.host.bytes), tier="t1")
            if self._shares_fn is not None:
                for tid, owned in self._owned.items():
                    self.metrics.set_gauge(
                        "app_tpu_tenant_cache_bytes",
                        float(len(owned) * self._row_bytes), tenant=tid)
        except Exception:
            pass

    def stats(self) -> dict:
        """Top-level keys keep the flat index's contract (slots/entries/
        hits/misses are what tests and dashboards already read); tier
        detail nests under ``tiers``."""
        lookups = self.hits + self.misses
        out = {
            "slots": self.t0.slots,
            "entries": len(self.t0),
            "hits": self.hits,
            "misses": self.misses,
            "hit_ratio": round(self.hits / lookups, 4) if lookups else None,
            "block": self.block,
            "tiers": {
                "t0": {**self.t0.stats(), "hits": self._tier_hits["t0"],
                       "misses": self._tier_misses["t0"]},
            },
        }
        if self.host is not None:
            out["tiers"]["t1"] = {**self.host.stats(),
                                  "hits": self._tier_hits["t1"],
                                  "misses": self._tier_misses["t1"]}
        if self.redis is not None:
            out["tiers"]["t2"] = {**self.redis.stats(),
                                  "hits": self._tier_hits["t2"],
                                  "misses": self._tier_misses["t2"]}
        if self._shares_fn is not None:
            out["tenants"] = {
                tid: {"rows": len(owned),
                      "budget": self.tenant_budget(tid)}
                for tid, owned in self._owned.items()}
        return out
