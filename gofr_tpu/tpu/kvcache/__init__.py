"""Hierarchical prefix-KV cache: radix index + host offload + Redis.

The subsystem behind ``GenerationEngine``'s prefix reuse (the engine
owns device memory and every jitted copy; this package owns indexing,
host snapshots, and the shared tier):

  T0  HBM pool rows, block-hash radix indexed    (hbm.HBMTier)
  T1  host-DRAM spill of LRU-evicted rows        (host.HostTier)
  T2  Redis-shared int8 blocks across replicas   (redis_tier.RedisTier)

behind one facade (manager.CacheManager). See
docs/advanced-guide/kv-cache.md for the tier diagram and deployment
notes, and tools/kvcache_bench.py for the hit-vs-miss TTFT numbers.

Config (read by ``new_engine_from_config`` via options_from_config):

  TPU_KVCACHE_BLOCK        radix block size in tokens (default 16)
  TPU_KVCACHE_HOST_MB      T1 host-DRAM budget in MiB (default 0 = off)
  TPU_KVCACHE_REDIS        "true" enables the shared tier over the
                           framework Redis client (REDIS_HOST/PORT)
  TPU_KVCACHE_REDIS_TTL_S  shared-block TTL seconds (default 300)
  TPU_KVCACHE_REDIS_TIMEOUT_S  shared-tier socket timeout (default 0.25)
  TPU_KVCACHE_EPOCH_REFRESH_S  adapter-epoch staleness bound (default 5)
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Any

from .hbm import HBMTier
from .host import HostTier
from .manager import CacheManager, Match, clamp_restore_len
from .quant import (HostKV, KVLayout, ShardedHostKV, decode_block,
                    dense_hostkv, encode_block)
from .radix import Entry, RadixIndex, chain_hashes, first_block_hash
from .redis_tier import RedisTier

__all__ = [
    "CacheManager", "Match", "clamp_restore_len",
    "HBMTier", "HostTier", "RedisTier",
    "HostKV", "KVLayout", "ShardedHostKV", "dense_hostkv",
    "encode_block", "decode_block",
    "Entry", "RadixIndex", "chain_hashes", "first_block_hash",
    "KVCacheOptions", "options_from_config", "model_fingerprint",
]


@dataclass
class KVCacheOptions:
    """Tier wiring handed to the engine. ``redis`` is a live
    RedisClient (or anything with get/mget/set/incr/pipeline/close) —
    the engine takes ownership and closes it on engine.close() (or
    immediately when a mesh engine discards the offload tiers); None
    keeps the shared tier off."""

    block: int = 16
    host_mb: int = 0
    redis: Any = None
    redis_ttl_s: float = 300.0
    epoch_refresh_s: float = 5.0


def options_from_config(cfg, logger=None, metrics=None) -> KVCacheOptions:
    """TPU_KVCACHE_* -> options. The Redis tier is built on the
    framework's own datasource client and degrades gracefully: an
    unreachable Redis logs once and leaves the tier off (reference
    container style — a down datasource never blocks startup)."""
    redis = None
    if cfg.get_bool("TPU_KVCACHE_REDIS"):
        try:
            from ...datasource.redisclient import RedisClient

            # a DEDICATED short socket timeout, not the datasource
            # default 5 s: T2 consults run on the serving-loop thread,
            # and a merely-degraded Redis must trip the tier's
            # fail-open error path instead of freezing every active
            # decode stream for seconds per lookup
            redis = RedisClient(
                host=cfg.get_or_default("REDIS_HOST", "localhost"),
                port=cfg.get_int("REDIS_PORT", 6379),
                logger=logger, metrics=metrics,
                timeout=cfg.get_float("TPU_KVCACHE_REDIS_TIMEOUT_S", 0.25))
        except Exception as e:  # noqa: BLE001 — degrade, don't block boot
            if logger is not None:
                logger.warn({"event": "kvcache redis tier disabled "
                             "(connect failed)", "error": repr(e)})
    return KVCacheOptions(
        block=cfg.get_int("TPU_KVCACHE_BLOCK", 16),
        host_mb=cfg.get_int("TPU_KVCACHE_HOST_MB", 0),
        redis=redis,
        redis_ttl_s=cfg.get_float("TPU_KVCACHE_REDIS_TTL_S", 300.0),
        epoch_refresh_s=cfg.get_float("TPU_KVCACHE_EPOCH_REFRESH_S", 5.0))


def model_fingerprint(cfg, params=None, extra: str = "") -> str:
    """Short stable id for (architecture, weights, cache dtype): the T2
    key prefix that keeps replicas with different models from ever
    exchanging KV. Weights contribute tiny deterministic samples from
    leaves spread ACROSS the tree — one leaf is not enough (fine-tunes
    often share a frozen/tied embedding table, typically first in tree
    order) — still without hashing gigabytes; on any failure the
    config-only hash still isolates architectures."""
    h = hashlib.sha256()
    h.update(repr((cfg.name, cfg.vocab_size, cfg.dim, cfg.n_layers,
                   cfg.n_heads, cfg.n_kv_heads, cfg.head_dim,
                   cfg.rope_theta)).encode())
    h.update(extra.encode())
    if params is not None:
        try:
            import jax
            import numpy as np

            leaves = jax.tree_util.tree_leaves(params)
            picks = sorted({0, len(leaves) // 3, (2 * len(leaves)) // 3,
                            len(leaves) - 1})
            # ONE batched transfer for all sampled leaves (device_get
            # takes a pytree) — per-leaf gets would sync the host once
            # per pick
            samples = jax.device_get(
                [leaves[i].reshape(-1)[:8] for i in picks])
            for sample in samples:
                h.update(np.asarray(sample).astype(np.float32).tobytes())
        except Exception:
            pass
    return h.hexdigest()[:16]
