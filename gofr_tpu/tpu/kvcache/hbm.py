"""T0: the HBM tier — radix-indexed bookkeeping for the device pool.

Same division of labor as the flat PrefixIndex it supersedes: the
ENGINE owns the device pool rows and every jitted copy; this class is
the host-side map from token prefixes to rows, now behind the
block-hash radix tree instead of an O(rows x len) scan. A hit costs
one HBM row copy on device; entries are LRU-evicted on store, and the
evicted entry is handed BACK to the caller so the engine can spill the
row's KV to the host tier before the pool row is overwritten.
"""

from __future__ import annotations

import numpy as np

from .radix import Entry, RadixIndex


class HBMTier:
    tier = "t0"

    def __init__(self, slots: int, block: int = 16):
        self.slots = int(slots)
        self.index = RadixIndex(block)
        self._rows: list[Entry | None] = [None] * self.slots
        self._tick = 0
        self.evictions = 0

    def __len__(self) -> int:
        return sum(1 for e in self._rows if e is not None)

    def match(self, prompt: np.ndarray, adapter: int = 0
              ) -> tuple[Entry | None, int]:
        """PURE longest-prefix lookup (see RadixIndex.match); the
        manager reports usability via touch() on accept."""
        return self.index.match(prompt, adapter)

    def touch(self, entry: Entry) -> None:
        self._tick += 1
        entry.tick = self._tick

    def covered(self, prompt: np.ndarray, adapter: int = 0) -> bool:
        """True when a stored entry already contains ``prompt`` as a
        prefix — storing it again would only duplicate a row."""
        _, m = self.index.match(prompt, adapter)
        return m >= len(prompt)

    def store(self, key: np.ndarray, adapter: int = 0,
              prefer=None) -> tuple[int, Entry | None]:
        """Claim a row for a new entry: a free row, else the LRU
        victim's. Returns (row, victim) with the victim ALREADY
        unindexed but its key/payload intact — the caller must read the
        victim's pool row (for host-tier spill) BEFORE dispatching the
        store that overwrites it.

        ``prefer``: optional set of entry ids (``Entry.eid``) to
        victimize FIRST — the cache manager passes the over-budget
        tenants' entries here so a tenant past its share evicts its own
        blocks before touching anyone else's. LRU order applies within
        the preferred set; an empty/absent set is plain global LRU."""
        victim = None
        row = next((i for i, e in enumerate(self._rows) if e is None), None)
        if row is None:
            candidates = None
            if prefer:
                candidates = [i for i in range(self.slots)
                              if self._rows[i].eid in prefer]
            if not candidates:
                candidates = range(self.slots)
            row = min(candidates, key=lambda i: self._rows[i].tick)
            victim = self._rows[row]
            self.index.remove(victim)
            self.evictions += 1
        entry = Entry(key, adapter, payload=row)
        self.index.insert(entry)
        self._rows[row] = entry
        self.touch(entry)
        return row, victim

    def entry_at(self, row: int) -> Entry | None:
        return self._rows[row] if 0 <= row < self.slots else None

    def evict(self, entry: Entry) -> bool:
        """Targeted eviction: unindex ``entry`` and free its row (the
        caller spills the row's KV first, exactly like a store-path
        victim). Used by the per-tenant cache-quota reclaim."""
        row = entry.payload
        if not (0 <= row < self.slots) or self._rows[row] is not entry:
            return False
        self.index.remove(entry)
        self._rows[row] = None
        self.evictions += 1
        return True

    def entries(self) -> list[Entry]:
        """The live entries (arbitrary order) — the arbiter's pool
        shrink reads them to spill each entry's row to the host tier
        before the pool is reallocated smaller."""
        return [e for e in self._rows if e is not None]

    def clear(self) -> int:
        """Drop every entry — engine recovery calls this after
        reallocating the side pool (stored keys would otherwise match
        prompts against zeroed rows and restore all-zero KV)."""
        n = len(self)
        self.index.clear()
        self._rows = [None] * self.slots
        return n

    def resize(self, slots: int) -> int:
        """Shrink (or regrow) the row table to ``slots``, dropping
        EVERY entry — the HBM arbiter's reclaim path reallocates the
        pool itself, so surviving row indices would point into a dead
        buffer. Callers spill entries to the host tier first; returns
        the number dropped."""
        n = len(self)
        self.index.clear()
        self.slots = max(1, int(slots))
        self._rows = [None] * self.slots
        return n

    def invalidate_adapter(self, adapter: int) -> int:
        n = self.index.invalidate_adapter(adapter)
        for i, e in enumerate(self._rows):
            if e is not None and e.adapter == int(adapter):
                self._rows[i] = None
        return n

    def stats(self) -> dict:
        return {"slots": self.slots, "entries": len(self),
                "evictions": self.evictions}
