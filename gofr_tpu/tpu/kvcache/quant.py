"""Host-side KV snapshots and the int8 block codec for the Redis tier.

``HostKV`` is the host half of a cache row: contiguous numpy arrays in
the engine's cache-native layout ([L, plen, KV, hd] values, [L, plen,
KV] scale planes when the cache is int8-quantized). The T1 host tier
stores them verbatim — a T1 round trip is bit-exact by construction.

The T2 Redis tier serializes per BLOCK (the radix block size) so
replicas can share partial prefixes: each payload is a self-describing
frame of int8 values + float32 per-vector scales + a truncated sha256
checksum. int8-cache engines store their native planes (lossless round
trip); fp-cache engines quantize on write with the same per-vector
max-abs scheme the serving cache uses (ops.quant) and dequantize on
read — a documented precision trade for cross-replica reuse.
"""

from __future__ import annotations

import hashlib
import struct
from typing import NamedTuple

import numpy as np

_MAGIC = b"GKV1"
# magic, version, flags, L, T, KV, hd
_HEADER = struct.Struct("<4sBBHHHH")
_DIGEST_LEN = 16
FLAG_INT8_SRC = 1  # payload came off an int8 cache (round trip exact)


class KVLayout(NamedTuple):
    """The engine-side shape contract a decoded block must satisfy
    before its bytes are allowed anywhere near a pool row."""

    layers: int
    kv_heads: int
    head_dim: int
    quantized: bool        # serving cache is int8 + scale planes
    np_dtype: np.dtype     # cache value dtype (int8 / float32 / ...)
    max_seq: int


class HostKV(NamedTuple):
    k: np.ndarray                  # [L, plen, KV, hd] cache-native dtype
    v: np.ndarray
    k_scale: np.ndarray | None     # [L, plen, KV] f32 (int8 caches)
    v_scale: np.ndarray | None

    @property
    def plen(self) -> int:
        return int(self.k.shape[1])

    @property
    def nbytes(self) -> int:
        n = self.k.nbytes + self.v.nbytes
        if self.k_scale is not None:
            n += self.k_scale.nbytes + self.v_scale.nbytes
        return n

    def slice_tokens(self, start: int, stop: int) -> "HostKV":
        return HostKV(
            self.k[:, start:stop], self.v[:, start:stop],
            self.k_scale[:, start:stop] if self.k_scale is not None else None,
            self.v_scale[:, start:stop] if self.v_scale is not None else None)


class ShardedHostKV(NamedTuple):
    """A mesh engine's host snapshot of one cache row: one
    :class:`HostKV` per tensor-parallel shard, ordered by KV-head
    offset (part i holds heads [i*KV/n, (i+1)*KV/n)). The spill half
    reads each part straight off its device shard (no cross-device
    assembly on the spill path); the T2 tier frames each part through
    the UNCHANGED int8 block codec with the per-shard head count —
    which is why its namespace keys carry the mesh shape (a tp=4
    replica's frames must never decode on a tp=2 one). ``assemble()``
    is the restore-side canonicalization: promotion pads a DENSE row
    and lands it with one sharded write, so T1 snapshots survive even
    a mesh-SHAPE change across device-loss re-placement."""

    parts: tuple  # of HostKV, kv-head order

    @property
    def shards(self) -> int:
        return len(self.parts)

    @property
    def plen(self) -> int:
        return self.parts[0].plen if self.parts else 0

    @property
    def nbytes(self) -> int:
        return sum(p.nbytes for p in self.parts)

    def slice_tokens(self, start: int, stop: int) -> "ShardedHostKV":
        return ShardedHostKV(tuple(p.slice_tokens(start, stop)
                                   for p in self.parts))

    def assemble(self) -> HostKV:
        """Concatenate the shards back into one cache-native dense
        HostKV (KV-head axis) — the canonical layout every device
        write path consumes."""
        if len(self.parts) == 1:
            return self.parts[0]
        k = np.concatenate([p.k for p in self.parts], axis=2)
        v = np.concatenate([p.v for p in self.parts], axis=2)
        if self.parts[0].k_scale is not None:
            ks = np.concatenate([p.k_scale for p in self.parts], axis=2)
            vs = np.concatenate([p.v_scale for p in self.parts], axis=2)
        else:
            ks = vs = None
        return HostKV(k, v, ks, vs)


def dense_hostkv(kv: "HostKV | ShardedHostKV") -> HostKV:
    """Canonical dense view of either host-snapshot flavor — what the
    promote/ingest write paths (and shape validation) consume."""
    return kv.assemble() if isinstance(kv, ShardedHostKV) else kv


def _quantize(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Per-vector max-abs int8: scale [..., KV] over the head dim."""
    x32 = np.asarray(x, np.float32)
    scale = np.max(np.abs(x32), axis=-1) / 127.0
    scale = np.maximum(scale, 1e-12)
    i8 = np.clip(np.rint(x32 / scale[..., None]), -127, 127).astype(np.int8)
    return i8, scale.astype(np.float32)


def encode_block(kv: HostKV) -> bytes:
    """One radix block's KV -> a checksummed wire frame."""
    L, T, KV, hd = kv.k.shape
    if kv.k.dtype == np.int8:
        flags = FLAG_INT8_SRC
        k8, v8 = np.ascontiguousarray(kv.k), np.ascontiguousarray(kv.v)
        ks = np.ascontiguousarray(kv.k_scale, dtype=np.float32)
        vs = np.ascontiguousarray(kv.v_scale, dtype=np.float32)
    else:
        flags = 0
        k8, ks = _quantize(kv.k)
        v8, vs = _quantize(kv.v)
    body = _HEADER.pack(_MAGIC, 1, flags, L, T, KV, hd) \
        + k8.tobytes() + v8.tobytes() + ks.tobytes() + vs.tobytes()
    return body + hashlib.sha256(body).digest()[:_DIGEST_LEN]


def decode_block(data: bytes, layout: KVLayout) -> HostKV | None:
    """Wire frame -> HostKV in the layout's cache-native dtype, or None
    for anything malformed: wrong magic/version, shape not matching
    this engine's layout, bad checksum, truncated payload. A None is a
    cache miss, never an error — shared-tier bytes are untrusted input."""
    if data is None or len(data) < _HEADER.size + _DIGEST_LEN:
        return None
    body, digest = data[:-_DIGEST_LEN], data[-_DIGEST_LEN:]
    if hashlib.sha256(body).digest()[:_DIGEST_LEN] != digest:
        return None
    magic, version, flags, L, T, KV, hd = _HEADER.unpack_from(body)
    if magic != _MAGIC or version != 1:
        return None
    if (L, KV, hd) != (layout.layers, layout.kv_heads, layout.head_dim) \
            or T <= 0:
        return None
    nval = L * T * KV * hd
    nsc = L * T * KV
    want = _HEADER.size + 2 * nval + 2 * nsc * 4
    if len(body) != want:
        return None
    off = _HEADER.size
    k8 = np.frombuffer(body, np.int8, nval, off).reshape(L, T, KV, hd)
    off += nval
    v8 = np.frombuffer(body, np.int8, nval, off).reshape(L, T, KV, hd)
    off += nval
    ks = np.frombuffer(body, np.float32, nsc, off).reshape(L, T, KV)
    off += nsc * 4
    vs = np.frombuffer(body, np.float32, nsc, off).reshape(L, T, KV)
    if layout.quantized:
        return HostKV(k8.copy(), v8.copy(), ks.copy(), vs.copy())
    k = (k8.astype(np.float32) * ks[..., None]).astype(layout.np_dtype)
    v = (v8.astype(np.float32) * vs[..., None]).astype(layout.np_dtype)
    return HostKV(k, v, None, None)


def concat_blocks(blocks: list[HostKV]) -> HostKV:
    """Consecutive decoded blocks -> one HostKV along the token axis."""
    k = np.concatenate([b.k for b in blocks], axis=1)
    v = np.concatenate([b.v for b in blocks], axis=1)
    if blocks[0].k_scale is not None:
        ks = np.concatenate([b.k_scale for b in blocks], axis=1)
        vs = np.concatenate([b.v_scale for b in blocks], axis=1)
    else:
        ks = vs = None
    return HostKV(k, v, ks, vs)
