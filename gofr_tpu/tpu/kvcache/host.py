"""T1: the host-DRAM offload tier.

Effective prefix-cache capacity stops being bounded by HBM pool rows:
when T0 evicts an entry, the engine ``device_get``s the victim's pool
row into page-locked-equivalent numpy arrays (one contiguous slab per
plane — the layout ``device_put`` restores without repacking) and
parks it here under a byte budget. A T1 hit is promoted back into a
pool row (host -> device transfer + the usual row copy), which still
beats recomputing the prefix through the MXU by a wide margin — and,
unlike T0, this tier SURVIVES device loss: engine recovery clears T0
(its rows point into a reallocated pool) while T1 rewarms the fresh
pool without a single prefill dispatch.
"""

from __future__ import annotations

import numpy as np

from .quant import HostKV
from .radix import Entry, RadixIndex


class HostTier:
    tier = "t1"

    def __init__(self, max_bytes: int, block: int = 16):
        self.max_bytes = int(max_bytes)
        self.index = RadixIndex(block)
        self._entries: dict[int, Entry] = {}
        self._tick = 0
        self.bytes = 0
        self.evictions = 0
        # successful spills accepted from T0 (LRU eviction AND the
        # arbiter's pool shrink both land here): the counter that
        # shows "shrink T0 toward the host tier" actually moved KV
        # down a tier instead of dropping it
        self.spills = 0

    def __len__(self) -> int:
        return len(self._entries)

    def match(self, prompt: np.ndarray, adapter: int = 0
              ) -> tuple[Entry | None, int]:
        return self.index.match(prompt, adapter)

    def touch(self, entry: Entry) -> None:
        self._tick += 1
        entry.tick = self._tick

    def put(self, key: np.ndarray, adapter: int, kv: HostKV) -> bool:
        """Park a spilled row. Skips entries a stored one already
        covers (duplicate bytes for no extra match length), drops
        stored entries the NEW key strictly covers (every probe they
        can serve the superset serves at least as well — under the
        growing-prefix multi-turn workload each turn would otherwise
        leave the previous turn's snapshot burning budget), and skips
        entries larger than the whole budget; evicts LRU until it
        fits."""
        need = kv.nbytes
        if need > self.max_bytes:
            return False
        _, m = self.index.match(key, adapter)
        if m >= len(key):
            return False
        adapter = int(adapter)
        for e in [e for e in self._entries.values()
                  if e.adapter == adapter and len(e.key) < len(key)
                  and np.array_equal(e.key, key[:len(e.key)])]:
            self._drop(e)  # dominated, not pressure: no eviction count
        while self.bytes + need > self.max_bytes and self._entries:
            self._evict_lru()
        entry = Entry(key, adapter, payload=kv)
        self.index.insert(entry)
        self._entries[entry.eid] = entry
        self.bytes += need
        self.spills += 1
        self.touch(entry)
        return True

    def _evict_lru(self) -> None:
        victim = min(self._entries.values(), key=lambda e: e.tick)
        self._drop(victim)
        self.evictions += 1

    def _drop(self, entry: Entry) -> None:
        self.index.remove(entry)
        self._entries.pop(entry.eid, None)
        self.bytes -= entry.payload.nbytes

    def invalidate_adapter(self, adapter: int) -> int:
        n = self.index.invalidate_adapter(adapter)
        for e in [e for e in self._entries.values()
                  if e.adapter == int(adapter)]:
            self._entries.pop(e.eid, None)
            self.bytes -= e.payload.nbytes
        return n

    def clear(self) -> int:
        n = len(self._entries)
        self.index.clear()
        self._entries.clear()
        self.bytes = 0
        return n

    def stats(self) -> dict:
        return {"entries": len(self), "bytes": self.bytes,
                "max_bytes": self.max_bytes, "evictions": self.evictions,
                "spills": self.spills}
