"""Block-hashed radix index: O(len) prefix lookup over stored prompts.

The flat PrefixIndex this replaces compared every stored key against
every prompt — O(slots x len) numpy scans per admission, fine for a
handful of HBM rows but hopeless once the host and Redis tiers multiply
the entry count. The standard shape (vLLM's PagedAttention block reuse,
SGLang's RadixAttention) is block-granular content hashing: split the
token stream into fixed B-token blocks, give block i the CHAIN hash
h_i = H(h_{i-1} || tokens_i) — so a block's identity encodes its whole
left context — and walk a tree keyed by those hashes. Lookup cost is
one hash + one dict probe per prompt block, independent of how many
entries are stored.

Entries are registered on EVERY node along their full-block path, so
the deepest node a prompt walk reaches holds exactly the entries that
share at least that many full blocks with it. The final partial block
(and the sub-block tail of short prompts) is resolved by a direct LCP
compare against a bounded set of MRU candidates at that node — block
granularity finds the candidate, token granularity sizes the match.

Adapters get separate roots: KV flows through the LoRA adapter's
wk/wv, so a prefix stored under one adapter must never match another
(tests/test_lora.py pins this), and dropping a root is how adapter
hot-swap invalidation stays O(1).
"""

from __future__ import annotations

import hashlib
import heapq
import itertools

import numpy as np

# versioned salt: a format change must never collide with old chains
# (the Redis tier persists these hashes across process generations)
CHAIN_SALT = b"gofr-kvcache-v1"

_EIDS = itertools.count(1)


def chain_hashes(tokens: np.ndarray, block: int, adapter: int = 0,
                 limit: int | None = None):
    """Chain hashes for the FULL blocks of ``tokens`` (the trailing
    partial block has no hash — it is matched by LCP compare). Yields
    lazily so a tree walk that dead-ends early never hashes the rest
    of a long prompt."""
    n = len(tokens) // block
    if limit is not None:
        n = min(n, limit)
    h = hashlib.sha256(CHAIN_SALT + str(int(adapter)).encode()).digest()
    toks = np.ascontiguousarray(tokens[:n * block], dtype=np.int32)
    for i in range(n):
        h = hashlib.sha256(h + toks[i * block:(i + 1) * block].tobytes()
                           ).digest()
        yield h


def first_block_hash(tokens, block: int = 16, adapter: int = 0) -> bytes:
    """The chain hash of the FIRST full block of ``tokens`` — the
    prefix-affinity routing key (gofr_tpu/gateway/): every multi-turn
    continuation of a conversation shares its first ``block`` tokens,
    so hashing exactly one block gives a key that is STABLE across
    turns while still spreading distinct sessions. Same salt, same
    chaining, same adapter separation as the radix index and the T2
    fingerprint keys — the gateway's notion of "where this prefix is
    warm" can never drift from the cache's notion of identity.

    Prompts shorter than one block (no full block to chain-hash) fall
    back to hashing the whole short prompt under the same salt: still
    deterministic, still adapter-separated, just turn-UNSTABLE — the
    router treats those as affinity-less and balances them by
    pressure, which is the right call for prompts too short to be
    worth cache affinity anyway."""
    tokens = np.asarray(tokens, np.int32).reshape(-1)
    for h in chain_hashes(tokens, block, adapter, limit=1):
        return h
    seed = hashlib.sha256(CHAIN_SALT + str(int(adapter)).encode()).digest()
    return hashlib.sha256(
        seed + np.ascontiguousarray(tokens).tobytes()).digest()


def lcp(a: np.ndarray, b: np.ndarray) -> int:
    """Length of the longest common prefix of two int token arrays."""
    n = min(len(a), len(b))
    if n == 0:
        return 0
    neq = np.nonzero(a[:n] != b[:n])[0]
    return int(neq[0]) if len(neq) else n


class Entry:
    """One stored prefix. ``payload`` is tier-specific (T0: pool row
    int; T1: a HostKV snapshot); ``tick`` is the owning tier's LRU
    clock. ``key`` is the full stored token sequence — ground truth for
    the token-granular part of a match."""

    __slots__ = ("eid", "key", "adapter", "payload", "tick")

    def __init__(self, key: np.ndarray, adapter: int, payload=None):
        self.eid = next(_EIDS)
        self.key = np.asarray(key, np.int32).copy()
        self.adapter = int(adapter)
        self.payload = payload
        self.tick = 0

    @property
    def row(self) -> int:
        return self.payload  # T0 convention: payload IS the pool row

    def __repr__(self) -> str:  # debug pages
        return (f"Entry(eid={self.eid}, len={len(self.key)}, "
                f"adapter={self.adapter})")


class _Node:
    __slots__ = ("children", "entries")

    def __init__(self):
        self.children: dict[bytes, _Node] = {}
        self.entries: dict[int, Entry] = {}  # eid -> entry through here


class RadixIndex:
    """The tree. Thread-compatible like the index it replaces: tiers
    are only ever mutated from the engine's serving-loop thread."""

    # candidates LCP-compared at the deepest matched node. Registration
    # on every path node means the set at that node already shares the
    # maximal full-block prefix; among them the true longest match can
    # only be missed if more than this many are fresher — at real slot
    # counts (tens of rows) the scan is effectively exhaustive.
    MAX_CANDIDATES = 16

    def __init__(self, block: int = 16):
        self.block = max(1, int(block))
        self._roots: dict[int, _Node] = {}

    def __len__(self) -> int:
        return sum(len(r.entries) for r in self._roots.values())

    def entries_for(self, adapter: int) -> int:
        root = self._roots.get(int(adapter))
        return len(root.entries) if root is not None else 0

    # -- mutation ------------------------------------------------------------
    def insert(self, entry: Entry) -> None:
        root = self._roots.setdefault(entry.adapter, _Node())
        node = root
        node.entries[entry.eid] = entry
        for h in chain_hashes(entry.key, self.block, entry.adapter):
            node = node.children.setdefault(h, _Node())
            node.entries[entry.eid] = entry

    def remove(self, entry: Entry) -> None:
        root = self._roots.get(entry.adapter)
        if root is None or entry.eid not in root.entries:
            return
        del root.entries[entry.eid]
        path = [root]
        node = root
        for h in chain_hashes(entry.key, self.block, entry.adapter):
            node = node.children.get(h)
            if node is None:
                break
            node.entries.pop(entry.eid, None)
            path.append(node)
        # prune childless, entryless suffix nodes (hash re-walk: cheap,
        # and keeps dead chains from accumulating under eviction churn)
        hashes = list(chain_hashes(entry.key, self.block, entry.adapter,
                                   limit=len(path) - 1))
        for i in range(len(path) - 1, 0, -1):
            child = path[i]
            if child.entries or child.children:
                break
            del path[i - 1].children[hashes[i - 1]]

    def invalidate_adapter(self, adapter: int) -> int:
        root = self._roots.pop(int(adapter), None)
        return len(root.entries) if root is not None else 0

    def clear(self) -> int:
        n = len(self)
        self._roots.clear()
        return n

    # -- lookup --------------------------------------------------------------
    def match(self, prompt: np.ndarray, adapter: int = 0
              ) -> tuple[Entry | None, int]:
        """(entry, matched_len) for the longest stored prefix sharing a
        prefix with ``prompt`` — PURE: no counter or LRU side effects
        (the caller decides usability and reports via the owning tier,
        exactly the accept()/reject() contract the flat index had).
        (None, 0) when nothing matches a single token."""
        root = self._roots.get(int(adapter))
        if root is None or not root.entries:
            return None, 0
        prompt = np.asarray(prompt, np.int32)
        node, depth = root, 0
        for h in chain_hashes(prompt, self.block, adapter):
            child = node.children.get(h)
            if child is None or not child.entries:
                break
            node, depth = child, depth + 1
        base = depth * self.block
        best, best_len = None, 0
        cands = heapq.nlargest(self.MAX_CANDIDATES, node.entries.values(),
                               key=lambda e: e.tick)
        for e in cands:
            # entries at this node share >= base tokens (chain-hash
            # equality); size the match at token granularity from there
            m = base + lcp(e.key[base:], prompt[base:])
            if m > best_len:
                best, best_len = e, m
                if m >= len(prompt):
                    break
        return (best, best_len) if best is not None and best_len > 0 \
            else (None, 0)
