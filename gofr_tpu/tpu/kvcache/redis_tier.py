"""T2: the Redis-shared tier — replicas warming each other's caches.

The paper's thesis is that the datasources and the TPU path belong in
ONE framework; this is where they finally meet: cache blocks travel
through ``datasource/redisclient.py`` — the same dependency-free RESP2
client every other part of the framework uses — so shared prefix
capacity scales with the Redis deployment, not with any one replica's
HBM or RAM.

Layout (all keys under one namespace):

  {ns}:{fingerprint}:ep:{adapter}          -> epoch integer
  {ns}:{fingerprint}:{adapter}:{epoch}:{chain-hash} -> block frame

``fingerprint`` hashes the model config + a weight sample, so replicas
serving different weights can share one Redis without ever exchanging
KV (quant.decode_block additionally shape-checks and checksums every
frame — shared-store bytes are untrusted input, a bad frame is a miss).
The chain hash (radix.chain_hashes) encodes each block's whole left
context, so a lookup is: compute the prompt's chain, MGET, take the
longest prefix run of valid frames.

Invalidation is by EPOCH, not deletion: adapter hot-swap INCRs the
epoch key, which renames the namespace for EVERY replica at once —
local DELs could never catch blocks other replicas wrote. Old-epoch
blocks age out via their TTL. Replicas cache the epoch locally for
``epoch_refresh_s`` (a bounded staleness window: the worst case is one
refresh interval of already-invalidated hits, the same class of trade
as any shared cache's TTL).

Every READ/WRITE is fail-open: a Redis error counts, logs once, and
reads as a miss — the serving loop must never stall on the shared tier.
Errors also open a backoff window (exponential, capped) during which
the tier is not consulted at all: a down Redis must not tax every
admission with a fresh connect timeout. The one fail-CLOSED operation
is ``invalidate_adapter``: if the epoch bump cannot reach Redis, the
adapter's shared reads and writes stay disabled (``_pending_bumps``)
until a later bump succeeds — serving pre-swap LoRA KV would be
silently wrong tokens, strictly worse than a cold tier.
"""

from __future__ import annotations

import time

import numpy as np

from .quant import (HostKV, KVLayout, ShardedHostKV, concat_blocks,
                    decode_block, encode_block)
from .radix import chain_hashes

NAMESPACE = "gofr:kv"
# cap on remembered already-written block hashes (write-once dedup);
# overflow just forgets — a duplicate SET is wasteful, never wrong
_WRITTEN_CAP = 8192
# error backoff: first failure pauses consults for _BACKOFF_S, doubling
# per consecutive failure up to the cap; any success resets
_BACKOFF_S = 1.0
_BACKOFF_CAP_S = 30.0


class RedisTier:
    tier = "t2"

    def __init__(self, client, fingerprint: str, layout: KVLayout,
                 block: int = 16, ttl_s: float = 300.0,
                 epoch_refresh_s: float = 5.0, logger=None,
                 namespace: str = NAMESPACE, shards: int = 1):
        self.client = client
        self.fingerprint = fingerprint
        self.layout = layout
        # tensor-parallel shard count (mesh engines): each stored block
        # becomes ``shards`` frames — the UNCHANGED int8 codec applied
        # per shard with the per-shard head count, keyed ...:s{i}. The
        # caller's fingerprint carries the mesh shape, so replicas
        # sharded differently occupy disjoint namespaces (a 2-shard
        # frame must never half-decode on a 4-shard reader).
        self.shards = max(1, int(shards))
        if self.shards > 1:
            if layout.kv_heads % self.shards:
                raise ValueError(
                    f"kv_heads={layout.kv_heads} not divisible by "
                    f"shards={self.shards}")
            self._shard_layout = layout._replace(
                kv_heads=layout.kv_heads // self.shards)
        else:
            self._shard_layout = layout
        self.block = int(block)
        self.ttl_s = float(ttl_s)
        self.epoch_refresh_s = float(epoch_refresh_s)
        self.logger = logger
        self.ns = namespace
        self._epochs: dict[int, tuple[int, float]] = {}  # adapter -> (ep, t)
        self._written: set[tuple[int, int, bytes]] = set()
        self._pending_bumps: set[int] = set()  # fail-closed invalidations
        self._down_until = 0.0
        self._backoff = _BACKOFF_S
        self.errors = 0
        self._logged_error = False
        self.blocks_put = 0
        self.blocks_got = 0
        self.bytes_put = 0
        self.bytes_got = 0
        self.checksum_rejects = 0

    # -- keys / epoch --------------------------------------------------------
    def _epoch_key(self, adapter: int) -> str:
        return f"{self.ns}:{self.fingerprint}:ep:{adapter}"

    def _block_key(self, adapter: int, epoch: int, h: bytes,
                   shard: int = 0) -> str:
        key = f"{self.ns}:{self.fingerprint}:{adapter}:{epoch}:{h.hex()}"
        return f"{key}:s{shard}" if self.shards > 1 else key

    def _epoch(self, adapter: int) -> int:
        if adapter in self._pending_bumps:
            # a past invalidation never reached Redis: the shared
            # namespace still holds pre-swap KV under the old epoch, so
            # the adapter stays fail-CLOSED until the bump lands
            ep = int(self.client.incr(self._epoch_key(adapter)))
            self._pending_bumps.discard(adapter)
            self._epochs[adapter] = (ep, time.monotonic())
            self._ok()
            return ep
        cached = self._epochs.get(adapter)
        now = time.monotonic()
        if cached is not None and now - cached[1] < self.epoch_refresh_s:
            return cached[0]
        raw = self.client.get(self._epoch_key(adapter))
        ep = int(raw) if raw else 0
        self._epochs[adapter] = (ep, now)
        self._ok()
        return ep

    @property
    def available(self) -> bool:
        """False inside the post-error backoff window — the manager
        skips the tier entirely so a down Redis costs admissions
        nothing (no connect attempt, no counter noise)."""
        return time.monotonic() >= self._down_until

    def _ok(self) -> None:
        self._backoff = _BACKOFF_S
        self._down_until = 0.0
        # re-arm the once-per-outage log: a LATER outage must be
        # visible, only repeats within one outage are squelched
        self._logged_error = False

    def _fail(self, op: str, e: Exception) -> None:
        self.errors += 1
        self._down_until = time.monotonic() + self._backoff
        self._backoff = min(self._backoff * 2, _BACKOFF_CAP_S)
        if self.logger is not None and not self._logged_error:
            self._logged_error = True  # once: a down Redis would spam
            self.logger.warn({"event": "kvcache redis tier error "
                              "(fail-open: reads as miss)",
                              "op": op, "error": repr(e)})

    # -- tier API ------------------------------------------------------------
    def match(self, prompt: np.ndarray, adapter: int = 0
              ) -> "tuple[int, HostKV | ShardedHostKV | None]":
        """(matched_tokens, kv) — the longest run of consecutive valid
        shared blocks from position 0; (0, None) on miss or error. On
        sharded tiers a block counts only when EVERY shard's frame
        decodes (a half-present block would restore half a row's
        heads), and the hit returns a :class:`ShardedHostKV`."""
        nb = len(prompt) // self.block
        if nb == 0 or not self.available:
            return 0, None
        S = self.shards
        try:
            ep = self._epoch(adapter)
            hashes = list(chain_hashes(prompt, self.block, adapter))
            keys = [self._block_key(adapter, ep, h, s)
                    for h in hashes for s in range(S)]
            raw = self.client.mget(*keys)
            self._ok()
        except Exception as e:  # noqa: BLE001 — fail-open by contract
            self._fail("match", e)
            return 0, None
        per_shard: list[list[HostKV]] = [[] for _ in range(S)]
        n_ok = 0
        for i in range(len(hashes)):
            row = raw[i * S:(i + 1) * S]
            kvs = [decode_block(d, self._shard_layout)
                   if d is not None else None for d in row]
            if any(kv is None or kv.plen != self.block for kv in kvs):
                # an integrity reject is a PRESENT frame that failed
                # decode (or carries the wrong plen); a merely-absent
                # shard is routine TTL/eviction churn — counting it
                # would fire corruption alerts on normal cache misses
                if any((kv is None and d is not None)
                       or (kv is not None and kv.plen != self.block)
                       for d, kv in zip(row, kvs)):
                    self.checksum_rejects += 1
                break
            for s, kv in enumerate(kvs):
                per_shard[s].append(kv)
            self.bytes_got += sum(len(d) for d in row)
            n_ok += 1
        if not n_ok:
            return 0, None
        self.blocks_got += n_ok
        if S == 1:
            return n_ok * self.block, concat_blocks(per_shard[0])
        return n_ok * self.block, ShardedHostKV(
            tuple(concat_blocks(bl) for bl in per_shard))

    def pending_put_len(self, key: np.ndarray, adapter: int = 0) -> int:
        """Token positions a put() for ``key`` would actually read: up
        to the END of the last full block this replica hasn't written
        this epoch (0 = nothing to write). The engine calls this BEFORE
        the device_get that feeds put(), so an already-shared prefix
        (the common repeat-traffic case) costs no D2H transfer at all
        and a partially shared one transfers only through the last
        unwritten block."""
        nb = len(key) // self.block
        if nb == 0 or not self.available:
            return 0
        try:
            ep = self._epoch(adapter)
        except Exception as e:  # noqa: BLE001
            self._fail("pending", e)
            return 0
        last = 0
        for i, h in enumerate(chain_hashes(key, self.block, adapter,
                                           limit=nb)):
            if (adapter, ep, h) not in self._written:
                last = i + 1
        return last * self.block

    def put(self, key: np.ndarray, adapter: int,
            kv: "HostKV | ShardedHostKV") -> int:
        """Write-through the FULL blocks of a newly stored prefix; the
        trailing partial block stays replica-local (it has no chain
        hash). Returns blocks written. One pipeline, one round trip.
        Sharded tiers take a :class:`ShardedHostKV` (one frame per
        shard per block); a block enters the write-once dedup set only
        when EVERY shard's SET succeeded — a half-written block must
        stay retryable or readers would forever decode half a row."""
        S = self.shards
        if S > 1:
            if not isinstance(kv, ShardedHostKV) or kv.shards != S:
                return 0  # shape drift (e.g. post-re-placement): skip
        elif isinstance(kv, ShardedHostKV):
            kv = kv.assemble()
        nb = min(len(key), kv.plen) // self.block
        if nb == 0 or not self.available:
            return 0
        try:
            ep = self._epoch(adapter)
            if len(self._written) > _WRITTEN_CAP:
                self._written.clear()
            pipe = self.client.pipeline()
            wrote = []
            for i, h in enumerate(chain_hashes(key, self.block, adapter,
                                               limit=nb)):
                seen = (adapter, ep, h)
                if seen in self._written:
                    continue
                sl = kv.slice_tokens(i * self.block, (i + 1) * self.block)
                parts = sl.parts if S > 1 else (sl,)
                sizes = []
                for s, part in enumerate(parts):
                    frame = encode_block(part)
                    pipe.command("SET",
                                 self._block_key(adapter, ep, h, s),
                                 frame, "PX", int(self.ttl_s * 1000))
                    sizes.append(len(frame))
                wrote.append((seen, sizes))
            if not wrote:
                return 0
            replies = pipe.execute()
            self._ok()
        except Exception as e:  # noqa: BLE001
            self._fail("put", e)
            return 0
        # the pipeline returns per-command ERROR REPLIES in-band (e.g.
        # -OOM at maxmemory/noeviction, -READONLY on a failed-over
        # replica) — a failed SET must NOT enter _written, or
        # pending_put_len would report the block shared forever while
        # no replica can ever read it
        ok = 0
        r = 0
        for seen, sizes in wrote:
            block_replies = replies[r:r + len(sizes)]
            r += len(sizes)
            good = True
            for reply in block_replies:
                if reply != "OK":
                    good = False
                    self._fail("put-reply",
                               reply if isinstance(reply, Exception)
                               else RuntimeError(repr(reply)))
            if good:
                self._written.add(seen)
                self.bytes_put += sum(sizes)
                ok += 1
        self.blocks_put += ok
        return ok

    def invalidate_adapter(self, adapter: int) -> None:
        """Bump the adapter's epoch — renames the key namespace for
        every replica sharing this Redis; stale blocks TTL out. This is
        the one fail-CLOSED path: if the bump cannot reach Redis, the
        old-epoch namespace still holds pre-swap KV, so the adapter's
        shared reads AND writes stay off until a later bump succeeds
        (retried lazily from _epoch on the next consult)."""
        adapter = int(adapter)
        try:
            ep = self.client.incr(self._epoch_key(adapter))
            self._epochs[adapter] = (int(ep), time.monotonic())
            self._pending_bumps.discard(adapter)
            self._ok()
        except Exception as e:  # noqa: BLE001
            self._fail("invalidate", e)
            self._pending_bumps.add(adapter)
            self._epochs.pop(adapter, None)
        self._written = {w for w in self._written if w[0] != adapter}

    def rekey(self, fingerprint: str, shards: int) -> None:
        """Re-namespace the tier after a mesh re-placement changed the
        shard layout (device-loss recovery onto a smaller tp): new
        fingerprint (it carries the mesh shape), new per-shard head
        count, and the write-once dedup set dropped — frames written
        under the old shape live in a namespace this replica no longer
        reads, and TTL out."""
        shards = max(1, int(shards))
        if shards > 1 and self.layout.kv_heads % shards:
            shards = 1
        self.fingerprint = fingerprint
        self.shards = shards
        self._shard_layout = (self.layout._replace(
            kv_heads=self.layout.kv_heads // shards) if shards > 1
            else self.layout)
        self._written.clear()
        self._epochs.clear()

    def stats(self) -> dict:
        return {"blocks_put": self.blocks_put, "blocks_got": self.blocks_got,
                "shards": self.shards,
                "bytes_put": self.bytes_put, "bytes_got": self.bytes_got,
                "errors": self.errors,
                "checksum_rejects": self.checksum_rejects,
                "available": self.available,
                "pending_bumps": len(self._pending_bumps),
                "ttl_s": self.ttl_s}
