"""Continuous-batching token generation: the streaming-decode serving loop.

No reference equivalent (SURVEY §5 "checkpoint/resume": the reference is a
stateless microservice framework; token streaming is the BASELINE.json
Llama target). Design:

  - A FIXED pool of B batch slots shares one preallocated KV cache
    [L, B, Smax, KV, hd]. Slots are admitted/retired independently via a
    per-slot ``lengths`` cursor — XLA shapes never change, so the decode
    step compiles exactly once.
  - ADMISSION runs a per-sequence prefill jitted at a small lattice of
    prompt buckets, writing KV straight into the slot with
    ``dynamic_update_slice`` (slot index is traced — no per-slot
    recompile) and emitting the first token, so TTFT = one prefill
    dispatch, never waiting for a decode round.
  - DECODE is one jitted step over all B slots per iteration — inactive
    slots compute but their cursors are frozen, so occupancy only affects
    useful-token throughput, never shape or compile state.
  - The KV cache is DONATED through both jits: the cache buffer is
    updated in place in HBM, zero copies per token.
  - Sampling (greedy + temperature) is fused into the jitted step; the
    host sees only B int32s per iteration.

Consumers call ``generate()`` from any thread and read tokens off a
stream; one background thread owns the device loop.
"""

from __future__ import annotations

import functools
import itertools
import os
import queue
import threading
import time
from collections import deque
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .. import chaos
from ..errors import DeadlineExceeded
from ..models import llama
from ..models.common import ModelConfig
from ..resilience import (SLO_LATENCY, SLO_THROUGHPUT, DecodePipelinePolicy,
                          current_deadline, current_slo_class)
from ..tenancy.fair import WeightedFairLine
from ..tenancy.registry import current_tenant
from ..wire import PushStream
from . import hbm
from .batcher import pad_bucket
from .kvcache import HostKV, ShardedHostKV, clamp_restore_len, dense_hostkv

_REQ_IDS = itertools.count(1)


class _ClassPending:
    """SLO-class-aware pending line for the serving loop: latency-class
    requests are picked first; a weighted anti-starvation counter hands
    every Nth pick to the throughput line while it has waiters, so
    saturating interactive traffic can never starve batch streams out
    of the slot pool entirely (the generator-side mirror of the
    batcher's ClassPolicy reserve).

    Thread model: any thread puts (``generate()``); ONLY the serving
    loop pops — the same single-consumer contract the old queue.Queue
    carried, which is what makes pop-then-push-front requeues exact.

    Each class line is a ``WeightedFairLine``: inside a class, tenants
    are served deficit-round-robin over their registry queue weight
    (2:1:1 weights pop A,A,B,C under saturation). Requests without a
    tenant all ride the default line, which collapses each class back
    to the plain FIFO this started as — the latency/throughput split
    and anti-starvation streak above are unchanged."""

    def __init__(self, throughput_share: float = 0.25):
        share = min(max(float(throughput_share), 0.0), 1.0)
        # share -> latency picks per throughput pick, FLOORED so the
        # realized contended fraction 1/(weight+1) is always >= the
        # configured share (0.25 -> 3:1, 0.5 -> 1:1, >= 0.5 rounds
        # toward throughput-first). None disables the guarantee
        # (throughput then drains only when the latency line is empty).
        self._weight = (int((1.0 - share) / share) if share > 0 else None)
        self._lat = WeightedFairLine()
        self._thr = WeightedFairLine()
        self._lock = threading.Lock()
        self._lat_streak = 0
        self._prev_streak = 0  # streak before the most recent pop

    def put(self, req: "_Request") -> None:
        with self._lock:
            (self._thr if req.slo_class == SLO_THROUGHPUT
             else self._lat).append(req)

    def put_front(self, req: "_Request") -> None:
        """UNDO the most recent pop: return the request to the head of
        its class line AND restore the anti-starvation streak to its
        pre-pop value (the in-flight lattice deferral). Without the
        restore, a throughput request whose streak-earned turn lands
        in a deferred pass would burn its credit with nothing served —
        under latency saturation its admission could slip far past the
        configured share. Valid because pops and push-fronts come from
        the single consumer thread, back-to-back."""
        with self._lock:
            (self._thr if req.slo_class == SLO_THROUGHPUT
             else self._lat).appendleft(req)
            self._lat_streak = self._prev_streak

    def get_nowait(self, allow_throughput: bool = True) -> "_Request":
        """Pop the next admissible request. ``allow_throughput=False``
        is the slot-reservation path: the caller is filling one of the
        latency-reserved slots, so only the latency line may serve it
        (raises queue.Empty when only throughput waits)."""
        with self._lock:
            use_thr = allow_throughput and bool(self._thr) and (
                not self._lat
                or (self._weight is not None
                    and self._lat_streak >= self._weight))
            line = self._thr if use_thr else self._lat
            if not line:
                raise queue.Empty
            self._prev_streak = self._lat_streak
            if use_thr:
                self._lat_streak = 0
            else:
                self._lat_streak += 1
            return line.popleft()

    def qsize(self) -> int:
        return len(self._lat) + len(self._thr)

    def qsize_class(self, slo_class: str) -> int:
        return len(self._thr if slo_class == SLO_THROUGHPUT else self._lat)

    def qsize_by_tenant(self) -> dict[str, int]:
        """Queued requests per tenant across both class lines (the
        per-tenant queue-depth gauge; snapshot under the put lock so a
        concurrent put can't double-count a request mid-move)."""
        with self._lock:
            out = dict(self._lat.by_tenant())
            for tid, n in self._thr.by_tenant().items():
                out[tid] = out.get(tid, 0) + n
            return out

    def empty(self) -> bool:
        return not (self._lat or self._thr)


def _copy_row(dst, src, dst_idx, src_idx):
    """Copy one batch row of KV (+ scale planes): src[:, src_idx] ->
    dst[:, dst_idx]. Shared by prefix-pool store (dst=pool) and load
    (dst=serving cache); lengths are untouched — the slot cursor is set
    by the chunk dispatches, the pool's lengths live host-side."""
    import jax.lax as lax

    def cp(d, s):
        r = lax.dynamic_slice_in_dim(s, src_idx, 1, axis=1)
        return lax.dynamic_update_slice_in_dim(d, r, dst_idx, axis=1)

    quant = dst.k_scale is not None
    return dst._replace(
        k=cp(dst.k, src.k), v=cp(dst.v, src.v),
        k_scale=cp(dst.k_scale, src.k_scale) if quant else None,
        v_scale=cp(dst.v_scale, src.v_scale) if quant else None)


def _write_row_from_host(pool, k, v, ks, vs, row):
    """Land a host KV slab in pool row ``row`` — the device half of a
    T1/T2 restore (kvcache promotion). ``k``/``v`` arrive padded to
    [L, 1, Smax, KV, hd] (scales [L, 1, Smax, KV]) so the program
    compiles once; positions past the entry's length are zeros that the
    resumed prefill overwrites or the cursor masks."""
    import jax.lax as lax

    def wr(dst, src):
        return lax.dynamic_update_slice_in_dim(dst, src, row, axis=1)

    quant = pool.k_scale is not None
    return pool._replace(
        k=wr(pool.k, k), v=wr(pool.v, v),
        k_scale=wr(pool.k_scale, ks) if quant else None,
        v_scale=wr(pool.v_scale, vs) if quant else None)


def _write_row_from_host_masked(pool, k, v, ks, vs, row):
    """GSPMD-friendly _write_row_from_host for SHARDED pools (mesh
    engines' T1/T2 promotion): the dynamic_update_slice form puts a
    traced start on the batch axis — the axis the pool shards over the
    data mesh axes — and GSPMD's only lowering for that replicates the
    whole pool (the _copy_row hazard). Select the destination row with
    a one-hot mask and blend instead: ``src`` [L, 1, Smax, ...] arrives
    replicated and broadcasts over the batch axis, every op partitions
    cleanly under any batch/tp sharding. Reads the full pool once; that
    extra HBM stream is the price of mesh support, paid only on a
    promotion (not per token)."""
    def wr(dst, src):
        sel = (jnp.arange(dst.shape[1]) == row)
        sel = sel.reshape((1, -1) + (1,) * (dst.ndim - 2))
        return jnp.where(sel, src.astype(dst.dtype), dst)

    quant = pool.k_scale is not None
    return pool._replace(
        k=wr(pool.k, k), v=wr(pool.v, v),
        k_scale=wr(pool.k_scale, ks) if quant else None,
        v_scale=wr(pool.v_scale, vs) if quant else None)


def _copy_row_masked(dst, src, dst_idx, src_idx):
    """GSPMD-friendly _copy_row for sharded engines. _copy_row's dynamic
    slice/update puts a TRACED start index on the batch axis — the axis
    kv_cache_specs shards over the data mesh axes — and GSPMD's only
    lowering for that is replicating the whole cache (the same
    involuntary-full-remat class as MULTICHIP_r03's embedding gather).
    Mask-and-reduce instead: select the source row by one-hot mask and
    sum over the batch axis (partitioned as local reduce + psum over the
    data axes), then blend it into the destination row with an
    elementwise where over a broadcast of the (replicated) row — every
    op here partitions cleanly under any batch/tp sharding. Reads both
    caches fully instead of one row each; that extra HBM stream is the
    price of mesh support and stays well under one decode block."""
    def cp(d, s):
        sel_s = (jnp.arange(s.shape[1]) == src_idx)
        sel_s = sel_s.reshape((1, -1) + (1,) * (s.ndim - 2))
        # int8 KV sums exactly in int32 (one nonzero term per position)
        acc = jnp.int32 if jnp.issubdtype(s.dtype, jnp.integer) else s.dtype
        row = jnp.sum(jnp.where(sel_s, s, 0).astype(acc), axis=1,
                      keepdims=True)                       # [L, 1, ...]
        sel_d = (jnp.arange(d.shape[1]) == dst_idx)
        sel_d = sel_d.reshape((1, -1) + (1,) * (d.ndim - 2))
        return jnp.where(sel_d, row.astype(d.dtype), d)

    quant = dst.k_scale is not None
    return dst._replace(
        k=cp(dst.k, src.k), v=cp(dst.v, src.v),
        k_scale=cp(dst.k_scale, src.k_scale) if quant else None,
        v_scale=cp(dst.v_scale, src.v_scale) if quant else None)


class GenerationError(RuntimeError):
    pass


class GenStream(PushStream):
    """Iterator over generated token ids; ``cancel()`` releases the slot.

    A PushStream: transports may register a zero-handoff sink
    (``set_sink``) so the serving loop's ``_deliver`` hands each token
    straight to the connection writer instead of waking a consumer
    thread — the first-token fast path of the gRPC/HTTP streamers.
    ``stream.map(fn)`` adapts tokens to messages/chunks for either."""

    def __init__(self, request_id: int, engine: "GenerationEngine",
                 logprobs: bool = False):
        super().__init__()  # _q + sink state (wire.PushStream)
        self.request_id = request_id
        self._engine = engine
        self.cancelled = threading.Event()
        self.prompt_len = 0
        self.logprobs = logprobs  # items are (token, logprob) tuples
        # TTFT decomposition (time.monotonic seconds): "submit" set by
        # generate(), "admit" when the serving loop pops the request,
        # "prefill_done" when the first token hits this queue. Lets a
        # client attribute its observed TTFT to admission wait vs
        # prefill vs delivery wake-up (tools/ttft_probe.py).
        self.trace: dict[str, float] = {}
        # flight-recorder state (set by generate() when the engine has an
        # Observe bundle): the request's W3C trace context — inherited
        # from the submitting thread's span or minted fresh — and its
        # in-flight registry entry
        self.traceparent: str | None = None
        self.trace_id: str = ""
        self.obs_entry = None
        self.failed: str | None = None  # set by the loop's error handler
        # canonical-wide-event state (docs/advanced-guide/
        # observability.md "wide events"): accumulated by the serving
        # loop, emitted once at the stream's terminal outcome
        self.slo_class: str = SLO_LATENCY
        self.chunks = 0             # mid-chunk dispatches of this prefill
        self.cache_tier: str | None = None  # kvcache tier that served it
        self.cache_tokens = 0       # prompt positions the tier covered
        # deadline-expiry site for the wide event ("queue"/"mid-prefill"/
        # "mid-decode"; "post-handoff" for ingested P/D requests — the
        # decode-side record that a request died AFTER the pool boundary)
        self.where: str | None = None
        # durable-stream resume state (docs/advanced-guide/resilience.md
        # "stream resume contract"): ``cursor_base`` is the absolute
        # generated-token index this stream CONTINUES from (0 for a
        # fresh request) — token i of this stream sits at absolute
        # cursor ``cursor_base + i``; ``seed`` is the per-request
        # sampling seed the resume token must carry so a continuation
        # re-keys the PRNG identically (None for greedy requests)
        self.cursor_base = 0
        self.seed: int | None = None
        # tenancy: the resolved (canonical) tenant id for wide events
        # and per-tenant metric labels; ``_tenant_held`` marks a live
        # concurrency-quota slot that must be released exactly once at
        # the stream's terminal (whatever that terminal is)
        self.tenant: str = "default"
        self._tenant_held = False

    def tokens(self) -> list[int]:
        """Drain the whole stream (blocking) into a list of ids
        (logprobs, when enabled, are dropped here — iterate for them)."""
        return [t[0] if isinstance(t, tuple) else t for t in self]

    def cancel(self) -> None:
        self.cancelled.set()


class _Request:
    __slots__ = ("stream", "prompt", "max_new", "temperature", "top_k",
                 "eos_id", "adapter", "enqueued_at", "lattice_peek",
                 "kv_match", "deadline", "slo_class", "kv_sink",
                 "kv_shipped", "ingest", "seed", "pos_base", "tenant",
                 "tenant_weight")

    @property
    def logprobs(self) -> bool:
        return self.stream.logprobs

    def __init__(self, stream: GenStream, prompt: np.ndarray, max_new: int,
                 temperature: float, top_k: int, eos_id: int | None,
                 adapter: int = 0, deadline=None,
                 slo_class: str = SLO_LATENCY):
        self.stream = stream
        self.prompt = prompt
        self.max_new = max_new
        self.temperature = temperature
        self.top_k = top_k
        self.eos_id = eos_id
        self.adapter = adapter
        self.enqueued_at = time.monotonic()
        self.lattice_peek: tuple[int, bool] | None = None
        # memoized CacheManager.match verdict, keyed by the manager's
        # version counter (see GenerationEngine._kv_match)
        self.kv_match: tuple[int, Any] | None = None
        # resilience.Deadline: expired requests are dropped at admission
        # (no prefill dispatch for a caller that already gave up)
        self.deadline = deadline
        # resilience SLO class: selects the pending line, the gate's
        # degradation band, and the per-class telemetry labels
        self.slo_class = slo_class
        # disaggregated serving (gofr_tpu/pd/): ``kv_sink`` marks a
        # PREFILL-ONLY request — prefill runs normally, the slot's KV
        # streams out through the sink per chunk, the single delivered
        # token is the sampled first token, and the slot retires
        # without decoding. ``ingest`` is the DECODE-side mirror:
        # (HostKV, first_token, first_lp) shipped by a prefill worker —
        # admission installs the rows instead of dispatching a prefill.
        self.kv_sink = None
        self.kv_shipped = 0
        self.ingest: "tuple | None" = None
        # per-request sampling seed (int32; 0 for greedy) and the
        # absolute generated-token index this request resumes from —
        # together they re-key every sample on ABSOLUTE position
        # (fold_in(PRNGKey(seed), pos)), which is what makes a
        # mid-stream continuation sample-exact: token P of a resumed
        # stream consumes the key token P of the original would have
        self.seed = 0
        self.pos_base = 0
        # tenancy: the fair line's scheduling key and DRR quantum (the
        # registry queue weight, snapshotted at admission)
        self.tenant = "default"
        self.tenant_weight = 1


class _Inflight:
    """A dispatched-but-unreaped device tick. ``arrays``: the dispatch's
    output futures (readiness probe); ``reap``: fetch results and
    deliver tokens — must run under the engine's device lock.
    ``ready_t``: when the loop observed the outputs ready (None until
    then) — the instant the device stream ran dry unless another block
    was already queued behind this one, i.e. the dispatch-gap anchor."""
    __slots__ = ("arrays", "reap", "ready_t")

    def __init__(self, arrays, reap):
        self.arrays = arrays
        self.reap = reap
        self.ready_t: float | None = None


class _Slot:
    __slots__ = ("request", "remaining", "generated", "last_token_t")

    def __init__(self):
        self.request: _Request | None = None
        self.remaining = 0
        self.generated = 0
        self.last_token_t = 0.0  # monotonic time of the last delivery

    @property
    def free(self) -> bool:
        return self.request is None


class GenerationEngine:
    def __init__(self, cfg: ModelConfig, params: Any, *, slots: int = 8,
                 max_seq: int | None = None,
                 prompt_buckets: tuple[int, ...] = (32, 64, 128, 256, 512),
                 logger=None, metrics=None, observe=None, seed: int = 0,
                 mesh=None, gate=None,
                 kv_dtype=None, decode_block: int = 4,
                 decode_pipeline: int = 2,
                 admit_window_ms: float = 2.0,
                 prefix_cache_slots: int = 0,
                 prefix_store_min: int | None = None,
                 kvcache=None,
                 spec_decode_k: int = 0,
                 lora_adapters: int = 0, lora_rank: int = 16,
                 paged_blocks: int = 0, paged_block_size: int = 128,
                 prefill_chunk: int | None = None,
                 slo_throughput_share: float = 0.25,
                 slo_latency_slots: int = 1):
        self.cfg = cfg
        self.params = params
        self.n_slots = slots
        # serializes device-state mutation (the loop thread vs warmup/
        # close). Created BEFORE the first hbm.alloc below: the
        # arbiter's reclaim callbacks registered on those leases take
        # this lock, and another engine's construction may invoke them
        # while ours is still mid-__init__. REENTRANT because the
        # serving loop itself can trigger reclaim (admission check ->
        # budget overshoot -> our own pool shrink) while already
        # holding the lock.
        self._device_lock = threading.RLock()
        # guards the _closed check-then-enqueue in generate() against close()
        self._admission_lock = threading.Lock()
        # Multi-LoRA serving: n adapter slots of rank-r deltas on the
        # attention projections, stacked inside params["layers"] so the
        # layer scan slices them with the base weights; each request
        # picks its adapter (generate(adapter=i)) and every program
        # gathers per-row — multi-tenant fine-tunes over ONE shared
        # weight stream. Adapter 0 is the base no-op (B initialized
        # zero); fill others via load_adapter()/checkpoints.
        self._n_adapters = max(0, int(lora_adapters))
        if self._n_adapters:
            if "lora_a_wq" not in params["layers"]:
                def _build_lora():
                    built = llama.init_lora(cfg, self._n_adapters,
                                            int(lora_rank),
                                            jax.random.PRNGKey(seed + 1))
                    if mesh is not None:
                        # stacks shard like any stacked leaf (layer dim
                        # over pp, rank-r matrices replicated — they're
                        # tiny next to the weight stream); the per-row
                        # adapter gather reads a replicated table with
                        # batch-sharded indices, which GSPMD partitions
                        # cleanly
                        from ..parallel import shardings_for

                        built = jax.device_put(built,
                                               shardings_for(built, mesh))
                    return built

                stacks = hbm.alloc("lora", _build_lora, owner=self,
                                   priority=hbm.PRI_CACHE)
                self.params = {**params, "layers": {
                    **params["layers"], **stacks}}
            else:
                # a checkpoint brought its own stacks: their width is
                # the truth. A silent mismatch would CLAMP the device
                # gather (tenant 4 served tenant 2's fine-tune) and
                # DROP out-of-bounds load_adapter scatters.
                n_stack = int(params["layers"]["lora_a_wq"].shape[1])
                if n_stack != self._n_adapters:
                    raise ValueError(
                        f"params carry {n_stack} LoRA adapter slots but "
                        f"lora_adapters={self._n_adapters}; they must "
                        "match (gather clamping would silently serve "
                        "the wrong tenant)")
        self._slot_adapter = np.zeros((slots,), np.int32)
        # K decode steps fused into one dispatch (lax.scan on device): the
        # host sees K tokens per roundtrip instead of one, amortizing
        # dispatch/tunnel latency K-fold. Cost: a finished stream wastes at
        # most K-1 slot-steps, and admission waits at most one block.
        self.decode_block = max(1, int(decode_block))
        # Decode dispatch pipeline (TPU_DECODE_PIPELINE): how many fused
        # blocks may be in flight on the device stream at once. At depth
        # 2 the loop dispatches block N+1 BEFORE reaping block N — all
        # of N+1's inputs (cache, PRNG key, slot-state carry) are device
        # futures chained from N's outputs, so the dispatch queues with
        # zero host feedback and the host overlaps N's reap/delivery/
        # admission with N+1's compute. The policy collapses to 1 when
        # queueing a second block would cost an SLO (latency admission
        # waiting, chunk lattice deferred, spec decode) — see
        # resilience.DecodePipelinePolicy.
        self._pipeline = DecodePipelinePolicy(decode_pipeline)
        self._lattice_deferred = False
        self._depth_now = 0
        # inter-block host-gap instrumentation: _idle_from marks when
        # the device stream ran dry (reap with no successor queued);
        # the next dispatch closes the gap into the histogram/timeline.
        # Overlapped reaps (a block still queued at reap) record 0.0 —
        # the pipelined steady state the A/B bench gates on.
        self._idle_from: float | None = None
        self._gap_samples: "deque[float]" = deque(maxlen=2048)
        self._reaps = 0
        self._overlapped_reaps = 0
        # In-flight admission poll cadence (seconds). While a decode
        # block runs on device, the serving loop waits on the submit
        # event in slices of this length and admits new arrivals
        # immediately (their prefill queues behind the block on the
        # device stream) — see _admit_inflight. Historically this was a
        # post-block GIL-yield sleep ("admit window"); the env knob
        # TPU_ADMIT_WINDOW_MS keeps the name. 0 falls back to 1 ms.
        self._admit_window = max(0.0, float(admit_window_ms)) / 1e3
        # flash-decode kernel (ops.flash_decode). FENCED, not just
        # opt-in: the 2026-07-31 device capture (BENCH_CANDIDATE.json)
        # measured the kernel SLOWER than the fused XLA step inside the
        # K-step scan (2309 vs 2709 tok/s — see PERF.md "flash-decode
        # regression"), so GOFR_FLASH_DECODE=1 alone now logs the
        # recorded regression and stays on the XLA path;
        # GOFR_FLASH_DECODE_FORCE=1 runs the kernel anyway (the
        # A/B-profiling escape hatch). Mesh engines run it shard_map'd
        # per head/batch shard (ops.flash_decode.flash_decode_sharded)
        # under the same env gating.
        self._flash_decode = False
        if os.environ.get("GOFR_FLASH_DECODE") == "1":
            if os.environ.get("GOFR_FLASH_DECODE_FORCE") == "1":
                self._flash_decode = True
            elif logger is not None:
                logger.warn({"event": "GOFR_FLASH_DECODE ignored: known "
                             "regression vs the fused XLA step (PERF.md "
                             "2026-07-31: 2309 vs 2709 tok/s); set "
                             "GOFR_FLASH_DECODE_FORCE=1 to run it anyway"})
        self.max_seq = min(max_seq or cfg.max_seq, cfg.max_seq)
        self.prompt_buckets = tuple(sorted(b for b in prompt_buckets
                                           if b <= self.max_seq)) or (self.max_seq,)
        # Chunked-prefill interleave budget (TPU_PREFILL_CHUNK): a
        # prompt longer than the budget is admitted as a SEQUENCE of
        # bounded chunk dispatches, and between chunks the admission
        # loop runs one decode block for the live batch AND an
        # admission pass for new arrivals — a 4k-token prefill can no
        # longer stall every active stream's next token, and a newly
        # arrived short request gets its first dispatch within one
        # chunk budget (docs/advanced-guide/serving-scheduler.md).
        #   None -> budget = largest prompt bucket (interleave on);
        #   <= 0 -> interleave OFF: the lattice's chunks dispatch
        #           back-to-back (the head-of-line A/B arm);
        #   else -> snapped UP to the nearest prompt bucket (chunk
        #           shapes are compile keys — off-lattice sizes would
        #           recompile mid-serving).
        C_max = self.prompt_buckets[-1]
        if prefill_chunk is None:
            self._chunk, self._chunk_interleave = C_max, True
        elif prefill_chunk <= 0:
            self._chunk, self._chunk_interleave = C_max, False
        else:
            self._chunk = pad_bucket(min(int(prefill_chunk), C_max),
                                     self.prompt_buckets)
            self._chunk_interleave = True

        # Paged (block-pool) KV cache: slots share a pool of fixed
        # T-token blocks via a host-owned block table instead of owning
        # [max_seq] rows — HBM sized to expected LIVE tokens, so decode
        # batch scales past what contiguous rows fit (the road past
        # batch 96 on 8B/v5e; models/paged_llama.py). On a MESH the
        # pool shards KV-heads over tp (parallel.paged_cache_specs —
        # the block axis stays whole so the host-owned table remains
        # global dispatch data) and attention runs the dense-gather
        # reference instead of the Pallas kernel (a pallas_call is
        # opaque to the GSPMD partitioner) — mesh-aware paged serving
        # is a tensor-parallel configuration, token-exact vs the
        # contiguous mesh path (docs/advanced-guide/
        # multichip-serving.md).
        self._paged = paged_blocks > 0
        if self._paged:
            self._block_t = int(paged_block_size)
            self._mb = -(-self.max_seq // self._block_t)
            min_blocks = 2 + (self.prompt_buckets[-1] // self._block_t)
            if paged_blocks < min_blocks:
                raise ValueError(f"paged_blocks={paged_blocks} too small: "
                                 f"need >= {min_blocks} (trash block + "
                                 "one prompt's worth)")
            from ..models.paged_llama import (BlockAllocator,
                                              SharedPrefixIndex)

            self._alloc = BlockAllocator(paged_blocks)
            self._table = np.zeros((slots, self._mb), np.int32)
            self._slot_blocks: list[list[int]] = [[] for _ in range(slots)]
            self._cursors = np.zeros((slots,), np.int64)  # device cursor
            # the cursor each slot's on-device stop mask freezes at
            # (budget/capacity; 0 = none): the host advances _cursors
            # eagerly at dispatch, and under the depth-2 pipeline a
            # stream can have finished on device one whole un-reaped
            # block ago — without this bound _ensure_blocks would
            # demand pool blocks the stream will never write and could
            # starvation-retire it (or a neighbor) for them
            self._stop_cursors = np.zeros((slots,), np.int64)
            self._paged_evictions = 0
            self._prefix_idx = None
            if prefix_cache_slots > 0:
                # ZERO-COPY prefix cache over the pool itself: entries
                # hold refcounted references to a stored prompt's full
                # blocks (no KV moves to store); a hit refs the shared
                # blocks into the new slot's table and prefill resumes
                # at the match point via the scratch row. Evictable
                # under pool pressure.
                self._prefix_idx = SharedPrefixIndex(prefix_cache_slots,
                                                     self._alloc,
                                                     self._block_t)
                self._store_min = int(prefix_store_min
                                      or self.prompt_buckets[-1])
        self.logger = logger
        self.metrics = metrics
        if metrics is not None:
            # device-byte attribution gauges (app_tpu_device_bytes):
            # the hbm registry pushes every accounting change
            hbm.set_metrics(metrics)
        # resilience.AdmissionGate fronting the pending queue (None =
        # admit everything): sheds with TooManyRequests under overload
        # and caps max_new_tokens in its brownout band; fed with each
        # admission's observed queue wait at _start
        self.gate = gate
        # flight recorder + in-flight registry + stage spans (observe/)
        self._observe = observe
        # serving timeline (observe/timeline.py): hot paths hold None
        # when emission is off (TPU_TIMELINE=0) so the disabled cost is
        # one attribute test, not a method call into a dead ring
        tl = getattr(observe, "timeline", None) if observe is not None \
            else None
        self._tl = tl if (tl is not None and tl.enabled) else None
        if self._tl is not None:
            # device-byte accounting changes land HBM counter samples
            # on the exported Perfetto trace (one track per subsystem)
            hbm.set_timeline(self._tl)
        self.mesh = mesh
        self.rope_tables = llama.get_rope_tables(cfg, self.max_seq)

        # kv_dtype=jnp.int8 halves decode's cache HBM stream (quantize on
        # write, dequant fused into attention) — the default for serving
        # big models; None keeps the model dtype (exact numerics).
        self._kv_dtype = kv_dtype
        self._cache_sh = None  # set below for mesh engines
        self.down: str | None = None  # set when the device loop is bricked
        # every persistent device buffer flows through hbm.alloc — the
        # arbiter leases the bytes against the process budget BEFORE
        # allocating (reclaiming other subsystems' holdings when it
        # must), retries once on a real device OOM, and accounts the
        # result (gofrlint GL202's choke point); keyed to this
        # instance so close() releases exactly our bytes. The serving
        # cache is PRI_SERVING: never auto-reclaimed, but the paged
        # variant attaches the cold-prefix-block release so storms
        # can still drain logical pool pressure. MESH engines compute
        # their shardings FIRST (from eval_shape structs) so every
        # buffer is BORN sharded and leased PER SHARD
        # (hbm.alloc_sharded): the arbiter settles one lease entry per
        # device, per-device budgets check each shard, and device-loss
        # re-placement re-settles the same keys instead of
        # double-counting.
        self._rep_sh = None   # mesh: replicated sharding (set below)
        self._pool_sh = None  # mesh: prefix-pool sharding (set below)
        self._scratch_sh = None
        self._dev_labels: tuple = ()
        self._kv_shards = 1   # tp shards of the KV-head axis
        self._replacements = 0  # warm mesh re-placements survived
        if self._paged:
            from ..models.paged_llama import init_paged_cache

            def _init_cache():
                c = init_paged_cache(cfg, slots, paged_blocks,
                                     self._block_t, dtype=kv_dtype)
                if self._cache_sh is not None:
                    c = jax.device_put(c, self._cache_sh)
                return c

            cache_reclaim = self._hbm_paged_reclaim
        else:
            def _init_cache():
                c = llama.init_cache(cfg, slots, self.max_seq,
                                     dtype=kv_dtype)
                if self._cache_sh is not None:
                    c = jax.device_put(c, self._cache_sh)
                return c

            cache_reclaim = None
        self._seed = int(seed)  # recovery reseeds the chained key
        self._recoveries = 0
        if mesh is not None:
            # ICI-sharded serving (SURVEY §2 last row): KV heads over
            # tp, slots over the data axes (paged pools: KV heads over
            # tp only — the block axis stays whole for the global
            # table). Params carry their own shardings (placed by the
            # config wiring); out_shardings pin the cache layout so
            # donation aliases buffers across steps and XLA never
            # resharding-copies the cache. Collectives are emitted by
            # XLA from the specs — nothing here names a device.
            from ..parallel import (kv_cache_specs, kv_head_shards,
                                    paged_cache_specs, replicated)

            self._dev_labels = tuple(str(d.id) for d in mesh.devices.flat)
            self._kv_shards = kv_head_shards(mesh, cfg.n_kv_heads)
            tp = mesh.shape.get("tp", 1)
            data = mesh.devices.size // max(tp * mesh.shape.get("sp", 1)
                                            * mesh.shape.get("pp", 1), 1)
            if tp > 1 and cfg.n_kv_heads % tp and data > 1:
                # VERIFIED numerics hazard (tools/multichip_bench.py
                # bring-up, CPU GSPMD): a tp that splits a KV head
                # (n_kv_heads % tp != 0) combined with dp/fsdp > 1
                # produced logits off by O(1) — not reduction noise —
                # while the same tp with data axes = 1, and any
                # head-aligned tp, stayed exact. Until root-caused in
                # the partitioner this config is REFUSED at startup
                # (it served wrong answers silently when it was only a
                # warning); tp alone (data axes = 1) falls back to the
                # jnp reference instead (docs/advanced-guide/
                # multichip-serving.md "known limits").
                from ..errors import ShardingConfigError

                row = ",".join(
                    f"{ax}={n}" for ax, n in
                    zip(mesh.axis_names, mesh.devices.shape) if n > 1)
                raise ShardingConfigError(
                    f"TPU_SHARDING='{row}': tp={tp} splits a KV head "
                    f"(n_kv_heads={cfg.n_kv_heads}) on a multi-axis mesh "
                    f"(data axes product {data}) — a verified "
                    f"wrong-logits configuration. Use a tp that divides "
                    f"n_kv_heads, or drop the data axes (dp/fsdp=1) to "
                    f"serve tp-only on the jnp fallback.",
                    sharding_row=row)
            self._rep_sh = replicated(mesh)
            struct = jax.eval_shape(_init_cache)  # _cache_sh still None
            self._cache_sh = (paged_cache_specs(mesh, struct) if self._paged
                              else kv_cache_specs(mesh, struct))
            # commit the seed key to the replicated sharding NOW: the
            # chained key outputs are rep-committed, and a first
            # dispatch with an UNCOMMITTED key would occupy a different
            # jit cache entry than every later one — warming one
            # signature and serving the other re-lowers the program
            # mid-serving under the device lock. (GL202 suppressed: a
            # 16-byte PRNG key sits below accounting granularity — the
            # arbiter leases buffers, not scalars.)
            self._key = jax.device_put(jax.random.PRNGKey(seed), self._rep_sh)  # noqa: GL202, E501
            self.cache = hbm.alloc_sharded(
                "engine", _init_cache, owner=self, tag="cache",
                priority=hbm.PRI_SERVING, reclaim=cache_reclaim,
                devices=self._dev_labels)
        else:
            self._key = jax.random.PRNGKey(seed)
            self.cache = hbm.alloc(
                "engine", _init_cache, owner=self, tag="cache",
                priority=hbm.PRI_SERVING, reclaim=cache_reclaim)
        self._slots = [_Slot() for _ in range(slots)]
        self._last_tokens = np.zeros((slots,), np.int32)
        self._active = np.zeros((slots,), bool)
        self._temps = np.zeros((slots,), np.float32)
        self._top_ks = np.zeros((slots,), np.int32)
        # on-device stop-mask state: each slot's remaining token budget
        # (the device carry of _Slot.remaining) and its EOS stop set,
        # EOS_PAD-padded to a fixed width (sets wider than EOS_MAX keep
        # the host check as the only stop — correct, just K-step lazier)
        self._budgets = np.zeros((slots,), np.int32)
        self._eos_mat = np.full((slots, self.EOS_MAX), llama.EOS_PAD,
                                np.int32)
        # durable-streams sampling state: each slot's request seed and
        # the absolute generated-token position of its next sample
        # (pos_base + delivered count) — see _resume_keys
        self._slot_seed = np.zeros((slots,), np.int32)
        self._pos_abs = np.zeros((slots,), np.int32)
        # auto-seed counter for sampled requests submitted without an
        # explicit seed: deterministic per engine (same engine seed +
        # same request order -> same streams), and surfaced on the
        # stream so resume tokens can replay it
        self._auto_seed = itertools.count(1)
        # the coalesced dispatch pack: every host-owned per-slot decode
        # input (last token, active, budget, temp, top-k, adapter,
        # host-wins, seed, position, EOS set, block table) rides to the
        # device as ONE
        # [B, W] int32 h2d transfer, rebuilt only when a mirror is
        # dirty — in steady-state decode the dispatch is all-device
        # (cache/key/carry chain from the previous block's outputs)
        self._pack = None
        self._pack_dirty = True
        # device mirrors of host-owned dispatch arrays (see _dev)
        self._mirror: dict[str, Any] = {}
        self._dirty: set[str] = set()
        self._last_dev = None
        self._host_wins = np.ones((slots,), bool)

        # Hierarchical prefix KV cache (tpu/kvcache/): a P-row HBM pool
        # (T0) indexed by a block-hash radix tree, spilling LRU-evicted
        # rows into host DRAM (T1) and sharing int8 blocks through the
        # framework Redis client (T2), behind one CacheManager facade.
        # A hit replaces MXU prefill work for the matched positions
        # with one HBM row copy (T0) or a host->device upload + row
        # copy (T1/T2 promotion); the remainder (always >= 1 token, so
        # the first sample recomputes) prefills from the match point.
        # On mesh engines the pool shards like the serving cache, the
        # row copies run mask-and-reduce (_copy_row_masked) instead of
        # traced-index dynamic slices (which GSPMD could only lower by
        # replicating the cache), and the OFFLOAD tiers run PER-SHARD:
        # T1 spills read each tp shard's head range straight off its
        # own device shard (ShardedHostKV — no cross-device assembly
        # on the spill path), T2 frames each shard through the
        # unchanged int8 block codec under a fingerprint carrying the
        # mesh shape, and promotion lands the assembled dense row via
        # _write_row_from_host_masked (the same one-hot blend trick).
        # (Paged engines built their zero-copy SharedPrefixIndex above
        # instead — no side pool, entries reference pool blocks.)
        self._pool = None
        self._kvc = None
        self._host_write_jit = None
        # P/D ingest row-install program (pd/ingest.py): compiled on
        # first shipped-KV admission — decode-role engines pay one
        # compile there instead of every engine paying it at startup
        self._ingest_write_jit = None
        if not self._paged:
            self._prefix_idx = None
            if prefix_cache_slots > 0:
                from .kvcache import (CacheManager, KVCacheOptions,
                                      KVLayout, model_fingerprint)

                opts = kvcache or KVCacheOptions()
                if (mesh is not None and jax.process_count() > 1
                        and (opts.host_mb > 0 or opts.redis is not None)):
                    # Multi-PROCESS meshes: _kv_row_get snapshots only
                    # the process-LOCAL shards (addressable_shards),
                    # so a T1/T2 row would silently hold a fraction of
                    # the KV heads and every restore would degrade to
                    # a shape-drift miss. Keep the T0 radix index;
                    # disable the offload tiers loudly until the
                    # snapshot is process-aware.
                    import dataclasses

                    if logger is not None:
                        logger.warn({
                            "event": "kvcache offload tiers disabled on "
                            "multi-process mesh (per-shard snapshots are "
                            "process-local; T0 radix index stays on)"})
                    if opts.redis is not None:
                        try:  # don't leak the discarded connection
                            opts.redis.close()
                        except Exception:
                            pass
                    opts = dataclasses.replace(opts, host_mb=0, redis=None)

                def _init_pool():
                    p = llama.init_cache(cfg, prefix_cache_slots,
                                         self.max_seq, dtype=kv_dtype)
                    if self._pool_sh is not None:
                        p = jax.device_put(p, self._pool_sh)
                    return p

                # PRI_CACHE with the shrink callback: under budget
                # pressure from ANY subsystem the arbiter spills this
                # pool's entries to the host tier and reallocates it
                # smaller (_hbm_pool_reclaim) — T0 shrinks so e.g. a
                # paged engine's lease in the same process proceeds.
                # Mesh pools settle per-shard lease keys; pool shards
                # like the serving cache (batch rows over the data
                # axes when they divide, KV heads over tp).
                if mesh is not None:
                    from ..parallel import kv_cache_specs

                    self._pool_sh = kv_cache_specs(
                        mesh, jax.eval_shape(_init_pool))
                    self._pool = hbm.alloc_sharded(
                        "kvcache-t0", _init_pool, owner=self, tag="pool",
                        priority=hbm.PRI_CACHE,
                        reclaim=self._hbm_pool_reclaim,
                        devices=self._dev_labels)
                else:
                    self._pool = hbm.alloc(
                        "kvcache-t0", _init_pool,
                        owner=self, tag="pool", priority=hbm.PRI_CACHE,
                        reclaim=self._hbm_pool_reclaim)
                layout = KVLayout(cfg.n_layers, cfg.n_kv_heads,
                                  cfg.head_dim, self._pool.quantized,
                                  np.dtype(str(self._pool.k.dtype)),
                                  self.max_seq)
                self._kvc = CacheManager(
                    prefix_cache_slots, layout, block=opts.block,
                    host_bytes=opts.host_mb << 20, redis=opts.redis,
                    redis_ttl_s=opts.redis_ttl_s,
                    epoch_refresh_s=opts.epoch_refresh_s,
                    fingerprint=model_fingerprint(
                        cfg, params,
                        extra=str(layout.np_dtype) + self._mesh_extra()),
                    metrics=metrics, logger=logger,
                    shards=self._kv_shards)
                self._store_min = int(prefix_store_min
                                      or self.prompt_buckets[-1])
        if (self._kvc is None and kvcache is not None
                and kvcache.redis is not None):
            # KVCacheOptions promises the engine owns the client; a
            # paged or prefix_cache_slots=0 engine never builds the
            # manager, so honor the contract here instead of leaking
            # the socket for the process lifetime
            if logger is not None:
                logger.warn({"event": "kvcache redis client discarded "
                             "(engine has no prefix cache: paged or "
                             "prefix_cache_slots=0)"})
            try:
                kvcache.redis.close()
            except Exception:
                pass

        # Prompt-lookup speculative decoding (greedy slots only): each
        # tick proposes K draft tokens per slot by matching the trailing
        # n-gram of the slot's history against its own earlier tokens
        # (repetitive text, code, JSON); ONE verify dispatch streams the
        # weights once and emits 1..K+1 tokens per slot. Misses cost a
        # normal decode tick (the engine falls back when no slot drafts,
        # any active slot samples, or a slot is within a window of
        # capacity). Drafting is host-side numpy either way; on mesh
        # engines the verify dispatch shards exactly like the decode
        # step (batch over data axes, KV heads over tp).
        self._spec_k = max(0, int(spec_decode_k))
        if self._spec_k:
            self._spec_windows = 0
            self._spec_emitted = 0
            # per-slot token history as preallocated buffers: _draft
            # slices VIEWS (no list boxing on the decode loop's
            # GIL-held critical path); append is one index write
            self._hist_buf = np.zeros((slots, self.max_seq), np.int32)
            self._hist_n = np.zeros((slots,), np.int64)

        self._pending = _ClassPending(slo_throughput_share)
        # Latency slot reservation: throughput-class admissions may
        # never take the LAST ``slo_latency_slots`` free slots, so a
        # latency arrival under batch-driven saturation finds a slot
        # at its uncontended wait instead of queueing behind admitted
        # batch streams (the gate bounds the LINE; this bounds the
        # SLOTS). Clamped so throughput can always run somewhere; costs
        # nothing when traffic is untagged (all-latency).
        self._lat_reserve = max(0, min(int(slo_latency_slots), slots - 1))
        self._work = threading.Event()
        self._closed = False
        self._draining = False
        # requests popped off _pending but not yet visible in _active —
        # the admission window (prefill can compile for seconds on a
        # first-shape request); drain() must count them as in-flight
        self._admitting = 0
        self.total_tokens = 0
        self.total_requests = 0
        # tenancy plane (gofr_tpu/tenancy/): installed post-construction
        # by install_tenancy(); None means every request is the
        # anonymous default tenant and nothing tenant-shaped runs
        self.tenancy = None
        self._tenant_leased: set[str] = set()   # live tenant:{id} leases
        self._gauge_tenants: set[str] = set()   # tenants ever gauged

        self._chunk_mid = functools.partial(self._chunk_fn, sample=False)
        self._chunk_final = functools.partial(self._chunk_fn, sample=True)
        if self._paged and (self.max_seq - 1 > self._chunk
                            or self._prefix_idx is not None):
            # Long-prompt admission AND prefix-hit resume both run the
            # chunk lattice against a dense single-slot SCRATCH row
            # (identical programs to the contiguous engine's, B=1),
            # then one dispatch lands the row in the pool
            # (paged_llama.write_row_to_blocks). The scratch costs one
            # slot-row of HBM (~67 MB at 8B/1024).
            self._alloc_scratch()
        self._build_jits()
        self._thread = threading.Thread(target=self._loop, name="gofr-tpu-gen",
                                        daemon=True)
        self._thread.start()

    def install_tenancy(self, plane) -> None:
        """Attach the multi-tenant serving plane (tenancy.TenantPlane).
        From here on generate() resolves the ambient tenant against the
        registry: quota admission, weighted fair queueing, per-tenant
        cache budgets, and tenant-labeled telemetry all switch on."""
        self.tenancy = plane
        if plane is not None and self._kvc is not None:
            row_bytes = 0
            if self._pool is not None and self._kvc.slots > 0:
                row_bytes = hbm.tree_nbytes(self._pool) // self._kvc.slots
            self._kvc.set_tenancy(plane.cache_shares, row_bytes=row_bytes)

    def _alloc_scratch(self) -> None:
        """Allocate the dense single-slot scratch row (paged chunk
        lattice / prefix restore / PD ingest staging). Mesh engines
        shard it like a one-row serving cache (KV heads over tp; the
        batch axis is 1, so data axes fit to nothing) and settle it
        per shard."""
        def _init_scratch():
            s = llama.init_cache(self.cfg, 1, self.max_seq,
                                 dtype=self._kv_dtype)
            if self._scratch_sh is not None:
                s = jax.device_put(s, self._scratch_sh)
            return s

        if self.mesh is not None:
            from ..parallel import kv_cache_specs

            self._scratch_sh = kv_cache_specs(
                self.mesh, jax.eval_shape(_init_scratch))
            self._scratch = hbm.alloc_sharded(
                "engine", _init_scratch, owner=self, tag="scratch",
                priority=hbm.PRI_SCRATCH, devices=self._dev_labels)
        else:
            self._scratch = hbm.alloc(
                "engine", _init_scratch, owner=self, tag="scratch",
                priority=hbm.PRI_SCRATCH)

    def _build_jits(self) -> None:
        """Build (or REBUILD) every compiled program. Factored out of
        __init__ because warm device-loss re-placement compiles the
        whole surface again: out_shardings pin donation aliasing, and
        a sharding names its mesh, so programs built against a dead
        mesh can never serve the replacement.

        outputs: (token, logprob, next_key, cache) for prefill/
        final-chunk, (tokens, logprobs, emitted, slot-state carry,
        next_key, cache) for the fused step — sampling keys derive
        in-trace from each request's (seed, absolute position) pair
        (see _resume_keys; the threaded key is signature ballast), and
        the carry chains the per-slot decode state — last token,
        active, budget, position — the pipeline's next dispatch
        consumes."""
        mesh = self.mesh
        if mesh is not None:
            rep = self._rep_sh
            cache_sh = self._cache_sh
            prefill_fn = (self._paged_prefill_fn if self._paged
                          else self._prefill_fn)
            step_fn = self._paged_step_fn if self._paged else self._step_fn
            self._prefill_jit = jax.jit(prefill_fn, donate_argnums=(0,),
                                        out_shardings=(rep, rep, rep,
                                                       cache_sh))
            self._step_jit = jax.jit(step_fn, donate_argnums=(0,),
                                     out_shardings=(rep, rep, rep,
                                                    (rep, rep, rep, rep),
                                                    rep, cache_sh))
            if self._spec_k:
                verify_fn = (self._paged_verify_fn if self._paged
                             else self._verify_fn)
                self._verify_jit = jax.jit(verify_fn, donate_argnums=(0,),
                                           out_shardings=(rep, rep, rep,
                                                          cache_sh))
            if self._paged:
                if hasattr(self, "_scratch"):
                    from ..models.paged_llama import (read_blocks_to_row,
                                                      write_row_to_blocks)

                    sc = self._scratch_sh
                    self._chunk_mid_jit = jax.jit(self._chunk_mid,
                                                  donate_argnums=(0,),
                                                  out_shardings=sc)
                    self._chunk_final_jit = jax.jit(self._chunk_final,
                                                    donate_argnums=(0,),
                                                    out_shardings=(rep, rep,
                                                                   rep, sc))
                    self._row_to_blocks_jit = jax.jit(write_row_to_blocks,
                                                      donate_argnums=(0,),
                                                      out_shardings=cache_sh)
                    self._blocks_to_row_jit = jax.jit(read_blocks_to_row,
                                                      donate_argnums=(0,),
                                                      out_shardings=sc)
            else:
                self._chunk_mid_jit = jax.jit(self._chunk_mid,
                                              donate_argnums=(0,),
                                              out_shardings=cache_sh)
                self._chunk_final_jit = jax.jit(self._chunk_final,
                                                donate_argnums=(0,),
                                                out_shardings=(rep, rep, rep,
                                                               cache_sh))
                if self._kvc is not None:
                    self._build_pool_jits()
        elif self._paged:
            self._prefill_jit = jax.jit(self._paged_prefill_fn,
                                        donate_argnums=(0,))
            self._step_jit = jax.jit(self._paged_step_fn, donate_argnums=(0,))
            if self._spec_k:
                self._verify_jit = jax.jit(self._paged_verify_fn,
                                           donate_argnums=(0,))
            if hasattr(self, "_scratch"):
                from ..models.paged_llama import (read_blocks_to_row,
                                                  write_row_to_blocks)

                self._chunk_mid_jit = jax.jit(self._chunk_mid,
                                              donate_argnums=(0,))
                self._chunk_final_jit = jax.jit(self._chunk_final,
                                                donate_argnums=(0,))
                self._row_to_blocks_jit = jax.jit(write_row_to_blocks,
                                                  donate_argnums=(0,))
                self._blocks_to_row_jit = jax.jit(read_blocks_to_row,
                                                  donate_argnums=(0,))
        else:
            self._prefill_jit = jax.jit(self._prefill_fn, donate_argnums=(0,))
            self._step_jit = jax.jit(self._step_fn, donate_argnums=(0,))
            self._chunk_mid_jit = jax.jit(self._chunk_mid, donate_argnums=(0,))
            self._chunk_final_jit = jax.jit(self._chunk_final,
                                            donate_argnums=(0,))
            if self._kvc is not None:
                self._pool_load_jit = jax.jit(_copy_row, donate_argnums=(0,))
                self._pool_store_jit = jax.jit(_copy_row, donate_argnums=(0,))
                if self._kvc.wants_offload or self._kvc.shares:
                    self._host_write_jit = jax.jit(_write_row_from_host,
                                                   donate_argnums=(0,))
            if self._spec_k:
                self._verify_jit = jax.jit(self._verify_fn,
                                           donate_argnums=(0,))

    def _build_pool_jits(self) -> None:
        """Mesh prefix-pool programs — split out because the arbiter's
        pool SHRINK reallocates the pool at a new row count, whose
        fitted sharding can differ (a batch axis the data axes no
        longer divide replicates), so the shrink path rebuilds these
        three against the new _pool_sh. The row copies run
        mask-and-reduce; T1/T2 promotion lands the assembled dense
        row via the one-hot blend (_write_row_from_host_masked) —
        both GSPMD-clean under any batch/tp sharding."""
        self._pool_load_jit = jax.jit(_copy_row_masked,
                                      donate_argnums=(0,),
                                      out_shardings=self._cache_sh)
        self._pool_store_jit = jax.jit(_copy_row_masked,
                                       donate_argnums=(0,),
                                       out_shardings=self._pool_sh)
        if self._kvc.wants_offload or self._kvc.shares:
            self._host_write_jit = jax.jit(_write_row_from_host_masked,
                                           donate_argnums=(0,),
                                           out_shardings=self._pool_sh)

    def _mesh_extra(self) -> str:
        """Fingerprint suffix carrying the KV shard layout: the T2
        tier frames blocks PER SHARD, so replicas sharded differently
        must occupy disjoint namespaces — a tp=4 frame must never
        half-decode on a tp=2 reader."""
        return f":tp{self._kv_shards}" if self._kv_shards > 1 else ""

    @staticmethod
    def _device_alive(dev) -> bool:
        """Can this device still take work? A tiny placed transfer is
        the probe — a lost mesh device fails it, a healthy one costs
        microseconds (recovery path only, never per token)."""
        try:
            jax.block_until_ready(
                jax.device_put(jnp.zeros((1,), jnp.int32), dev))
            return True
        except Exception:
            return False

    def _replace_mesh(self) -> None:
        """Warm device-loss re-placement: after a mesh engine's loop
        failure, rebuild the mesh over the devices still alive (the
        same shape when all answer — the chaos-simulated case and a
        hot-spare rejoin — or a shrunk plan, dp-first/tp-last, when
        chips are gone), re-place params, recompute every sharding
        from the surviving buffer SHAPES, and rebuild the compiled
        surface. The recovery code that runs next re-settles the same
        hbm lease keys per shard (account's group SET semantics — no
        double count even across a shape change) and rewarms T0 from
        the T1/T2 tiers exactly like single-device recovery, so
        serving resumes token-exact instead of the process dying with
        the device. Runs under the device lock on the loop thread.

        LIMIT: the params re-place below reads the OLD placement. A
        device that answers the probe again (transient loss, the
        chaos-simulated case) or whose param shards are replicated
        elsewhere recovers warm; a chip that is physically gone while
        holding the only copy of a tp param shard makes that
        device_put raise, and the outer recovery marks the engine
        down — restart-and-reload is the path for that case until
        params can re-place from a host/checkpoint copy
        (docs/advanced-guide/multichip-serving.md, known limits)."""
        from ..parallel import (kv_cache_specs, kv_head_shards,
                                paged_cache_specs, remesh, replicated,
                                shardings_for)

        live = [d for d in self.mesh.devices.flat if self._device_alive(d)]
        lost = self.mesh.devices.size - len(live)
        new_mesh = remesh(self.mesh, live)
        self.mesh = new_mesh
        self._dev_labels = tuple(str(d.id) for d in new_mesh.devices.flat)
        self._rep_sh = replicated(new_mesh)
        # params re-place (a no-op data move when the mesh is
        # unchanged); the LoRA stacks ride along and re-settle their
        # lease via account's SET semantics right below. (GL202
        # suppressed: params are placed and owned by the config
        # wiring, not the engine — the engine accounts only the
        # subtree it allocated, exactly like construction does.)
        self.params = jax.device_put(  # noqa: GL202 — see note above
            self.params, shardings_for(self.params, new_mesh))
        if self._n_adapters:
            stacks = {k: v for k, v in self.params["layers"].items()
                      if k.startswith("lora_")}
            if stacks:
                hbm.account("lora", stacks, owner=self)
        # shardings recompute from the dead buffers' SHAPES (the aval
        # outlives the donated storage), so the reallocs that follow
        # land placed on the new mesh
        self._cache_sh = (paged_cache_specs(new_mesh, self.cache)
                          if self._paged
                          else kv_cache_specs(new_mesh, self.cache))
        if self._pool is not None:
            self._pool_sh = kv_cache_specs(new_mesh, self._pool)
        if hasattr(self, "_scratch"):
            self._scratch_sh = kv_cache_specs(new_mesh, self._scratch)
        new_shards = kv_head_shards(new_mesh, self.cfg.n_kv_heads)
        if self._kvc is not None and new_shards != self._kv_shards:
            # the shard layout changed (degraded tp): T1 survives
            # (payloads assemble dense at promotion), T2 re-namespaces
            from .kvcache import model_fingerprint

            self._kv_shards = new_shards
            self._kvc.rekey(
                model_fingerprint(self.cfg, self.params,
                                  extra=str(self._kvc.layout.np_dtype)
                                  + self._mesh_extra()),
                new_shards)
        else:
            self._kv_shards = new_shards
        self._build_jits()
        self._replacements += 1
        if self.logger is not None:
            self.logger.warn({
                "event": "mesh re-placed after device failure",
                "lost_devices": lost,
                "devices": int(new_mesh.devices.size),
                "axes": {k: int(v) for k, v in
                         zip(new_mesh.axis_names, new_mesh.devices.shape)
                         if v > 1}})

    # top-k truncation width: per-request k is traced (no recompiles);
    # ranks past k are masked within this fixed top set
    TOP_K_MAX = 64

    # on-device EOS stop-set width (llama.decode_stop_mask): requests
    # with more stop ids than this keep host-side retirement as their
    # only stop — still correct, the slot just burns up to a block of
    # junk steps before the host notices. Never a compile key per
    # request (the [B, EOS_MAX] matrix is fixed-shape dispatch data).
    EOS_MAX = 8

    # dispatch-pack column layout (_dispatch_pack / _fused_decode_scan
    # must agree): 0 last_token, 1 active, 2 budget, 3 temp (f32 bits),
    # 4 top_k, 5 adapter, 6 host_wins, 7 seed, 8 pos (absolute
    # generated-token index of the slot's NEXT sample — the host-side
    # truth the carry merge reads under host_wins), 9.. EOS set, then
    # (paged) the block-table row
    _PACK_EXTRA = 9

    # -- jitted device functions --------------------------------------------
    @staticmethod
    def _resume_keys(seeds, pos):
        """Per-slot sampling keys: fold_in(PRNGKey(seed), position).
        Re-keying every sample on the request's seed and the ABSOLUTE
        generated-token position (not the engine's chained key, not a
        step count) is the durable-streams invariant: a continuation
        admitted with ``continue_from`` samples token P with exactly
        the key the original stream would have, on any replica."""
        return jax.vmap(
            lambda s, p: jax.random.fold_in(jax.random.PRNGKey(s), p)
        )(seeds, pos)

    def _sample(self, logits, temps, keys, top_ks):
        """Greedy where temp==0; categorical(logits/temp) otherwise,
        truncated to the request's top-k logits when top_k > 0 — all
        fused per-slot so mixed-sampling batches stay one program.
        ``keys`` [B, ...]: one PRNG key per slot, derived by the caller
        from (request seed, absolute position) — see _resume_keys."""
        V = logits.shape[-1]
        safe_t = jnp.maximum(temps, 1e-6)[:, None]
        scaled = logits / safe_t
        sampled = jax.vmap(jax.random.categorical)(keys, scaled)
        kmax = min(self.TOP_K_MAX, V)
        vals, idx = jax.lax.top_k(scaled, kmax)          # [B, kmax]
        kk = jnp.minimum(jnp.where(top_ks > 0, top_ks, kmax), kmax)
        vals = jnp.where(jnp.arange(kmax)[None, :] < kk[:, None],
                         vals, -jnp.inf)
        in_k = jax.vmap(jax.random.categorical)(keys, vals)
        topk_tok = jnp.take_along_axis(idx, in_k[:, None], axis=1)[:, 0]
        sampled = jnp.where(top_ks > 0, topk_tok, sampled)
        greedy = jnp.argmax(logits, axis=-1)
        tok = jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)
        # logprob of the chosen token under the MODEL's (untempered)
        # distribution — the number OpenAI-style logprobs report
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        lp = jnp.take_along_axis(logp, tok[:, None], axis=1)[:, 0]
        return tok, lp

    def _prefill_fn(self, cache, params, tokens, length, slot, temp,
                    top_k, key, seed, pos, adapter=None):
        """tokens [1, Sb] (padded), length/slot scalars. Writes the slot's
        KV, sets its cursor, returns (first_token scalar, cache).
        ``seed``/``pos``: the request's sampling seed and the absolute
        position of the token sampled here (pos_base — 0 for a fresh
        request, the emitted count for a continuation); ``key`` chains
        through unchanged for signature stability."""
        # flash prefill everywhere: bare Pallas calls do not partition
        # under GSPMD, so on mesh engines ops.flash wraps the kernel in
        # shard_map per head shard (jnp reference when tp would split a
        # KV head) — the mesh= plumbing picks the form.
        logits, k, v, _ = llama.prefill_kv(
            params, self.cfg, tokens, jnp.asarray([length]),
            rope_max=self.max_seq, rope_tables=self.rope_tables,
            flash=True, mesh=self.mesh, adapter=adapter,
            logit_pos=jnp.asarray([length - 1]))
        lengths = cache.lengths.at[slot].set(length)
        cache = llama.write_kv(cache, k, v, (0, slot, 0, 0, 0), lengths)
        last = logits[0, 0]  # [V] at the true prompt end (logit_pos)
        tok, lp = self._sample(last[None, :], temp[None],
                               self._resume_keys(seed[None], pos[None]),
                               top_k[None])
        return tok[0], lp[0], key, cache

    def _chunk_fn(self, cache, params, tokens, start, slot, total_len,
                  pos_in_chunk, temp, top_k, key, seed, pos, adapter,
                  sample: bool):
        """Chunked prefill for prompts longer than the largest bucket:
        slice the slot's cache view, run one chunk against it, write back.
        The final chunk (``sample=True``) also sets the slot's cursor to
        ``total_len`` and samples the first token at ``pos_in_chunk``."""
        L, _, Smax, KV, hd = cache.k.shape
        quant = cache.quantized

        def slot_view(a, rank5: bool):
            size = (L, 1, Smax, KV, hd) if rank5 else (L, 1, Smax, KV)
            idx = (0, slot, 0, 0, 0)[: len(size)]
            return jax.lax.dynamic_slice(a, idx, size)

        small = llama.KVCache(
            slot_view(cache.k, True), slot_view(cache.v, True),
            jnp.zeros((1,), jnp.int32),
            slot_view(cache.k_scale, False) if quant else None,
            slot_view(cache.v_scale, False) if quant else None)
        logits, small = llama.prefill_chunk(
            params, self.cfg, tokens, small, start,
            rope_tables=self.rope_tables, compute_logits=sample,
            adapter=adapter,
            logit_pos=jnp.asarray(pos_in_chunk)[None] if sample else None)
        k_new = jax.lax.dynamic_update_slice(cache.k, small.k, (0, slot, 0, 0, 0))
        v_new = jax.lax.dynamic_update_slice(cache.v, small.v, (0, slot, 0, 0, 0))
        ks, vs = cache.k_scale, cache.v_scale
        if quant:
            ks = jax.lax.dynamic_update_slice(ks, small.k_scale, (0, slot, 0, 0))
            vs = jax.lax.dynamic_update_slice(vs, small.v_scale, (0, slot, 0, 0))
        if not sample:
            # PARK the slot while its prompt is chunk-written: decode
            # blocks interleave with mid-chunks, and every decode step
            # scatter-writes garbage KV at each slot's cursor — a stale
            # cursor inside [0, prompt_len) would corrupt KV this chunk
            # just wrote. Cursor = capacity makes those writes land out
            # of range, where mode="drop" discards them.
            lengths = cache.lengths.at[slot].set(Smax)
            return llama.KVCache(k_new, v_new, lengths, ks, vs)
        lengths = cache.lengths.at[slot].set(total_len)
        last = logits[0, 0]  # [V] at pos_in_chunk (logit_pos)
        tok, lp = self._sample(last[None, :], temp[None],
                               self._resume_keys(seed[None], pos[None]),
                               top_k[None])
        return (tok[0], lp[0], key,
                llama.KVCache(k_new, v_new, lengths, ks, vs))

    def _fused_decode_scan(self, cache, pack, carry, key, step_model):
        """K fused decode steps over all slots (K = decode_block); one
        dispatch returns [K, B] tokens + an emitted mask. Each step
        feeds its sampled token to the next on device — the host is off
        the per-token critical path entirely. Inactive cursors stay
        frozen every step (their garbage KV scatter lands at the frozen
        position, which admission either overwrites or — for parked
        slots — drops). ``step_model(tokens, cache) -> (logits,
        stepped)`` is the only thing that differs between the
        contiguous and paged engines.

        ``pack`` [B, W] int32 is the coalesced host dispatch state (one
        h2d when dirty — see _dispatch_pack); ``carry`` is the device
        slot-state chain (last token, active, budget, position)
        returned by the PREVIOUS block — per slot, ``host_wins`` picks
        which side is the truth (host after admission/retire/verify,
        device in steady state). Chaining ACTIVE and BUDGET through the
        device is what makes depth-2 pipelining exact: block N+1 is
        dispatched before the host has seen block N's tokens, and a
        stream that hits EOS/budget/capacity inside N self-deactivates
        via the in-scan stop mask (llama.decode_stop_mask) so N+1
        freezes it instead of emitting junk. ``emitted`` [K, B] tells
        the host exactly which tokens are real — host delivery replays
        it verbatim, so device stop masks and host retirement stay
        token-equivalent.

        Sampling keys derive in-trace from the pack's per-request SEED
        and the carried absolute POSITION (fold_in(PRNGKey(seed), pos))
        — never from a chained engine key — so a stream interrupted
        anywhere and resumed via ``generate(continue_from=...)`` samples
        the identical tokens (the durable-streams contract). Position
        rides the device carry (not the pack) because under pipelining
        the host cannot know block N's emitted count when it packs
        block N+1; it advances only where a token was actually emitted,
        so delivered token i of a request always consumed position
        ``pos_base + i``. ``key`` chains through untouched (returned
        as-is) purely for dispatch-signature stability."""
        E = self.EOS_MAX
        host_tokens = pack[:, 0]
        host_active = pack[:, 1].astype(bool)
        host_budget = pack[:, 2]
        temps = jax.lax.bitcast_convert_type(pack[:, 3], jnp.float32)
        top_ks = pack[:, 4]
        host_wins = pack[:, 6].astype(bool)
        seeds = pack[:, 7]
        host_pos = pack[:, 8]
        eos_ids = pack[:, self._PACK_EXTRA:self._PACK_EXTRA + E]
        dev_tokens, dev_active, dev_budget, dev_pos = carry
        tokens0 = jnp.where(host_wins, host_tokens, dev_tokens)
        active0 = jnp.where(host_wins, host_active, dev_active)
        budget0 = jnp.where(host_wins, host_budget, dev_budget)
        pos0 = jnp.where(host_wins, host_pos, dev_pos)
        # the host retires one delivered token before the cursor hits
        # capacity (see _deliver's at_capacity): post-step cursors at
        # max_seq - 2 mean the NEXT delivery would reach the bound
        cap = jnp.int32(self.max_seq - 2)

        def body(carry, _):
            tokens, active, budget, pos, cache = carry
            logits, stepped = step_model(tokens, cache)
            lengths = jnp.where(active, stepped.lengths, cache.lengths)
            stepped = stepped._replace(lengths=lengths)
            toks, lps = self._sample(logits, temps,
                                     self._resume_keys(seeds, pos),
                                     top_ks)
            toks = jnp.where(active, toks, tokens)
            emitted = active
            budget = jnp.where(active, budget - 1, budget)
            # position advances only where a token was emitted: frozen
            # slots must not burn positions, or a resume after their
            # retirement would re-key mid-stream
            pos = pos + emitted.astype(jnp.int32)
            stop = active & llama.decode_stop_mask(toks, lengths, budget,
                                                   eos_ids, cap)
            return (toks, active & ~stop, budget, pos, stepped), \
                (toks, lps, emitted)

        (last, active, budget, pos, cache), (toks, lps, emitted) = \
            jax.lax.scan(body, (tokens0, active0, budget0, pos0, cache),
                         None, length=self.decode_block)
        return (toks, lps, emitted, (last, active, budget, pos), key,
                cache)

    def _verify_epilogue(self, logits, window, active, stepped):
        """Shared verify-pass tail: greedy tokens + their logprobs, the
        longest agreeing draft run per slot (accept), emit counts (the
        +1 is the pass's guaranteed token; inactive slots emit 0), and
        cursors advanced by exactly what the caller may deliver."""
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, W]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        lps = jnp.take_along_axis(logp, greedy[..., None], axis=-1)[..., 0]
        agree = (greedy[:, :-1] == window[:, 1:]).astype(jnp.int32)
        accept = jnp.sum(jnp.cumprod(agree, axis=1), axis=1)     # [B]
        emit = jnp.where(active, accept + 1, 0)
        lengths = stepped.lengths + emit
        return greedy, lps, emit, stepped._replace(lengths=lengths)

    def _step_fn(self, cache, params, pack, carry, key):
        adapter = pack[:, 5] if self._n_adapters else None

        def step_model(tokens, cache):
            return llama.decode_step(
                params, self.cfg, tokens, cache,
                rope_tables=self.rope_tables, flash=self._flash_decode,
                adapter=adapter, mesh=self.mesh)

        return self._fused_decode_scan(cache, pack, carry, key, step_model)

    def _paged_prefill_fn(self, cache, params, tokens, length, blocks,
                          slot, temp, top_k, key, seed, pos,
                          adapter=None):
        """Paged admission: prefill the prompt, write its KV into the
        slot's allocated ``blocks`` ([ceil(Sb/T)] int32 — entries past
        the prompt's own blocks point at the trash block so bucket
        padding lands nowhere), set the cursor, sample the first token
        (re-keyed on ``seed``/``pos`` — see _resume_keys)."""
        from ..models import paged_llama

        # flash prefill everywhere — shard_map'd per head shard on mesh,
        # same contract as the contiguous _prefill_fn
        logits, k, v, _ = llama.prefill_kv(
            params, self.cfg, tokens, jnp.asarray([length]),
            rope_max=self.max_seq, rope_tables=self.rope_tables,
            flash=True, mesh=self.mesh, adapter=adapter,
            logit_pos=jnp.asarray([length - 1]))
        cache = paged_llama.write_prompt_blocks(cache, k, v, blocks, length)
        cache = cache._replace(lengths=cache.lengths.at[slot].set(length))
        last = logits[0, 0]  # [V] at the true prompt end (logit_pos)
        tok, lp = self._sample(last[None, :], temp[None],
                               self._resume_keys(seed[None], pos[None]),
                               top_k[None])
        return tok[0], lp[0], key, cache

    def _paged_verify_fn(self, cache, params, window, active, key, table,
                         adapter=None):
        """_verify_fn over the paged pool (models.paged_llama.
        paged_verify_step): same greedy/accept/emit semantics, window KV
        routed through the block table."""
        from ..models import paged_llama

        logits, stepped = paged_llama.paged_verify_step(
            params, self.cfg, window, cache, table,
            rope_tables=self.rope_tables, adapter=adapter,
            flash=True, mesh=self.mesh)
        return self._verify_epilogue(logits, window, active, stepped)

    def _paged_step_fn(self, cache, params, pack, carry, key):
        """_step_fn over the block pool. The table rides in the pack's
        trailing [B, MB] columns — host-owned and constant through the
        block (the host pre-allocates blocks covering K tokens per
        slot)."""
        from ..models import paged_llama

        lo = self._PACK_EXTRA + self.EOS_MAX
        table = pack[:, lo:lo + self._mb]
        adapter = pack[:, 5] if self._n_adapters else None

        def step_model(tokens, cache):
            return paged_llama.paged_decode_step(
                params, self.cfg, tokens, cache, table,
                rope_tables=self.rope_tables, adapter=adapter,
                flash=True, mesh=self.mesh)

        return self._fused_decode_scan(cache, pack, carry, key, step_model)

    def _verify_fn(self, cache, params, window, active, key, adapter=None):
        """One speculative verify pass. ``window`` [B, W]: col 0 = each
        slot's pending last token, cols 1.. = prompt-lookup drafts.
        Greedy-only (callers route sampling slots to the decode path).
        Returns (greedy [B, W], emit [B] — how many of greedy's leading
        tokens are real, 0 for inactive slots) and the cache with
        cursors advanced by emit. ``key`` is unused (greedy) but kept so
        the signature matches _step_fn's calling convention."""
        logits, stepped = llama.verify_step(params, self.cfg, window,
                                            cache,
                                            rope_tables=self.rope_tables,
                                            adapter=adapter)
        return self._verify_epilogue(logits, window, active, stepped)

    def _hist_set(self, idx: int, tokens) -> None:
        n = min(len(tokens), self._hist_buf.shape[1])
        self._hist_buf[idx, :n] = tokens[:n]
        self._hist_n[idx] = n

    def _hist_append(self, idx: int, token: int) -> None:
        n = self._hist_n[idx]
        if n < self._hist_buf.shape[1]:
            self._hist_buf[idx, n] = token
            self._hist_n[idx] = n + 1

    def _draft(self, idx: int) -> list[int] | None:
        """Prompt-lookup draft: the K tokens that followed the most
        recent earlier occurrence of the history's trailing 2-gram.
        None = no match (this slot proposes nothing). Pure numpy over
        buffer views — no per-tick list boxing on the decode loop's
        GIL-held critical path."""
        n = int(self._hist_n[idx])
        K = self._spec_k
        if n < 3:
            return None
        h = self._hist_buf[idx, :n]  # view, no copy
        a, b = h[-2], h[-1]
        # positions j <= n-3 with h[j] == a and h[j+1] == b
        hits = np.flatnonzero((h[:-2] == a) & (h[1:-1] == b))
        if len(hits) == 0:
            return None
        j = int(hits[-1])  # most recent earlier occurrence
        cont = h[j + 2:j + 2 + K]
        if cont.size == 0:
            return None
        return cont.tolist() + [0] * (K - cont.size)

    # -- public API ----------------------------------------------------------
    def generate(self, prompt, max_new_tokens: int = 128,
                 temperature: float = 0.0, top_k: int = 0,
                 eos_id=None, adapter: int = 0,
                 logprobs: bool = False, deadline=None,
                 slo_class: str | None = None,
                 kv_sink=None, ingest=None,
                 traceparent: str | None = None,
                 seed: int | None = None,
                 continue_from=None) -> GenStream:
        """Enqueue a prompt (sequence of token ids); returns a GenStream
        yielding generated ids as the device produces them.

        ``temperature=0`` (default) is greedy. ``top_k > 0`` truncates
        sampling to the k most likely tokens; k is CAPPED at
        TOP_K_MAX (64) — the compiled step extracts a fixed top set
        once and masks within it, so larger requested k silently
        saturates to 64 rather than widening the distribution.

        ``eos_id``: a single stop token id, or any iterable of them
        (OpenAI-style ``stop`` sets) — the stream ends at (and includes)
        the first generated token in the set. Checked host-side per
        delivered token; never a compile key.

        ``deadline`` (resilience.Deadline) defaults to the ambient one
        the transport opened from the wire deadline; an expired request
        raises here, and one that expires while queued is dropped at
        admission without a prefill dispatch. With an admission gate
        configured, overload sheds with ``TooManyRequests`` (fast 429/
        RESOURCE_EXHAUSTED) and the brownout band caps
        ``max_new_tokens``.

        ``slo_class`` (resilience.SLO_LATENCY/SLO_THROUGHPUT) defaults
        to the transport's ambient class (``X-SLO-Class`` header /
        ``slo-class`` gRPC metadata): latency-class requests pick up
        slots first; throughput-class tolerates longer queueing, is
        shed/browned-out FIRST under pressure, and still drains via the
        pending line's weighted anti-starvation pickup.

        Disaggregated serving (gofr_tpu/pd/, docs/advanced-guide/
        disaggregated-serving.md): ``kv_sink`` runs the request
        PREFILL-ONLY — the stream delivers exactly the sampled first
        token while the slot's KV ships out through the sink
        ``(HostKV, start, total)`` per prefill chunk (single-device
        contiguous engines only). ``ingest=(HostKV, first_token,
        first_lp)`` is the decode-side mirror: admission installs the
        shipped rows under an ``hbm`` stage lease instead of running a
        prefill, then decodes normally. ``traceparent`` overrides the
        ambient trace context — the cross-process propagation seam, so
        both pools' spans join ONE distributed trace and the tail
        sampler's deterministic trace-id verdict keeps or drops the
        whole handoff together.

        Durable streams (docs/advanced-guide/resilience.md): ``seed``
        fixes the request's sampling PRNG; every sample is keyed on
        ``fold_in(PRNGKey(seed), absolute_position)``, so the stream is
        replayable token-exact from any position. Sampled requests
        without a seed get a deterministic per-engine one (surfaced as
        ``stream.seed`` for resume tokens). ``continue_from=(prompt,
        emitted)`` admits a CONTINUATION of an interrupted stream: the
        prompt + already-emitted tokens prefill as one prompt (the
        emitted tokens extend the same block-chain hashes the radix
        index and T2 keys use, so a warm resume prefills only the
        un-cached tail), ``max_new_tokens`` still counts from the
        ORIGINAL request (the continuation yields at most
        ``max_new_tokens - len(emitted)`` more), and sampling resumes
        at absolute position ``len(emitted)`` — greedy continuations
        are bit-exact by construction, seeded-sampled ones by the
        position re-keying."""
        if self._closed:
            raise GenerationError("generation engine is closed")
        if self._draining:
            raise GenerationError("generation engine is draining")
        if self.down is not None:
            raise GenerationError(f"generation engine is down: {self.down}")
        pos_base = 0
        if continue_from is not None:
            base, emitted = continue_from
            base = np.asarray(base, np.int32).reshape(-1)
            emitted = np.asarray(emitted, np.int32).reshape(-1)
            # the continuation's prefill IS prompt + emitted: one
            # prompt whose block-chain hashes extend the original's, so
            # the radix index / T1 / T2 tiers cover everything a warm
            # replica already computed and only the tail re-prefills
            prompt = np.concatenate([base, emitted])
            pos_base = int(emitted.size)
            max_new_tokens = int(max_new_tokens) - pos_base
            if max_new_tokens <= 0:
                raise GenerationError(
                    f"continue_from carries {pos_base} emitted tokens "
                    "but the request budget allows no more — nothing "
                    "to resume")
        if kv_sink is not None and ingest is not None:
            raise GenerationError("kv_sink and ingest are exclusive "
                                  "(a request is prefill-only OR "
                                  "decode-only, never both)")
        if kv_sink is not None and (self._paged or self.mesh is not None):
            raise GenerationError("kv_sink (prefill-only serving) "
                                  "requires a single-device contiguous "
                                  "engine")
        if ingest is not None:
            self._validate_ingest(ingest, np.asarray(prompt,
                                                     np.int32).reshape(-1))
        if deadline is None:
            deadline = current_deadline()
        if slo_class is None:
            slo_class = current_slo_class()
        elif slo_class not in (SLO_LATENCY, SLO_THROUGHPUT):
            raise GenerationError(f"unknown slo_class {slo_class!r}")
        tenant_spec = None
        tenant = None
        if self.tenancy is not None:
            # resolve the ambient tenant (stamped by the transport's
            # tenant_scope) against the registry: canonical id, class
            # default for untagged traffic, registry-routed LoRA
            tenant_spec = self.tenancy.resolve(current_tenant())
            tenant = tenant_spec.tenant_id
            slo_class = self.tenancy.effective_class(tenant_spec, slo_class)
            adapter = self.tenancy.effective_adapter(tenant_spec,
                                                     int(adapter))
        if deadline is not None and deadline.expired():
            self._count_expired(where="post-handoff" if ingest is not None
                                else "pre-queue")
            raise DeadlineExceeded("deadline expired before generate() "
                                   "was queued")
        if tenant_spec is not None:
            try:
                # per-tenant quota FIRST: an over-quota tenant sheds on
                # its own 429 (reason=tenant_quota) without consuming
                # the shared gate's judgment of global pressure
                self.tenancy.admit(tenant_spec, program="generate",
                                   slo_class=slo_class, gate=self.gate)
            except BaseException:
                self._wide_shed(slo_class, tenant=tenant)
                raise
        try:
            # from here to enqueue, the tenant holds a live concurrency
            # slot: EVERY early raise must give it back (the stream's
            # terminal releases it otherwise)
            if self.gate is not None:
                try:
                    self.gate.admit(self._pending.qsize(),
                                    program="generate",
                                    slo_class=slo_class,
                                    tenant=tenant or "")
                except BaseException:
                    # shed: the request dies HERE, before a stream
                    # exists — its canonical wide event and timeline
                    # marker are the only record that it ever arrived
                    self._wide_shed(slo_class, tenant=tenant)
                    raise
                max_new_tokens = self.gate.cap_tokens(max_new_tokens,
                                                      slo_class=slo_class)
            if eos_id is not None and not isinstance(eos_id,
                                                     (int, np.integer)):
                eos_id = frozenset(int(t) for t in eos_id) or None
            elif isinstance(eos_id, np.integer):
                eos_id = int(eos_id)
            if adapter and not 0 <= adapter < max(self._n_adapters, 1):
                raise GenerationError(
                    f"adapter {adapter} out of range (engine has "
                    f"{self._n_adapters} LoRA adapter slots)")
            if seed is not None:
                seed = int(seed) & 0x7FFFFFFF
            elif temperature > 0:
                # deterministic per-engine auto-seed: same engine seed +
                # same submission order -> same streams, and the value
                # is surfaced on the stream so a resume token can
                # replay it
                seed = (self._seed * 1000003 + next(self._auto_seed)) \
                    & 0x7FFFFFFF
        except BaseException:
            if tenant_spec is not None:
                self.tenancy.release(tenant)
            raise
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        stream = GenStream(next(_REQ_IDS), self, logprobs=logprobs)
        stream.trace["submit"] = time.monotonic()
        stream.prompt_len = len(prompt)
        stream.slo_class = slo_class
        stream.cursor_base = pos_base
        stream.seed = seed
        stream.tenant = tenant or "default"
        stream._tenant_held = tenant_spec is not None
        if len(prompt) == 0:
            stream._q.put(GenerationError("empty prompt"))
            stream._q.put(None)
            self._release_tenant(stream)
            return stream
        # Prompts longer than the largest bucket run through chunked
        # prefill at admission (see _start; paged engines chunk into a
        # dense scratch row, then land it in the pool); the only hard
        # limit is cache capacity minus one position for the first
        # generated token.
        limit = self.max_seq - 1
        if len(prompt) > limit:
            stream._q.put(GenerationError(
                f"prompt length {len(prompt)} exceeds serving limit {limit}"))
            stream._q.put(None)
            self._release_tenant(stream)
            return stream
        if self._paged:
            # fail-fast when the POOL can never hold this prompt — a
            # transient shortage requeues at admission, but a structural
            # one would requeue forever (livelock, caller blocked)
            need = -(-len(prompt) // self._block_t)
            usable = self._alloc.n_blocks - 1
            if need > usable:
                stream._q.put(GenerationError(
                    f"prompt needs {need} pool blocks but the pool has "
                    f"{usable} (raise TPU_PAGED_BLOCKS or "
                    "TPU_PAGED_BLOCK)"))
                stream._q.put(None)
                self._release_tenant(stream)
                return stream
        if traceparent:
            # explicit cross-process context (the P/D ingest path): the
            # shipped request's spans must join the PREFILL worker's
            # trace, not a fresh local one — that one shared trace id
            # is also what makes both processes' tail samplers agree
            from .. import tracing

            ids = tracing.parse_traceparent(traceparent)
            if ids is not None:
                stream.traceparent = traceparent
                stream.trace_id = ids[0]
        if self._observe is not None:
            from .. import tracing

            if not stream.trace_id:
                span = tracing.current_span()
                if span is not None:  # inherit the submitter's context
                    stream.traceparent = span.traceparent()
                    stream.trace_id = span.trace_id
                else:  # mint a trace id so the stage spans still
                    # correlate; no traceparent — they export as roots
                    # of that trace rather than children of a span
                    # nobody ever emits
                    stream.trace_id = tracing._new_trace_id()
            # detail.request_id is the FLIGHT-RECORDER key: registry
            # entry ids and stream request ids are separate counters, so
            # /debug/requests must surface the one /debug/events filters
            # by, or cross-referencing the two pages silently lies
            stream.obs_entry = self._observe.requests.add(
                "generate", "generate", stream.trace_id, stage="queued",
                detail={"request_id": stream.request_id,
                        "prompt_len": len(prompt),
                        "max_new": max_new_tokens,
                        "slo_class": slo_class})
            self._observe.recorder.record(
                "submitted", request_id=stream.request_id,
                trace_id=stream.trace_id, prompt_len=len(prompt),
                max_new=max_new_tokens)
        try:
            with self._admission_lock:
                if self._closed:
                    raise GenerationError("generation engine is closed")
                if self._draining:
                    # drain() sets the flag under this lock; without this
                    # re-check a racing generate() could slip a request in
                    # after the drain snapshot and silently extend the window
                    raise GenerationError("generation engine is draining")
                req = _Request(stream, prompt, max_new_tokens,
                               temperature, top_k, eos_id,
                               adapter=int(adapter), deadline=deadline,
                               slo_class=slo_class)
                req.kv_sink = kv_sink
                req.ingest = ingest
                req.seed = 0 if seed is None else seed
                req.pos_base = pos_base
                if tenant_spec is not None:
                    req.tenant = tenant
                    req.tenant_weight = tenant_spec.weight
                self._pending.put(req)
        except BaseException:
            self._obs_end(stream, "failed", error="rejected at admission")
            raise
        self._obs_gauges()
        self._work.set()
        return stream

    def stats(self) -> dict:
        if self.down is not None:
            return {"down": self.down, "slots": self.n_slots}
        out = {
            "slots": self.n_slots,
            "active": int(self._active.sum()),
            "queued": self._pending.qsize(),
            "draining": self._draining,
            "max_seq": self.max_seq,
            "prompt_buckets": list(self.prompt_buckets),
            "total_requests": self.total_requests,
            "total_tokens": self.total_tokens,
            "scheduler": {
                "prefill_chunk": self._chunk,
                "chunk_interleave": self._chunk_interleave,
                "latency_reserved_slots": self._lat_reserve,
                "queued_latency": self._pending.qsize_class(SLO_LATENCY),
                "queued_throughput":
                    self._pending.qsize_class(SLO_THROUGHPUT),
                "pipeline": self._pipeline_stats(),
            },
        }
        if self.tenancy is not None:
            out["scheduler"]["queued_by_tenant"] = \
                self._pending.qsize_by_tenant()
            out["tenancy"] = self.tenancy.stats()
        if self.mesh is not None:
            out["mesh"] = {
                "devices": int(self.mesh.devices.size),
                "axes": {k: int(v) for k, v in
                         zip(self.mesh.axis_names, self.mesh.devices.shape)
                         if v > 1},
                "kv_shards": self._kv_shards,
                "replacements": self._replacements,
            }
        if self.gate is not None:
            out["admission"] = self.gate.stats()
        if self._kvc is not None:
            out["prefix_cache"] = self._kvc.stats()
        elif self._prefix_idx is not None:
            out["prefix_cache"] = self._prefix_idx.stats()
        if self._paged:
            n_usable = self._alloc.n_blocks - 1
            out["paged"] = {
                "block_size": self._block_t,
                "blocks": n_usable,
                "free": self._alloc.free_blocks,
                "utilization": round(1 - self._alloc.free_blocks
                                     / max(1, n_usable), 3),
                "evictions": self._paged_evictions,
            }
        if self._n_adapters:
            out["lora"] = {"adapters": self._n_adapters,
                           "rank": int(self.params["layers"]
                                       ["lora_a_wq"].shape[-1])}
        if self._spec_k:
            out["spec_decode"] = {
                "k": self._spec_k,
                "windows": self._spec_windows,
                "emitted": self._spec_emitted,
                "tokens_per_window": (
                    round(self._spec_emitted / self._spec_windows, 3)
                    if self._spec_windows else None),
            }
        return out

    def _pipeline_stats(self) -> dict:
        """Decode-pipeline observability (also the deterministic probe
        the depth tests poll): the configured ceiling, the depth the
        NEXT top-up would target (computed from the same facts the loop
        reads), the depth currently in flight, and the measured
        inter-block host-gap distribution — overlapped reaps are the
        blocks whose successor was already queued on-device."""
        # lock-free snapshot: the serving loop appends concurrently and
        # CPython raises if an append lands mid-iteration — retry a few
        # times rather than taking the device lock on a stats poll
        samples: list = []
        for _ in range(4):
            try:
                samples = list(self._gap_samples)
                break
            except RuntimeError:
                continue
        return {
            "depth": self._pipeline.depth,
            "target_depth": self._target_depth(),
            "depth_now": self._depth_now,
            "reaps": self._reaps,
            "overlapped_reaps": self._overlapped_reaps,
            "gap_p50_ms": (round(float(np.median(samples)) * 1e3, 4)
                           if samples else None),
            "gap_samples": len(samples),
        }

    def warmup(self) -> None:
        """Prime every compiled shape (prefill per bucket + the step).

        Safe while serving: the device lock excludes the loop thread for
        the duration (both jits donate the cache buffer); dummy prefills
        go into a FREE slot only (they overwrite that slot's KV), and the
        cursor snapshot restores the lengths afterwards. With every slot
        busy the prefill warmup is skipped — an all-busy engine has those
        shapes compiled already or will compile them on admission."""
        with self._device_lock:
            cursors = np.asarray(jax.device_get(self.cache.lengths))
            free = next((i for i, s in enumerate(self._slots) if s.free), None)
            if free is not None:
                # chunk programs run for prompts past the chunk budget
                # (the largest bucket unless TPU_PREFILL_CHUNK bounds
                # it) — and, with a prefix pool, for ANY hit (prefill
                # resumes mid-prompt through the chunk lattice), so
                # they must be warm whenever the pool exists
                # paged engines chunk into the scratch row; warm those
                # programs against it below instead of the serving cache
                C = self._chunk
                paged_chunks = self._paged and hasattr(self, "_scratch")
                chunked_reachable = (not self._paged
                                     and (self.max_seq - 1 > C
                                          or self._kvc is not None))
                for b in self.prompt_buckets:
                    if b > C:
                        # single-dispatch prefills and final chunks are
                        # both bounded by the chunk budget — wider
                        # buckets never dispatch
                        continue
                    toks = jnp.zeros((1, b), jnp.int32)
                    if paged_chunks:
                        _, _, self._key, self._scratch = \
                            jax.block_until_ready(self._chunk_final_jit(
                                self._scratch, self.params, toks,
                                jnp.int32(0), jnp.int32(0), jnp.int32(1),
                                jnp.int32(0), jnp.float32(0.0),
                                jnp.int32(0), self._key, jnp.int32(0),
                                jnp.int32(0), self._adapter1(None)))
                    if self._paged:
                        # dummy KV lands in the trash block (blocks all
                        # 0); the cursor restore below undoes lengths
                        zeros = jnp.zeros((-(-b // self._block_t),),
                                          jnp.int32)
                        _, _, self._key, self.cache = jax.block_until_ready(
                            self._prefill_jit(
                                self.cache, self.params, toks, jnp.int32(1),
                                zeros, jnp.int32(free), jnp.float32(0.0),
                                jnp.int32(0), self._key, jnp.int32(0),
                                jnp.int32(0), self._adapter1(None)))
                    else:
                        _, _, self._key, self.cache = jax.block_until_ready(
                            self._prefill_jit(
                                self.cache, self.params, toks, jnp.int32(1),
                                jnp.int32(free), jnp.float32(0.0),
                                jnp.int32(0), self._key, jnp.int32(0),
                                jnp.int32(0), self._adapter1(None)))
                    if chunked_reachable:
                        # chunked-admission lattice: the final chunk
                        # compiles per bucket, mid chunks only at C
                        _, _, self._key, self.cache = jax.block_until_ready(
                            self._chunk_final_jit(
                                self.cache, self.params, toks, jnp.int32(0),
                                jnp.int32(free), jnp.int32(1), jnp.int32(0),
                                jnp.float32(0.0), jnp.int32(0), self._key,
                                jnp.int32(0), jnp.int32(0),
                                self._adapter1(None)))
                if chunked_reachable:
                    toks = jnp.zeros((1, C), jnp.int32)
                    self.cache = jax.block_until_ready(self._chunk_mid_jit(
                        self.cache, self.params, toks, jnp.int32(0),
                        jnp.int32(free), jnp.int32(0), jnp.int32(0),
                        jnp.float32(0.0), jnp.int32(0), self._key,
                        jnp.int32(0), jnp.int32(0), self._adapter1(None)))
                if paged_chunks:
                    toks = jnp.zeros((1, C), jnp.int32)
                    self._scratch = jax.block_until_ready(
                        self._chunk_mid_jit(
                            self._scratch, self.params, toks, jnp.int32(0),
                            jnp.int32(0), jnp.int32(0), jnp.int32(0),
                            jnp.float32(0.0), jnp.int32(0), self._key,
                            jnp.int32(0), jnp.int32(0),
                            self._adapter1(None)))
                    self.cache = jax.block_until_ready(
                        self._row_to_blocks_jit(
                            self.cache, self._scratch,
                            jnp.zeros((self._mb,), jnp.int32)))
                    # prefix-hit restore program (trash-block gather)
                    self._scratch = jax.block_until_ready(
                        self._blocks_to_row_jit(
                            self._scratch, self.cache,
                            jnp.zeros((self._mb,), jnp.int32)))
            elif self.logger is not None:
                self.logger.debug({"event": "generator warmup skipped prefill",
                                   "reason": "no free slot"})
            if self._host_write_jit is not None:
                # warm the T1/T2 promote program with an IDENTITY
                # rewrite of pool row 0 (a zero-filled dummy would
                # corrupt a live entry's stored KV); mesh snapshots
                # assemble dense first, like the promote path
                kv = dense_hostkv(self._kv_row_get(self._pool, 0,
                                                   self.max_seq))
                quant = self._pool.quantized
                self._pool = jax.block_until_ready(self._host_write_jit(
                    self._pool, jnp.asarray(kv.k[:, None]),
                    jnp.asarray(kv.v[:, None]),
                    jnp.asarray(kv.k_scale[:, None]) if quant else None,
                    jnp.asarray(kv.v_scale[:, None]) if quant else None,
                    jnp.int32(0)))
            # All-inactive warm pack (host_wins set, active clear, EOS
            # padded, paged table ZEROED — not the live one: an active
            # slot whose cursor sits at an unallocated block boundary
            # would have its clamped row redirect the dummy write INTO
            # its last live block; with zeros every garbage write lands
            # in the trash block). Two calls: the first covers the
            # host-built carry signature (first live block,
            # _last_dev=None); the second feeds the returned carry +
            # chained key back — the STEADY-STATE signature, whose
            # inputs are jit-output-committed (mesh: rep-sharded).
            # Warming only one would re-lower the big fused scan
            # mid-serving.
            warm_pack = self._warm_pack()
            _, _, _, carry_w, self._key, self.cache = \
                jax.block_until_ready(self._step_jit(
                    self.cache, self.params, warm_pack,
                    self._host_carry(), self._key))
            _, _, _, _, self._key, self.cache = jax.block_until_ready(
                self._step_jit(self.cache, self.params, warm_pack,
                               carry_w, self._key))
            if self._spec_k:
                # the verify program too — its first real tick would
                # otherwise compile mid-serving under the device lock,
                # freezing every live stream. All-inactive dispatch:
                # emit 0, cursors frozen, garbage KV lands beyond
                # cursors (paged: in the trash block via a zeroed table)
                # like the step warmup's.
                window = jnp.zeros((self.n_slots, self._spec_k + 1),
                                   jnp.int32)
                if self._paged:
                    _, _, _, cache_w = self._verify_jit(
                        self.cache, self.params, window,
                        jnp.zeros((self.n_slots,), bool), self._key,
                        jnp.zeros_like(jnp.asarray(self._table)),
                        self._adapters())
                else:
                    _, _, _, cache_w = self._verify_jit(
                        self.cache, self.params, window,
                        jnp.zeros((self.n_slots,), bool), self._key,
                        self._adapters())
                self.cache = jax.block_until_ready(cache_w)
            # restore cursors dirtied by the dummy dispatches
            self.cache = self.cache._replace(lengths=jnp.asarray(cursors))

    def kvcache_stats(self) -> dict | None:
        """Tiered prefix-cache stats for /debug/cache; None when no
        prefix cache is configured."""
        if self._kvc is not None:
            return {"kind": "hierarchical", **self._kvc.stats()}
        if self._prefix_idx is not None:
            return {"kind": "paged-shared", **self._prefix_idx.stats()}
        return None

    def load_adapter(self, idx: int, tree: dict) -> None:
        """Install adapter weights into slot ``idx``: ``tree`` maps a
        projection name ('wq'/'wk'/'wv'/'wo') to its (A [L, in, r],
        B [L, r, out]) pair — the layout LoRA training produces per
        layer. Safe while serving: the swap happens under the device
        lock between iterations; params are never donated, so in-flight
        dispatches keep their snapshot."""
        if not self._n_adapters:
            raise GenerationError("engine built without lora_adapters")
        if not 0 < idx < self._n_adapters:
            raise GenerationError(
                f"adapter slot {idx} invalid (1..{self._n_adapters - 1}; "
                "slot 0 is the base no-op)")
        for name in tree:
            if f"lora_a_{name}" not in self.params["layers"]:
                raise GenerationError(f"unknown LoRA target {name!r}")
        with self._device_lock:
            layers = dict(self.params["layers"])
            for name, (a, b) in tree.items():
                ka, kb = f"lora_a_{name}", f"lora_b_{name}"
                layers[ka] = layers[ka].at[:, idx].set(
                    jnp.asarray(a, layers[ka].dtype))
                layers[kb] = layers[kb].at[:, idx].set(
                    jnp.asarray(b, layers[kb].dtype))
            self.params = {**self.params, "layers": layers}
            if self._prefix_idx is not None:
                # Stored prefix KV was computed through the OLD adapter
                # weights — restoring it after the swap would serve
                # wrong attention keys (same hazard as cross-adapter
                # reuse). Invalidating inside the device lock, AFTER the
                # swap, serializes against the loop's match/store: no
                # old-weight entry can be stored after we invalidate,
                # and the index is only ever mutated under this lock.
                self._prefix_idx.invalidate_adapter(idx)
            if self._kvc is not None:
                # ALL tiers (same hazard as above): T0/T1 drop locally;
                # T2 bumps the adapter's Redis epoch, which renames the
                # shared namespace for every replica at once
                self._kvc.invalidate_adapter(idx)

    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful shutdown, phase 1: refuse NEW requests (generate()
        raises), keep serving everything already accepted — active slots
        and the admission queue — until idle or ``timeout``. Returns
        True when fully drained; either way the caller still owns the
        final close(). The k8s-style stop sequence is
        ``app.stop(grace_s=...)``: listeners stay up through the drain
        so in-flight streams complete over their live connections."""
        with self._admission_lock:
            self._draining = True
        def idle() -> bool:
            return (not self._active.any() and self._pending.empty()
                    and self._admitting == 0)

        deadline = time.monotonic() + max(0.0, timeout)
        while time.monotonic() < deadline:
            if idle():
                return True
            time.sleep(0.05)
        return idle()

    def close(self) -> None:
        with self._admission_lock:
            self._closed = True
        self._work.set()
        self._thread.join(timeout=10.0)
        # the registry must not keep claiming bytes for a closed engine
        # (hbmwatch reconciles accounted vs live bytes; the buffers
        # themselves die with this instance's last reference)
        hbm.release(owner=self)
        if self._kvc is not None and self._kvc.redis is not None:
            try:  # the engine owns the T2 client (KVCacheOptions.redis)
                self._kvc.redis.client.close()
            except Exception:
                pass
        for slot in self._slots:
            if slot.request is not None:
                slot.request.stream._q.put(GenerationError("engine closed"))
                slot.request.stream._q.put(None)
                self._obs_end(slot.request.stream, "failed",
                              error="engine closed")
                slot.request = None
        while True:
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                break
            req.stream._q.put(GenerationError("engine closed"))
            req.stream._q.put(None)
            self._obs_end(req.stream, "failed", error="engine closed")

    # -- the serving loop ----------------------------------------------------
    def _pack_width(self) -> int:
        return (self._PACK_EXTRA + self.EOS_MAX
                + (self._mb if self._paged else 0))

    def _warm_pack(self):
        """All-inactive dispatch pack for warmup: host_wins set so the
        carry is ignored, active clear so no cursor moves, EOS rows
        padded, (paged) table zeroed so garbage lands in the trash
        block."""
        p = np.zeros((self.n_slots, self._pack_width()), np.int32)
        p[:, 6] = 1
        p[:, self._PACK_EXTRA:self._PACK_EXTRA + self.EOS_MAX] = \
            llama.EOS_PAD
        return jnp.asarray(p)

    def _host_carry(self):
        """Host-built device slot-state carry — the first block's (and
        post-recovery's) stand-in for the previous dispatch's outputs.
        np.array copies before conversion: see _dev's aliasing note."""
        return (jnp.asarray(np.array(self._last_tokens)),
                jnp.asarray(np.array(self._active)),
                jnp.asarray(np.array(self._budgets)),
                jnp.asarray(np.array(self._pos_abs)))

    def _dispatch_pack(self):
        """The decode dispatch's ONE host input: every host-owned
        per-slot array packed into a [B, W] int32 matrix (temps ride as
        f32 bit patterns; the scan prologue bitcasts them back). These
        arrays change only at admission/retirement — re-uploading them
        as a handful of separate h2d transfers per block cost real
        milliseconds through the tunnel (the 1.9 ms dispatch floor the
        ROADMAP names), so the pack re-uploads as a single transfer and
        ONLY when a mutation site marked it dirty (_touch); in steady
        state the cached device copy is reused and the dispatch carries
        zero host payload. The np staging buffer is fresh per build and
        never mutated after conversion, so CPU-backend zero-copy
        aliasing (the r4 token-carry flake) cannot bite."""
        if self._pack is None or self._pack_dirty:
            E = self.EOS_MAX
            p = np.empty((self.n_slots, self._pack_width()), np.int32)
            p[:, 0] = self._last_tokens
            p[:, 1] = self._active
            p[:, 2] = self._budgets
            p[:, 3] = self._temps.view(np.int32)
            p[:, 4] = self._top_ks
            p[:, 5] = self._slot_adapter
            p[:, 6] = self._host_wins
            p[:, 7] = self._slot_seed
            p[:, 8] = self._pos_abs
            p[:, self._PACK_EXTRA:self._PACK_EXTRA + E] = self._eos_mat
            if self._paged:
                p[:, self._PACK_EXTRA + E:] = self._table
            self._pack = jnp.asarray(p)
            self._pack_dirty = False
        return self._pack

    def _dev(self, name: str, host):
        """Device mirror of a host-owned dispatch array. These arrays
        (active mask, temps, top-ks, adapters, block table) change only
        at admission/retirement; re-uploading them every block cost a
        handful of h2d transfers per dispatch — real milliseconds
        through the tunnel. Mutation sites mark them dirty (_touch).

        The np source is COPIED before device conversion: on the CPU
        backend jnp.asarray ALIASES numpy memory zero-copy, and
        dispatches are async — a host mutation (in-flight admission,
        post-dispatch bookkeeping) would otherwise be read by the
        still-executing block. That aliasing was the r4 token-carry
        flake's root cause."""
        if name in self._dirty or name not in self._mirror:
            self._mirror[name] = jnp.asarray(np.array(host))
            self._dirty.discard(name)
        return self._mirror[name]

    def _touch(self, *names: str) -> None:
        # one call dirties both representations: the legacy per-name
        # mirrors (_dev — verify/predict paths) and the coalesced
        # decode dispatch pack
        self._dirty.update(names)
        self._pack_dirty = True

    def _adapters(self):
        """[B] adapter ids for batch dispatches, or None when LoRA is
        off (None is an empty pytree: the jit signature stays stable
        and the model paths skip the gather entirely)."""
        if not self._n_adapters:
            return None
        return self._dev("adapters", self._slot_adapter)

    def _adapter1(self, req: "_Request | None"):
        if not self._n_adapters:
            return None
        return jnp.asarray([0 if req is None else req.adapter], jnp.int32)

    def _admit(self, defer_lattice: bool = False) -> int:
        """Admit pending requests into free slots; returns the number
        started. ``defer_lattice``: in-flight admission (see
        _admit_inflight) must NOT start a chunk-lattice admission — the
        lattice interleaves its own decode blocks, which would
        double-decode every active slot from the un-reaped outer
        block's stale _last_tokens — so lattice-path requests stay
        queued until the outer reap and the next synchronous pass."""
        started = 0
        for idx, slot in enumerate(self._slots):
            if not slot.free:
                continue
            # _admitting goes up BEFORE the pop: between get_nowait and
            # any later increment a request would be invisible to all of
            # drain()'s idle conditions (not pending, not active, not
            # admitting) and a graceful shutdown could kill an accepted
            # stream. Only this thread mutates the counter.
            self._admitting += 1
            try:
                # slot reservation: this pick may only go to a
                # throughput-class request if filling it still leaves
                # the reserved latency slots free
                free_now = sum(1 for s in self._slots if s.free)
                try:
                    req = self._pending.get_nowait(
                        allow_throughput=free_now > self._lat_reserve)
                except queue.Empty:
                    return started
                if defer_lattice and self._needs_lattice(req):
                    # a lattice admission cannot start under an
                    # un-reaped block (its interleaved decode ticks
                    # would re-decode stale tokens): return the
                    # request to the HEAD of its class line for the
                    # next synchronous pass. Pop-then-push-front
                    # instead of peek: with per-class lines a
                    # concurrent put() could otherwise change which
                    # head the verdict applied to. The flag drops the
                    # pipeline to depth 1 so that synchronous pass
                    # arrives within one reap instead of never (a full
                    # pipeline would otherwise re-dispatch forever).
                    self._lattice_deferred = True
                    self._pending.put_front(req)
                    return started
                if req.stream.cancelled.is_set():
                    req.stream._q.put(None)
                    self._obs_end(req.stream, "cancelled", tokens=0)
                    continue
                if req.deadline is not None and req.deadline.expired():
                    # the caller's wire deadline ran out while queued:
                    # fail fast, never dispatch its prefill. Ingested
                    # (P/D-shipped) requests record where=post-handoff:
                    # the budget burned AFTER the pool boundary, and
                    # the wide event on THIS worker is the record
                    where = self._expiry_where(req, "queue")
                    self._count_expired(where=where,
                                        request_id=req.stream.request_id)
                    req.stream.where = where
                    wait_s = time.monotonic() - req.enqueued_at
                    req.stream._q.put(DeadlineExceeded(
                        f"deadline expired after {wait_s:.3f}s in the "
                        "admission queue"))
                    req.stream._q.put(None)
                    self._obs_end(req.stream, "failed",
                                  error="deadline expired in queue",
                                  wait_s=round(wait_s, 6))
                    continue
                try:
                    # arbiter checkpoint: one zero-byte lease per
                    # admission. The seeded HBM_ALLOC chaos seam and
                    # the budget-overshoot reclaim both live behind
                    # it, and a failure sheds THIS request (429 +
                    # Retry-After through the gate's shed surface)
                    # instead of raising into the loop's device-loss
                    # recovery — memory pressure degrades the
                    # request, never the engine
                    hbm.check("engine")
                except hbm.HBMExhausted as e:
                    self._shed_oom(req, e)
                    continue
                blocks = None
                if self._paged:
                    blocks = (self._ingest_blocks(req)
                              if req.ingest is not None
                              else self._paged_admission_blocks(req))
                    if blocks is None:
                        # transient pool pressure: requeue and let active
                        # slots retire blocks. (FIFO order is not
                        # preserved across the requeue — pool-pressure
                        # reordering is documented engine behavior.)
                        self._pending.put(req)
                        return started
                self._start(idx, slot, req, blocks)
                started += 1
            finally:
                self._admitting -= 1
        return started

    def _needs_lattice(self, req: _Request) -> bool:
        """Would admitting ``req`` run the chunk-prefill lattice?
        True for prompts past the largest bucket, and for paged prefix
        hits (a hit resumes the lattice from the match point).
        SharedPrefixIndex.match is pure — hit/miss accounting happens
        in accept()/reject() at real admission — so peeking here costs
        one LCP scan and perturbs nothing. The verdict is memoized on
        the request, keyed by the index's version counter: the in-flight
        admission path re-peeks the queue head every ~2 ms poll, and an
        O(entries x prompt) LCP rescan of an unchanged index on the
        serving-loop thread is pure waste."""
        if req.ingest is not None:
            # shipped-KV admission: the install is one row write, no
            # prefill dispatch and no chunk lattice regardless of
            # prompt length — always safe under an un-reaped block
            return False
        L = len(req.prompt)
        if L > self._chunk:
            # past the chunk budget (== the largest bucket by default;
            # smaller when TPU_PREFILL_CHUNK bounds per-dispatch
            # prefill work) the prompt admits through the lattice
            return True
        if not self._paged and self._kvc is not None:
            # contiguous engines: a usable tier hit ALSO resumes the
            # chunk lattice mid-prompt, so in-flight admission must
            # defer it exactly like the paged path (starting the
            # lattice under an un-reaped outer block double-decodes
            # active slots). The memoized _kv_match keeps the verdict
            # consistent with the real admission's — a T2 consult does
            # network I/O, and peeking a DIFFERENT answer than the
            # restore would re-open the hazard this guard closes.
            mt = self._kv_match(req)
            if mt is None:
                return False
            m_eff = clamp_restore_len(mt.matched_len, L)
            return (m_eff >= self.prompt_buckets[0]
                    and self._lattice_resume_valid(L, m_eff))
        if self._paged and self._prefix_idx is not None:
            ver = self._prefix_idx.version
            if req.lattice_peek is not None and req.lattice_peek[0] == ver:
                return req.lattice_peek[1]
            _, m = self._prefix_idx.match(
                np.asarray(req.prompt, np.int32), req.adapter)
            verdict = bool(m) and self._lattice_resume_valid(L, m)
            req.lattice_peek = (ver, verdict)
            return verdict
        return False

    def _paged_admission_blocks(self, req: _Request
                                ) -> "tuple[list, int, list] | None":
        """Blocks for one paged admission: consult the prefix index,
        take the slot's hold on any shared blocks, allocate the fresh
        remainder (evicting LRU prefix entries under pressure). Returns
        (shared, matched_tokens, fresh) with one reference per block
        held for the slot — or None (nothing held) when the pool cannot
        cover the request right now."""
        shared, m = [], 0
        if self._prefix_idx is not None:
            shared, m = self._prefix_idx.match(
                np.asarray(req.prompt, np.int32), req.adapter)
            if m and not self._lattice_resume_valid(len(req.prompt), m):
                shared, m = [], 0  # off-lattice window: full recompute
            if shared:
                # take the slot's hold NOW: the evict-retry below could
                # otherwise free the matched entry's blocks out from
                # under us
                self._alloc.ref(shared)
        need = -(-len(req.prompt) // self._block_t) - len(shared)
        fresh = self._alloc.alloc(need)
        while fresh is None and self._prefix_idx is not None \
                and self._prefix_idx.evict_one():
            fresh = self._alloc.alloc(need)
        if fresh is None:
            if shared:
                self._alloc.free(shared)
            return None
        if self._prefix_idx is not None:
            if m:
                self._prefix_idx.accept(shared)
                if self.metrics is not None:
                    self.metrics.increment_counter(
                        "app_tpu_prefix_cache_hits_total")
            else:
                self._prefix_idx.reject()
        return shared, m, fresh

    def _admit_prefill(self, idx: int, req: _Request) -> tuple[int, float]:
        """Run the request's prompt through prefill into slot ``idx`` and
        return (first sampled token, its logprob).

        Prompts within the bucket lattice go through one padded prefill
        dispatch. Longer prompts run CHUNKED: full chunks of the largest
        bucket size C from position 0, then a final chunk of bucket size
        Sb that ENDS exactly at the prompt end — it may overlap the tail
        of the last full chunk (those positions recompute to identical
        KV: same tokens, same positions, same prefix visibility), which
        keeps every dispatch on the compiled lattice with zero padding
        waste in the cache: capacity used == prompt length."""
        L = len(req.prompt)
        C = self.prompt_buckets[-1]
        self._slot_adapter[idx] = req.adapter
        self._touch("adapters")
        pos = self._prefix_restore(idx, req, L, C)
        if pos == 0 and L <= self._chunk:
            Sb = pad_bucket(L, self.prompt_buckets)
            padded = np.zeros((1, Sb), np.int32)
            padded[0, :L] = req.prompt
            tok, lp, self._key, self.cache = self._prefill_jit(
                self.cache, self.params, jnp.asarray(padded), jnp.int32(L),
                jnp.int32(idx), jnp.float32(req.temperature),
                jnp.int32(req.top_k), self._key, jnp.int32(req.seed),
                jnp.int32(req.pos_base), self._adapter1(req))
            return int(tok), float(lp)
        return self._chunk_lattice("cache", idx, req, pos)

    def _lattice_resume_valid(self, L: int, m: int) -> bool:
        """Can the chunk lattice resume at position ``m`` of an L-token
        prompt? The final chunk's bucket must not pad wider than the
        prompt (a negative window start would slice off the compiled
        lattice) — the shared reject-to-miss guard for prefix hits on
        both engine kinds. Mirrors ``_chunk_lattice``'s loop: mid
        chunks advance by the configured chunk budget."""
        T = self._chunk
        rem = L - m
        while rem > T:
            rem -= T
        return L - pad_bucket(rem, self.prompt_buckets) >= 0

    def _chunk_lattice(self, attr: str, slot: int, req: _Request,
                       pos: int = 0,
                       track_slot: int | None = None) -> tuple[int, float]:
        """Run the chunked-prefill lattice for ``req.prompt[pos:]``
        against the cache at ``getattr(self, attr)`` ("cache" for the
        contiguous engine, "_scratch" for paged long-prompt admission),
        writing into batch row ``slot``. Between mid chunks (interleave
        on) the loop yields the device: one admission pass for NEW
        arrivals — a bucket-lattice request reaching the pending line
        mid-prefill gets its own prefill dispatched within one chunk
        budget instead of waiting out this whole prompt — then one
        decode block for the live batch, so long admissions never
        stall active decode streams. With ``prefill_chunk <= 0`` the
        chunks dispatch back-to-back (the head-of-line contrast arm
        tools/slo_bench.py measures against). Returns the final
        chunk's sampled (token, logprob) — or (0, 0.0) when the
        request was cancelled or deadline-expired mid-lattice (the
        token is discarded anyway: _deliver retires cancelled slots
        before use). ``track_slot``: the serving slot the timeline
        renders these chunk slices on (paged admissions dispatch
        against scratch row 0 but serve slot ``idx``)."""
        L = len(req.prompt)
        T = self._chunk
        tslot = slot if track_slot is None else track_slot
        ship_cap = L
        if req.kv_sink is not None:
            # prefill-only: the FINAL chunk re-computes its window
            # [L - Sb, L) reading already-quantized cache for the
            # earlier positions, so on int8 caches the overlap's
            # layer>0 KV differs from the mid-chunk version by one
            # int8 round trip — and the slot row keeps the FINAL
            # version. Mid-chunk shipping stops at the final window's
            # start; the overlap ships from the settled row in _start,
            # keeping the shipped stream bit-identical to the row (the
            # decode pool must replicate THIS engine's cache exactly).
            rem = L - pos
            while rem > T:
                rem -= T
            ship_cap = L - pad_bucket(rem, self.prompt_buckets)
        while L - pos > T:
            if req.stream.cancelled.is_set():
                return 0, 0.0
            if self._expire_mid_lattice(req, pos):
                return 0, 0.0
            chaos.fire(chaos.GENERATOR_CHUNK)
            chunk = req.prompt[pos:pos + T]
            t0c = time.monotonic() if self._tl is not None else 0.0
            setattr(self, attr, self._chunk_mid_jit(
                getattr(self, attr), self.params,
                jnp.asarray(chunk[None, :]), jnp.int32(pos),
                jnp.int32(slot), jnp.int32(0), jnp.int32(0),
                jnp.float32(0.0), jnp.int32(0), self._key,
                jnp.int32(0), jnp.int32(0), self._adapter1(req)))
            pos += T
            req.stream.chunks += 1
            if self._tl is not None:
                # host dispatch slice (the device work runs async
                # behind it); index + length make the lattice's shape
                # readable on the slot's track
                self._tl.chunk(t0c, time.monotonic(), tslot,
                               req.stream.chunks - 1, T,
                               req.stream.request_id)
            if self.metrics is not None:
                self.metrics.increment_counter("app_tpu_prefill_chunks_total")
            if req.kv_sink is not None and attr == "cache":
                # prefill-only: stream the chunk's KV out NOW — the
                # decode peer's host-side assembly (and the wire
                # transfer) overlaps the remaining chunks' compute, so
                # the handoff costs one tail ship, not a whole-prompt
                # serialization (capped before the final window — see
                # ship_cap above). The row read blocks on this chunk's
                # dispatch; a ship failure cancels the request (never
                # the loop).
                if not self._ship_range(attr, slot, req,
                                        min(pos, ship_cap)):
                    return 0, 0.0
            if not self._chunk_interleave:
                continue
            # Yield between chunks — everything below already runs
            # under the device lock (the lattice is only entered from
            # the loop thread's admission pass):
            #   1. admit new arrivals into OTHER free slots (this
            #      slot is claimed by _start); lattice-path arrivals
            #      stay queued — one chunk stream at a time;
            #   2. one decode block for the live batch, reaped
            #      synchronously so its tokens deliver before the
            #      next chunk occupies the device.
            self._admit(defer_lattice=True)
            inflight = self._decode_tick()
            if inflight is not None:
                inflight.reap()
        if req.stream.cancelled.is_set():
            return 0, 0.0
        if self._expire_mid_lattice(req, pos):
            return 0, 0.0
        rem = L - pos
        Sb = pad_bucket(rem, self.prompt_buckets)
        final = req.prompt[L - Sb:]
        tok, lp, self._key, new_cache = self._chunk_final_jit(
            getattr(self, attr), self.params, jnp.asarray(final[None, :]),
            jnp.int32(L - Sb), jnp.int32(slot), jnp.int32(L),
            jnp.int32(Sb - 1), jnp.float32(req.temperature),
            jnp.int32(req.top_k), self._key, jnp.int32(req.seed),
            jnp.int32(req.pos_base), self._adapter1(req))
        setattr(self, attr, new_cache)
        return int(tok), float(lp)

    def _expire_mid_lattice(self, req: _Request, pos: int) -> bool:
        """Deadline check between chunk dispatches: a half-prefilled
        request whose caller already gave up must stop burning device
        time NOW — its remaining chunks, its decode slot, all of it.
        Fails the stream with DeadlineExceeded and flips the cancelled
        flag so the existing cancel-retire path (parked cursor, block
        release at _deliver/_retire) cleans the slot up."""
        if req.deadline is None or not req.deadline.expired():
            return False
        self._count_expired(where="mid-prefill",
                            request_id=req.stream.request_id)
        req.stream.where = "mid-prefill"
        req.stream.failed = "deadline expired mid-prefill"
        req.stream._q.put(DeadlineExceeded(
            f"deadline expired after {pos}/{len(req.prompt)} prompt "
            "tokens were prefilled"))
        req.stream.cancel()
        if self._observe is not None:
            self._observe.recorder.record(
                "expired_mid_prefill", request_id=req.stream.request_id,
                trace_id=req.stream.trace_id, prefilled=pos,
                prompt_len=len(req.prompt))
        return True

    def _expire_decoding(self, idx: int, slot: _Slot) -> bool:
        """Deadline check at the reap, once per slot per block: a
        decoding stream whose caller's wire deadline ran out stops
        consuming its slot NOW — even with further blocks already in
        flight (the pipelined dispatches' tokens for this slot are
        dropped by the snapshot/emitted guards, and _retire's host_wins
        deactivates it for every dispatch after those). Fails the
        stream with DeadlineExceeded and retires the slot."""
        req = slot.request
        if req is None or req.deadline is None or not req.deadline.expired():
            return False
        where = self._expiry_where(req, "mid-decode")
        self._count_expired(where=where,
                            request_id=req.stream.request_id)
        req.stream.where = where
        req.stream.failed = "deadline expired mid-decode"
        req.stream._q.put(DeadlineExceeded(
            f"deadline expired after {slot.generated} generated tokens"))
        req.stream.cancel()
        if self._observe is not None:
            self._observe.recorder.record(
                "expired_mid_decode", request_id=req.stream.request_id,
                trace_id=req.stream.trace_id, tokens=slot.generated)
        self._retire(idx, slot)
        return True

    # -- paged-mode host side ------------------------------------------------
    def _paged_admit_prefill(self, idx: int, req: _Request,
                             shared: list[int], m: int,
                             fresh: list[int]) -> tuple[int, float]:
        """Paged admission. ``shared``/``m``: prefix-cache hit — m
        tokens of KV already live in ``shared`` pool blocks (the slot
        holds a reference, taken at _admit); ``fresh``: newly allocated
        blocks for the rest. Bucket-lattice prompts without a hit go
        through one padded prefill dispatch; everything else (long
        prompts, any hit) resumes the chunk lattice on the dense
        scratch row — for hits, the shared blocks gather into the
        scratch first and only the FRESH region writes back, so shared
        blocks are never rewritten."""
        L = len(req.prompt)
        T = self._block_t
        blocks = shared + fresh
        self._slot_adapter[idx] = req.adapter
        self._touch("adapters")
        # Register the blocks as the slot's FIRST — every exit path
        # (cancel mid-lattice included) then frees them through the
        # normal _retire, instead of leaking pool blocks the allocator
        # handed _admit (_start's exception path clears this state
        # itself before freeing). The TABLE row, however, stays zeroed
        # (trash-routed) until admission completes: the device cursor is
        # still the slot's STALE previous length, and the decode ticks
        # interleaved into the chunk lattice write garbage KV for
        # inactive slots at that cursor — through an installed row that
        # garbage would land inside the new blocks (for a prefix hit,
        # inside SHARED blocks, permanently corrupting every other
        # holder; the write-back only repairs the fresh region).
        self._slot_blocks[idx] = blocks
        self._cursors[idx] = L
        if m == 0 and L <= self._chunk:
            Sb = pad_bucket(L, self.prompt_buckets)
            n_wr = -(-Sb // T)
            write_blocks = blocks + [0] * (n_wr - len(blocks))
            padded = np.zeros((1, Sb), np.int32)
            padded[0, :L] = req.prompt
            tok, lp, self._key, self.cache = self._prefill_jit(
                self.cache, self.params, jnp.asarray(padded), jnp.int32(L),
                jnp.asarray(write_blocks, jnp.int32), jnp.int32(idx),
                jnp.float32(req.temperature), jnp.int32(req.top_k),
                self._key, jnp.int32(req.seed), jnp.int32(req.pos_base),
                self._adapter1(req))
            self._write_table_row(idx)
            return int(tok), float(lp)
        if m > 0:
            # restore: shared blocks -> scratch positions [0, m)
            read_blocks = shared + [0] * (self._mb - len(shared))
            self._scratch = self._blocks_to_row_jit(
                self._scratch, self.cache,
                jnp.asarray(read_blocks, jnp.int32))
            # zero-copy block-share hit: the wide event and timeline
            # call it tier "paged" (the paged engine has no t0/t1/t2)
            req.stream.cache_tier = "paged"
            req.stream.cache_tokens = m
            if self._tl is not None:
                self._tl.kvcache("paged", m, idx)
        tok, lp = self._chunk_lattice("_scratch", 0, req, pos=m,
                                      track_slot=idx)
        if req.stream.cancelled.is_set():
            return tok, lp  # slot retires at _deliver; blocks free there
        # write back only the FRESH region: scratch rows for the shared
        # blocks (identical data) route to the trash block
        write_blocks = [0] * len(shared) + fresh \
            + [0] * (self._mb - len(blocks))
        self.cache = self._row_to_blocks_jit(
            self.cache, self._scratch,
            jnp.asarray(write_blocks, jnp.int32))
        self.cache = self.cache._replace(
            lengths=self.cache.lengths.at[idx].set(L))
        self._write_table_row(idx)
        return tok, lp

    def _write_table_row(self, idx: int) -> None:
        """Clamped table row: entries past the slot's live blocks repeat
        the last one (the kernel's DMA-skip); empty slots stay on the
        trash block. Slice-assigned — this runs on the GIL-held serving
        loop."""
        blocks = self._slot_blocks[idx]
        self._touch("table")
        if not blocks:
            self._table[idx, :] = 0
            return
        n = min(len(blocks), self._mb)
        self._table[idx, :n] = blocks[:n]
        self._table[idx, n:] = blocks[n - 1]

    def _ensure_blocks(self, horizon: int | None = None) -> None:
        """Pre-dispatch invariant: every active slot owns blocks covering
        its next ``horizon`` positions (default: one decode block; verify
        ticks pass their window width). On pool exhaustion the slot that
        cannot grow is retired early (its stream ends as if at capacity)
        — freeing its blocks for the rest of the batch; the eviction is
        logged and counted."""
        K = horizon or self.decode_block
        T = self._block_t
        for idx, slot in enumerate(self._slots):
            if not self._active[idx]:
                continue
            cur = int(self._cursors[idx])
            hi = cur + K  # highest write is at position hi - 1
            stop = int(self._stop_cursors[idx])
            if horizon is None and stop > 0:
                # decode writes freeze at the device stop cursor: never
                # demand (or starvation-retire for) blocks a finished
                # stream will not touch. Verify windows keep the full
                # horizon — their junk rows past acceptance are the
                # clamped-table contract.
                hi = min(hi, stop)
                if hi <= cur:
                    continue  # device-stopped; awaiting the reap
            need = min((hi - 1) // T + 1, self._mb)
            if len(self._slot_blocks[idx]) >= need:
                continue  # row already written at admission/last growth
            starved = False
            while len(self._slot_blocks[idx]) < need:
                got = self._alloc.alloc(1)
                if got is None:
                    # prefix entries are the pressure valve: evict LRU
                    # stored prefixes before truncating a live stream
                    if self._prefix_idx is not None and \
                            self._prefix_idx.evict_one():
                        continue
                    starved = True
                    break
                self._slot_blocks[idx].extend(got)
            if starved:
                self._paged_evictions += 1
                if self.logger is not None:
                    self.logger.warn({
                        "event": "paged pool exhausted: stream truncated",
                        "slot": idx,
                        "generated": slot.generated,
                        "free_blocks": self._alloc.free_blocks})
                if self.metrics is not None:
                    self.metrics.increment_counter(
                        "app_tpu_paged_evictions_total")
                self._retire(idx, slot)
                continue
            self._write_table_row(idx)

    def _kv_match(self, req: _Request, prompt: np.ndarray | None = None):
        """Request-memoized ``CacheManager.match``, keyed by the
        manager's version counter. The in-flight admission peek
        (_needs_lattice) and the real admission must see ONE verdict —
        a disagreement would start a chunk lattice inside an in-flight
        admission — and a T2 consult does network I/O the ~2 ms peek
        poll must not repeat. Only the serving-loop thread calls this,
        and it cannot store between peek and admit, so a memo keyed by
        version is exact."""
        ver = self._kvc.version
        if req.kv_match is not None and req.kv_match[0] == ver:
            return req.kv_match[1]
        if prompt is None:
            prompt = np.asarray(req.prompt, np.int32)
        mt = self._kvc.match(prompt, req.adapter)
        req.kv_match = (ver, mt)
        return mt

    def _kv_row_get(self, store, row: int, plen: int,
                    start: int = 0) -> "HostKV | ShardedHostKV":
        """Fetch positions ``[start, plen)`` of one pool/cache row to
        host numpy — the spill half of T1 offload, the read half of
        T2 write-through, and (``start > 0``) the incremental KV-ship
        reads of a prefill worker. On a MESH the snapshot is
        PER-SHARD: each tp shard's head range reads straight off its
        own device shard (no cross-device gather on the spill path) —
        a ShardedHostKV whose parts the offload tiers store and frame
        verbatim; the restore side assembles the canonical dense row
        (dense_hostkv) before the placed write."""
        quant = store.k_scale is not None
        if self.mesh is None:
            return HostKV(
                np.asarray(store.k[:, row, start:plen]),
                np.asarray(store.v[:, row, start:plen]),
                np.asarray(store.k_scale[:, row, start:plen])
                if quant else None,
                np.asarray(store.v_scale[:, row, start:plen])
                if quant else None)
        k_p = self._row_shard_parts(store.k, row, start, plen)
        v_p = self._row_shard_parts(store.v, row, start, plen)
        ks_p = (self._row_shard_parts(store.k_scale, row, start, plen)
                if quant else None)
        vs_p = (self._row_shard_parts(store.v_scale, row, start, plen)
                if quant else None)
        parts = tuple(HostKV(k_p[i], v_p[i],
                             ks_p[i] if quant else None,
                             vs_p[i] if quant else None)
                      for i in range(len(k_p)))
        return parts[0] if len(parts) == 1 else ShardedHostKV(parts)

    @staticmethod
    def _row_shard_parts(arr, row: int, start: int, stop: int) -> list:
        """One batch row's positions ``[start, stop)`` read per tp
        shard of a [L, B, Smax, KV(, hd)] cache leaf: walk the leaf's
        addressable shards, keep the shard covering ``row`` for each
        distinct KV-head offset (replicated axes repeat the same
        heads — first wins), and return the pieces in head order.
        Each read is a single-device ``device_get`` of that shard's
        slab — the mesh never assembles the row to spill it."""
        parts: dict[int, np.ndarray] = {}
        B = arr.shape[1]
        for sh in arr.addressable_shards:
            idx = sh.index
            bsl = idx[1]
            b0 = bsl.start or 0
            b1 = B if bsl.stop is None else bsl.stop
            if not (b0 <= row < b1):
                continue
            h0 = idx[3].start or 0
            if h0 in parts:
                continue
            parts[h0] = np.asarray(sh.data)[:, row - b0, start:stop]
        return [parts[h] for h in sorted(parts)]

    def _offload_victim(self, victim) -> None:
        """Spill a T0-evicted entry's pool row to the host tier. MUST
        run before the dispatch that overwrites the row (store/promote
        call it between claiming the row and copying into it)."""
        if victim is None or not self._kvc.wants_offload:
            return
        plen = min(len(victim.key), self.max_seq)
        self._kvc.offload(victim, self._kv_row_get(self._pool,
                                                   victim.row, plen))

    def _promote_hostkv(self, mt) -> int | None:
        """Land a T1/T2 match's host KV in a T0 pool row (device_put +
        one compiled row write) and register it under the entry's full
        key — the next hit on this prefix is a T0 row copy. Returns the
        row, or None when the payload cannot serve this engine (shape/
        quantization drift: treat as a miss, never an error). Sharded
        snapshots assemble to the canonical dense row first — which is
        what lets T1 entries survive even a mesh-SHAPE change across
        device-loss re-placement."""
        kv = dense_hostkv(mt.hostkv) if mt.hostkv is not None else None
        quant = self._pool.quantized
        if (kv is None or kv.plen > self.max_seq or len(mt.key) < kv.plen
                or (quant and kv.k_scale is None)
                or kv.k.shape[0] != self._pool.k.shape[0]
                or kv.k.shape[2:] != self._pool.k.shape[3:]):
            return None
        row, victim = self._kvc.store(mt.key[:kv.plen], mt.adapter)
        self._offload_victim(victim)

        def pad(a, like):
            out = np.zeros((a.shape[0], 1, self.max_seq) + a.shape[2:],
                           like.dtype)
            out[:, 0, :kv.plen] = a
            return jnp.asarray(out)

        self._pool = self._host_write_jit(
            self._pool, pad(kv.k, self._pool.k), pad(kv.v, self._pool.v),
            pad(kv.k_scale, self._pool.k_scale) if quant else None,
            pad(kv.v_scale, self._pool.v_scale) if quant else None,
            jnp.int32(row))
        return row

    # -- disaggregated serving (gofr_tpu/pd/) --------------------------------
    @staticmethod
    def _expiry_where(req: _Request, default: str) -> str:
        """Expiry-site label for telemetry: ingested (P/D-shipped)
        requests died AFTER the pool handoff — the decode worker's
        wide event says so, whatever stage the local default names."""
        return "post-handoff" if req.ingest is not None else default

    def _ship_range(self, attr: str, row: int, req: _Request,
                    upto: int) -> bool:
        """Prefill-only KV ship: snapshot prompt positions
        ``[req.kv_shipped, upto)`` of the slot row and hand them to the
        request's sink (the PD shipper frames + sends them). A sink
        failure — peer gone, ship window stalled past its deadline —
        fails THIS request (cancel-retire path) and returns False; it
        must never surface into the loop's device-loss recovery, the
        engine is healthy."""
        if req.kv_sink is None or upto <= req.kv_shipped:
            return True
        if req.stream.cancelled.is_set():
            # a dead request (client cancel, or an earlier ship failure
            # that already cancelled it) must not re-block the serving
            # loop for another window deadline shipping KV nobody will
            # ingest — _start's tail ship hits this after a mid-lattice
            # failure
            return False
        try:
            kv = self._kv_row_get(getattr(self, attr), row, upto,
                                  start=req.kv_shipped)
            req.kv_sink(kv, req.kv_shipped, len(req.prompt))
            req.kv_shipped = upto
            return True
        except BaseException as e:  # noqa: BLE001 — per-request failure
            req.stream.failed = f"kv ship failed: {e!r}"
            req.stream._q.put(GenerationError(f"kv ship failed: {e!r}"))
            req.stream.cancel()
            if self._observe is not None:
                self._observe.recorder.record(
                    "kv_ship_failed", request_id=req.stream.request_id,
                    trace_id=req.stream.trace_id,
                    shipped=req.kv_shipped, prompt_len=len(req.prompt),
                    error=repr(e))
            if self.logger is not None:
                self.logger.warn({"event": "pd kv ship failed",
                                  "request_id": req.stream.request_id,
                                  "shipped": req.kv_shipped,
                                  "error": repr(e)})
            return False

    def _validate_ingest(self, ingest, prompt: np.ndarray) -> None:
        """Reject a shipped-KV payload that cannot land in THIS
        engine's cache before it is ever queued: the ingest server
        relays the raised error typed; nothing here touches the
        device. (Frame-level integrity — checksum, truncation — was
        already enforced per frame by quant.decode_block at the
        transfer boundary.)"""
        kv, _, _ = ingest
        if self.mesh is not None:
            raise GenerationError("KV ingest requires a single-device "
                                  "decode engine (sharded install does "
                                  "not partition)")
        if kv.plen != len(prompt):
            raise GenerationError(
                f"ingest KV covers {kv.plen} tokens but the prompt has "
                f"{len(prompt)} — the transfer is incomplete")
        cfg = self.cfg
        if (kv.k.shape[0] != cfg.n_layers
                or kv.k.shape[2:] != (cfg.n_kv_heads, cfg.head_dim)):
            raise GenerationError(
                f"ingest KV layout {kv.k.shape} does not match this "
                f"engine ({cfg.n_layers} layers, {cfg.n_kv_heads} KV "
                f"heads, head_dim {cfg.head_dim})")
        quant = self.cache.k_scale is not None
        if quant and kv.k_scale is None:
            raise GenerationError("ingest KV lacks scale planes but the "
                                  "serving cache is int8-quantized")
        if str(kv.k.dtype) != str(self.cache.k.dtype):
            raise GenerationError(
                f"ingest KV dtype {kv.k.dtype} != serving cache dtype "
                f"{self.cache.k.dtype}")

    def _ingest_blocks(self, req: _Request) -> "tuple[list, int, list] | None":
        """Paged-pool blocks for one shipped-KV admission: all fresh
        (the shipped rows are installed, not prefix-matched), evicting
        LRU stored prefixes under pressure exactly like a local
        admission. None = transient shortage, requeue."""
        need = -(-len(req.prompt) // self._block_t)
        fresh = self._alloc.alloc(need)
        while fresh is None and self._prefix_idx is not None \
                and self._prefix_idx.evict_one():
            fresh = self._alloc.alloc(need)
        if fresh is None:
            return None
        return [], 0, fresh

    def _ingest_install(self, idx: int, req: _Request,
                        fresh: "list | None") -> tuple[int, float]:
        """Land a prefill worker's shipped KV in slot ``idx`` with ZERO
        prefill FLOPs: pad the host rows to the compiled row shape and
        install them — contiguous engines write the serving row
        directly; paged engines stage through the dense scratch row
        and land it in their ``fresh`` pool blocks (the same two
        programs the T1/T2 promote and long-prompt admission paths
        compile). The transient padded upload is leased from the HBM
        arbiter first (``pd-ingest`` stage, PRI_SCRATCH): under memory
        pressure the request SHEDS 429 at the boundary instead of
        OOMing the decode pool. T0 promotion then rides the normal
        ``_prefix_store`` in _start — an ingested prompt's KV lands in
        a pool row / shared-block entry exactly like a locally
        prefilled one, so repeat traffic hits locally next time."""
        kv, first, first_lp = req.ingest
        L = kv.plen
        self._slot_adapter[idx] = req.adapter
        self._touch("adapters")
        if self._paged:
            self._ensure_scratch()
            target_attr = "_scratch"
            row = 0
        else:
            target_attr = "cache"
            row = idx
        target = getattr(self, target_attr)
        quant = target.k_scale is not None

        def pad(a, like):
            out = np.zeros((a.shape[0], 1, self.max_seq) + a.shape[2:],
                           np.dtype(str(like.dtype)))
            out[:, 0, :L] = a
            return out

        k_p, v_p = pad(kv.k, target.k), pad(kv.v, target.v)
        ks_p = pad(kv.k_scale, target.k_scale) if quant else None
        vs_p = pad(kv.v_scale, target.v_scale) if quant else None
        stage = k_p.nbytes + v_p.nbytes \
            + (ks_p.nbytes + vs_p.nbytes if quant else 0)
        # the stage lease is the admission's honest memory claim: the
        # padded device upload lives until the row write consumes it
        hbm.lease("pd-ingest", stage, owner=self, tag="stage",
                  priority=hbm.PRI_SCRATCH)
        try:
            if self._ingest_write_jit is None:
                self._ingest_write_jit = jax.jit(_write_row_from_host,
                                                 donate_argnums=(0,))
            installed = self._ingest_write_jit(
                target, jnp.asarray(k_p), jnp.asarray(v_p),
                jnp.asarray(ks_p) if quant else None,
                jnp.asarray(vs_p) if quant else None, jnp.int32(row))
            setattr(self, target_attr, installed)
            if self._paged:
                self._slot_blocks[idx] = list(fresh)
                self._cursors[idx] = L
                write_blocks = list(fresh) + [0] * (self._mb - len(fresh))
                self.cache = self._row_to_blocks_jit(
                    self.cache, self._scratch,
                    jnp.asarray(write_blocks, jnp.int32))
                self._write_table_row(idx)
            self.cache = self.cache._replace(
                lengths=self.cache.lengths.at[idx].set(L))
        finally:
            hbm.release("pd-ingest", owner=self, tag="stage")
        req.stream.cache_tier = "pd-ship"
        req.stream.cache_tokens = L
        if self._tl is not None:
            self._tl.kvcache("pd", L, idx)
        if self.metrics is not None:
            try:
                self.metrics.increment_counter(
                    "app_tpu_pd_ingests_total")
            except Exception:
                pass
        return int(first), float(first_lp)

    def _ensure_scratch(self) -> None:
        """Paged decode workers built without a chunk scratch (short
        max_seq, no prefix index) grow one lazily at the first ingest:
        the dense staging row and the row->blocks program are the same
        machinery long-prompt admission compiles."""
        if hasattr(self, "_scratch"):
            return
        from ..models.paged_llama import (read_blocks_to_row,
                                          write_row_to_blocks)

        self._alloc_scratch()
        self._row_to_blocks_jit = jax.jit(write_row_to_blocks,
                                          donate_argnums=(0,))
        self._blocks_to_row_jit = jax.jit(read_blocks_to_row,
                                          donate_argnums=(0,))

    def _prefix_restore(self, idx: int, req: _Request, L: int,
                        C: int) -> int:
        """Consult the cache hierarchy; on a useful hit land the prefix
        KV in slot ``idx`` and return the position prefill resumes from
        (0 = no hit). T0 hits are one pool-row copy; T1/T2 hits promote
        through a pool row first (_promote_hostkv). The returned
        position keeps every later dispatch on the compiled lattice:
        chunk STARTS are traced values, only chunk LENGTHS are compile
        keys, so resuming mid-prompt compiles nothing new. At least one
        prompt position is always recomputed — the final chunk ends at
        the prompt end and samples there."""
        if self._kvc is None:
            return 0
        prompt = np.asarray(req.prompt, np.int32)
        t_start = time.monotonic()
        mt = self._kv_match(req, prompt)
        # the memo's job (one verdict for peek AND restore) is done the
        # moment the restore reads it — drop it now, or a T2 match's
        # decoded HostKV (tens of MB at real model dims) stays pinned
        # on the request for the stream's whole lifetime
        req.kv_match = None
        if mt is None:
            self._kvc.reject(prompt=prompt)
            return 0
        # Full-prompt-hit clamp: match() may cover the ENTIRE prompt
        # (exact repeat); restore at most L-1 positions so the final
        # chunk prefills >= 1 token — the dispatch needs logits at the
        # prompt end to sample the first generated token (the pool
        # stores KV, not logits).
        m_eff = clamp_restore_len(mt.matched_len, L)
        assert m_eff < L, "kvcache restore clamp violated"
        if (m_eff < self.prompt_buckets[0]
                # matched less than the smallest bucket: the copy would
                # not remove a single dispatch's worth of work; and the
                # final chunk needs [L - Sb, L) to be a valid window
                or not self._lattice_resume_valid(L, m_eff)):
            self._kvc.reject(mt)
            return 0
        if mt.tier == "t0":
            row = mt.row
        else:
            row = self._promote_hostkv(mt)
            if row is None:
                self._kvc.reject(mt)
                return 0
        self.cache = self._pool_load_jit(self.cache, self._pool,
                                         jnp.int32(idx), jnp.int32(row))
        restore_s = time.monotonic() - t_start
        self._kvc.accept(mt, restore_s,
                         tenant=req.tenant if self.tenancy is not None
                         else None)
        req.stream.cache_tier = mt.tier
        req.stream.cache_tokens = m_eff
        if self._tl is not None:
            self._tl.kvcache(mt.tier, m_eff, idx)
        self._obs_span("tpu.prefix-restore", t_start, t_start + restore_s,
                       req.stream, {"tier": mt.tier, "tokens": m_eff,
                                    "slot": idx})
        if self.metrics is not None:
            self.metrics.increment_counter(
                "app_tpu_prefix_cache_hits_total")
        return m_eff

    def _prefix_store(self, idx: int, req: _Request) -> None:
        """After a completed admission, remember this prompt's KV row
        (skipped for short prompts and already-covered ones). Must run
        BEFORE the slot's first decode tick — decode writes position L
        into the same row. A T0 victim spills its row to the host tier
        before being overwritten; with the Redis tier on, the fresh
        KV's full blocks write through so sibling replicas skip the
        prefill too."""
        if req.stream.cancelled.is_set():
            return
        prompt = np.asarray(req.prompt, np.int32)
        if self._paged:
            if self._prefix_idx is None or len(prompt) < self._store_min \
                    or self._prefix_idx.covered(prompt, req.adapter):
                return
            # zero-copy: reference the slot's full prompt blocks as a
            # SharedPrefixIndex entry — they are immutable from here on
            # (decode only writes the cursor's block). _start calls this
            # AFTER the admit dispatch materialized, so a device-failed
            # prefill can never store an entry over garbage KV.
            self._prefix_idx.store(prompt, self._slot_blocks[idx],
                                   req.adapter)
            return
        if self._kvc is None or len(prompt) < self._store_min \
                or self._kvc.covered(prompt, req.adapter):
            return
        row, victim = self._kvc.store(prompt, req.adapter,
                                      tenant=req.tenant
                                      if self.tenancy is not None else None)
        self._offload_victim(victim)
        self._pool = self._pool_store_jit(self._pool, self.cache,
                                          jnp.int32(row), jnp.int32(idx))
        if self.tenancy is not None:
            self._tenant_cache_sync()
        if self._kvc.shares:
            # write-through: a device_get of the slot's fresh KV is the
            # price of warming every replica — but only through the
            # last full block this replica hasn't already shared (an
            # already-written prefix costs no transfer; the trailing
            # partial block has no chain hash and never transfers)
            want = self._kvc.redis.pending_put_len(prompt, req.adapter)
            if want > 0:
                self._kvc.store_shared(prompt, req.adapter,
                                       self._kv_row_get(self.cache, idx,
                                                        want))

    def _shed_oom(self, req: _Request, e: "hbm.HBMExhausted") -> None:
        """OOM-shed a popped admission: the arbiter could not cover a
        lease (seeded HBM_ALLOC fault, or a real budget overshoot that
        survived reclaim), so THIS request degrades to a served
        429/RESOURCE_EXHAUSTED with the arbiter's Retry-After while
        the engine keeps serving everything else — the memory-pressure
        mirror of the gate's queue-pressure shed. The arbiter counted
        app_tpu_hbm_shed_total at its raise site; here the failure
        routes through the gate's shed surface (counters + tpu.shed
        span with reason=hbm) and the stream's terminal wide event."""
        retry_after = getattr(e, "retry_after", None) or 1.0
        err: BaseException = e
        if self.gate is not None:
            err = self.gate.shed_memory(
                program="generate", slo_class=req.slo_class,
                retry_after=retry_after, trace_id=req.stream.trace_id)
        else:
            now = time.monotonic()
            self._obs_span("tpu.shed", now, now, req.stream,
                           {"reason": "hbm", "slo_class": req.slo_class})
        if self._tl is not None:
            self._tl.shed("generate", req.slo_class, req.stream.trace_id)
        req.stream.failed = "hbm exhausted: shed"
        req.stream._q.put(err)
        req.stream._q.put(None)
        self._obs_end(req.stream, "shed", tokens=0, error=str(e))

    # -- arbiter reclaim callbacks (registered on the hbm leases) ------------
    def _hbm_pool_reclaim(self, need: int) -> int:
        """Shrink the T0 prefix pool toward the host tier: spill every
        live entry's row to T1 (when configured), drop enough rows to
        cover ``need`` bytes (always keeping one), and reallocate the
        pool at the smaller size. Future hits promote back from T1/T2
        exactly like post-recovery rewarming — the cache gets slower,
        the process survives. Runs under the device lock (reentrant:
        the serving loop may trigger its own shrink via the admission
        checkpoint). Mesh pools shrink the same way — spills are
        per-shard snapshots, the smaller pool re-places onto a FITTED
        sharding (fewer rows may stop dividing the data axes) and the
        pool programs rebuild against it. Returns bytes freed
        (global; the arbiter's per-device pass scales by this lease's
        shard fraction)."""
        with self._device_lock:
            kvc = getattr(self, "_kvc", None)
            pool = getattr(self, "_pool", None)
            if kvc is None or pool is None:
                return 0
            slots = kvc.slots
            if slots <= 1:
                return 0
            total = hbm.tree_nbytes(pool)
            row_b = max(1, total // slots)
            drop = min(slots - 1, -(-max(int(need), 1) // row_b))
            new_slots = slots - drop
            for entry in kvc.t0.entries():
                # the same spill path T0's LRU eviction uses (host-tier
                # guard included) — one convention for moving a pool
                # row down a tier
                self._offload_victim(entry)
            kvc.shrink(new_slots)
            # drop the old buffer BEFORE allocating the replacement:
            # holding both would spike usage past the very budget this
            # reclaim is trying to satisfy
            self._pool = None
            del pool
            try:
                if self.mesh is not None:
                    from ..parallel import kv_cache_specs

                    # FITTED fresh: the shrunk row count may stop
                    # dividing the data axes (replicate instead), and
                    # the pool programs must rebuild against whatever
                    # the new placement actually is
                    self._pool_sh = kv_cache_specs(
                        self.mesh, jax.eval_shape(
                            lambda: llama.init_cache(
                                self.cfg, new_slots, self.max_seq,
                                dtype=self._kv_dtype)))

                def _smaller_pool():
                    p = llama.init_cache(self.cfg, new_slots,
                                         self.max_seq,
                                         dtype=self._kv_dtype)
                    if self._pool_sh is not None:
                        p = jax.device_put(p, self._pool_sh)
                    return p

                self._pool = hbm.account("kvcache-t0", _smaller_pool(),
                                         owner=self, tag="pool")
                if self.mesh is not None:
                    self._build_pool_jits()
            except BaseException:
                # even the SMALLER pool failed to allocate (we are, by
                # definition, under memory pressure here). A None pool
                # behind a live CacheManager would AttributeError every
                # later store/promote, so disable the prefix tiers
                # outright — serving continues cache-less, the whole
                # old pool's bytes count as freed, and the arbiter's
                # caller gets the maximum this lease could give
                self._disable_prefix_tiers()
                hbm.release("kvcache-t0", owner=self, tag="pool")
                if self.logger is not None:
                    self.logger.error({
                        "event": "kvcache t0 disabled: arbiter shrink "
                                 "could not reallocate the smaller pool",
                        "slots_attempted": new_slots})
                return total
            if self.logger is not None:
                self.logger.warn({
                    "event": "kvcache t0 shrunk by hbm arbiter reclaim",
                    "slots": new_slots, "dropped_rows": drop,
                    "freed_bytes": drop * row_b})
            return drop * row_b

    def _disable_prefix_tiers(self) -> None:
        """Last-resort degradation: drop the hierarchical prefix cache
        entirely (pool gone, manager detached, its Redis client closed)
        so every cache path sees the same None it sees on engines built
        without one — requests keep serving, they just prefill fully."""
        kvc, self._kvc = self._kvc, None
        self._pool = None
        self._host_write_jit = None
        if kvc is not None and kvc.redis is not None:
            try:  # the engine owns the T2 client (KVCacheOptions.redis)
                kvc.redis.client.close()
            except Exception:
                pass

    def _hbm_paged_reclaim(self, need: int) -> int:
        """Release ONE cold shared-prefix entry's blocks back to the
        paged pool — the same one-at-a-time valve the in-pool pressure
        paths use (_paged_admission_blocks/_ensure_blocks): flushing
        the whole index would trade every future hit for a reclaim
        that may have needed a single eviction. The pool tensor itself
        is one preallocated buffer, so this frees BLOCK capacity (room
        for live streams to grow / new admissions) rather than HBM
        bytes — it reports 0 toward a byte deficit but still runs
        under pressure so the next block-level allocation finds
        room."""
        del need
        if not self._paged or self._prefix_idx is None:
            return 0
        with self._device_lock:
            if self._prefix_idx.evict_one() and self.logger is not None:
                self.logger.warn({"event": "paged prefix entry evicted "
                                  "by hbm arbiter reclaim"})
        return 0

    def _tenant_cache_sync(self) -> None:
        """Reconcile per-tenant arbiter leases with the cache ledger.
        A tenant holding more T0 rows than its cache-share budget gets
        a zero-byte ``tenant:{id}`` lease at PRI_SCRATCH whose reclaim
        callback evicts THAT tenant's rows — so arbiter pressure asks
        the over-budget tenant to give back its own blocks before the
        PRI_CACHE pool shrink flushes everyone's. Back under budget,
        the lease releases. Zero-byte because the pool's own lease
        already accounts the bytes (the paged-index precedent); this
        lease exists purely for its reclaim ordering."""
        kvc = self._kvc
        if kvc is None or self.tenancy is None:
            return
        try:
            over = set()
            for tid, rows in kvc.tenant_rows().items():
                budget = kvc.tenant_budget(tid)
                if budget is not None and rows > budget:
                    over.add(tid)
            for tid in over - self._tenant_leased:
                hbm.tenant_lease(
                    "kvcache-t0", 0, tenant=tid, owner=self,
                    priority=hbm.PRI_SCRATCH,
                    reclaim=lambda ask, t=tid: self._tenant_cache_evict(t))
                self._tenant_leased.add(tid)
            for tid in self._tenant_leased - over:
                hbm.release("kvcache-t0", owner=self, tag=f"tenant:{tid}")
                self._tenant_leased.discard(tid)
        except Exception:
            pass  # quota leases are best-effort; serving must not stall

    def _tenant_cache_evict(self, tenant: str) -> int:
        """Arbiter reclaim callback for a tenant's cache-quota lease:
        evict the over-budget tenant's own T0 rows (LRU-first, down to
        its budget), spilling each to the host tier exactly like a
        store-path victim — warm state degrades to T1, other tenants'
        rows are untouched. Reports 0 toward a byte deficit (the pool
        lease accounts the bytes) but still frees the contended rows."""
        if self._kvc is None:
            return 0
        with self._device_lock:
            for victim in self._kvc.evict_tenant(tenant):
                self._offload_victim(victim)
        self._tenant_cache_sync()
        return 0

    def _count_expired(self, where: str = "queue",
                       request_id=None) -> None:
        if self._tl is not None:
            self._tl.expired(where, request_id)
        if self.metrics is not None:
            try:
                self.metrics.increment_counter(
                    "app_tpu_expired_dropped_total", program="generate")
            except Exception:
                pass

    def _release_tenant(self, stream: GenStream) -> None:
        """Give back the stream's tenant concurrency-quota slot, exactly
        once, at whatever terminal the stream reaches (finish, failure,
        cancel, early-return error stream)."""
        if not stream._tenant_held:
            return
        stream._tenant_held = False
        if self.tenancy is not None:
            try:
                self.tenancy.release(stream.tenant)
            except Exception:
                pass  # quota bookkeeping must never take the loop down

    # -- flight-recorder plumbing (all no-ops without an Observe bundle) -----
    def _obs_end(self, stream: GenStream, event: str, **fields) -> None:
        """Remove the request's registry entry, record its terminal
        lifecycle event (finished/failed/cancelled), and emit the
        request's canonical WIDE event. Every stream's one terminal
        passes through here, which is what makes it the tenant
        quota-release point."""
        self._release_tenant(stream)
        if self._observe is not None:
            self._observe.requests.remove(stream.obs_entry)
            self._observe.recorder.record(event, request_id=stream.request_id,
                                          trace_id=stream.trace_id, **fields)
        self._wide_event(stream, event, fields)

    def _wide_fields(self, outcome: str, trace_id: str,
                     slo_class: str, tenant: str | None = None) -> dict:
        """The canonical wide-event skeleton: key order is part of the
        contract (one grep on ``"event": "request"`` reconstructs any
        request; dashboards and scripts rely on stable field names).
        ``tenant`` appears only on tenancy-enabled engines — events from
        planeless deployments are byte-stable against older tooling."""
        out = {"event": "request", "outcome": outcome,
               "trace_id": trace_id, "slo_class": slo_class}
        if tenant is not None:
            out["tenant"] = tenant
        return out

    def _wide_event(self, stream: GenStream, outcome: str,
                    fields: dict) -> None:
        """One structured event per request at its terminal outcome —
        slo class, queue wait, chunk count, cache tier, tokens, trace
        id — through glog (grep the logs) AND the flight recorder
        (/debug/events survives log rotation)."""
        trace = stream.trace
        submit = trace.get("submit")
        admit = trace.get("admit")
        now = time.monotonic()
        if stream.cursor_base and outcome == "finished":
            # a continuation that ran to completion IS the resumed tail
            # of an interrupted stream — surface it as its own outcome
            # so dashboards can count resumes without joining on fields
            outcome = "resumed"
        wide = self._wide_fields(
            outcome, stream.trace_id, stream.slo_class,
            tenant=stream.tenant if self.tenancy is not None else None)
        wide.update({
            "request_id": stream.request_id,
            "prompt_len": stream.prompt_len,
            "tokens": fields.get("tokens", 0),
            "queue_wait_s": (round(admit - submit, 6)
                             if admit is not None and submit is not None
                             else None),
            "duration_s": fields.get(
                "duration_s",
                round(now - submit, 6) if submit is not None else None),
            "chunks": stream.chunks,
            "cache_tier": stream.cache_tier,
            "cache_tokens": stream.cache_tokens,
        })
        if stream.cursor_base:
            # durable-streams resume: where the continuation picked up
            # and how much prefix it actually had to recompute (a warm
            # resume covers most of prompt+emitted from T1/T2 and
            # recomputes only the tail)
            wide["resumed_at_cursor"] = stream.cursor_base
            wide["recompute_tokens"] = max(
                0, stream.prompt_len - stream.cache_tokens)
        # critical-path breakdown: the request's life as named segments
        # that SUM to duration_s (each bounded by consecutive trace
        # stamps, so the invariant holds by construction). On a decode
        # worker "prefill" is the ingest install of shipped KV.
        breakdown: dict = {}
        prefill_done = trace.get("prefill_done")
        first_put = trace.get("first_put")
        cuts = [("queue_wait", submit, admit),
                ("prefill", admit, prefill_done),
                ("handoff", prefill_done, first_put),
                ("decode", first_put, now)]
        for seg, a, b in cuts:
            if a is not None and b is not None:
                breakdown[seg + "_s"] = round(max(0.0, b - a), 6)
        if breakdown:
            wide["breakdown"] = breakdown
        # wall-clock anchor for cross-process placement: emission wall
        # time minus the monotonic elapsed puts submit on the wall axis
        # without a second stamp in the hot path
        if submit is not None:
            wide["submit_wall_s"] = round(time.time() - (now - submit), 6)
        if trace.get("kv_transfer_s") is not None:
            # the P/D wire segment — it PRECEDES submit on the decode
            # worker (the assembly exists before generate() is called),
            # so it rides beside the breakdown, not inside it
            wide["kv_transfer_s"] = trace["kv_transfer_s"]
        if self.metrics is not None and breakdown:
            tid = stream.trace_id or None
            for i, (seg_s, v) in enumerate(sorted(breakdown.items())):
                try:
                    self.metrics.record_histogram(
                        "app_tpu_request_segment_duration", v,
                        exemplar=tid if i == 0 else None,
                        segment=seg_s[:-2], program="generate")
                except Exception:
                    pass  # telemetry must never take the serving loop down
        if "error" in fields:
            wide["error"] = fields["error"]
        if stream.where is not None:
            # the deadline-expiry site — "post-handoff" on a decode
            # worker says the budget died AFTER the P/D pool boundary
            wide["where"] = stream.where
        if self._observe is not None:
            self._observe.recorder.record(
                "request", request_id=stream.request_id,
                trace_id=stream.trace_id,
                **{k: v for k, v in wide.items()
                   if k not in ("event", "request_id", "trace_id")})
        if self.logger is not None:
            try:
                self.logger.wide(wide)
            except Exception:
                pass  # telemetry must never take the serving loop down

    def _wide_shed(self, slo_class: str, tenant: str | None = None) -> None:
        """Wide event + timeline marker for a request shed at the gate
        (no stream exists yet; the ambient span is the only trace
        context the request ever had)."""
        trace_id = ""
        if self._observe is not None:
            from .. import tracing

            span = tracing.current_span()
            if span is not None:
                trace_id = span.trace_id
        if self._tl is not None:
            self._tl.shed("generate", slo_class, trace_id)
        wide = self._wide_fields("shed", trace_id, slo_class, tenant=tenant)
        wide["sheds"] = 1
        if self._observe is not None:
            self._observe.recorder.record(
                "request", trace_id=trace_id,
                **{k: v for k, v in wide.items()
                   if k not in ("event", "trace_id")})
        if self.logger is not None:
            try:
                self.logger.wide(wide)
            except Exception:
                pass

    def _obs_stage(self, stream: GenStream, stage: str) -> None:
        if stream.obs_entry is not None:
            stream.obs_entry.stage = stage

    def _obs_span(self, name: str, start_s: float, end_s: float,
                  stream: GenStream, attrs: dict | None = None) -> None:
        """Export one per-stage serving span (admit wait / prefill /
        decode), parented by the request's inbound trace context."""
        obs = self._observe
        if obs is None or obs.tracer is None:
            return
        try:
            obs.tracer.record_span(name, start_s, end_s,
                                   traceparent=stream.traceparent,
                                   trace_id=stream.trace_id or None,
                                   attributes=attrs)
        except Exception:
            pass  # telemetry must never take the serving loop down

    def _record_itl(self, slot: _Slot, n: int) -> None:
        """Record ``n`` inter-token-latency samples for a slot about to
        receive ``n`` tokens from one reaped dispatch: the block interval
        (time since the slot's previous delivery) amortized per token.
        This is the DEVICE cadence a steady-state client observes, not
        the microsecond host-loop gaps within one burst delivery."""
        if self.metrics is None or n <= 0 or slot.last_token_t == 0.0:
            return
        gap = (time.monotonic() - slot.last_token_t) / n
        # one exemplar per reap (first sample): n identical samples
        # land in one bucket, and the OpenMetrics join only needs one
        # trace id per bucket update
        tid = slot.request.stream.trace_id or None if slot.request else None
        for i in range(n):
            self.metrics.record_histogram("app_tpu_inter_token_duration",
                                          gap, exemplar=tid if i == 0 else None,
                                          program="generate")

    def _obs_gauges(self) -> None:
        """Refresh the live-load gauges after admission/retirement."""
        if self.metrics is None:
            return
        self.metrics.set_gauge("app_tpu_active_sequences",
                               float(self._active.sum()))
        self.metrics.set_gauge("app_tpu_queue_depth",
                               float(self._pending.qsize()),
                               program="generate")
        for cls in (SLO_LATENCY, SLO_THROUGHPUT):
            # per-class wait lines alongside the total (distinct label
            # sets are distinct series; dashboards on the unlabeled
            # total keep working)
            self.metrics.set_gauge("app_tpu_queue_depth",
                                   float(self._pending.qsize_class(cls)),
                                   program="generate", slo_class=cls)
        if self.tenancy is not None:
            # per-tenant wait lines; a tenant that drained must zero
            # (not freeze) its gauge, so remember everyone ever seen
            by_tenant = self._pending.qsize_by_tenant()
            self._gauge_tenants.update(by_tenant)
            for tid in self._gauge_tenants:
                self.metrics.set_gauge("app_tpu_queue_depth",
                                       float(by_tenant.get(tid, 0)),
                                       program="generate", tenant=tid)

    def _start(self, idx: int, slot: _Slot, req: _Request,
               blocks: "tuple | None" = None) -> None:
        t0 = time.monotonic()
        req.stream.trace["admit"] = t0
        if self.gate is not None:
            self.gate.note_wait(t0 - req.enqueued_at)
        if self._tl is not None:
            self._tl.admit(idx, req.slo_class, t0 - req.enqueued_at,
                           req.stream.request_id, req.stream.trace_id)
        self._obs_stage(req.stream, "prefill")
        if self._observe is not None:
            self._observe.recorder.record(
                "admitted", request_id=req.stream.request_id,
                trace_id=req.stream.trace_id, slot=idx,
                slo_class=req.slo_class,
                wait_s=round(t0 - req.enqueued_at, 6))
        # CLAIM the slot before any dispatch: a chunk-lattice admission
        # runs nested admission passes between chunks, and an unclaimed
        # slot (request still None until the old post-prefill
        # assignment) would be handed to a second request mid-lattice
        slot.request = req
        try:
            chaos.fire(chaos.GENERATOR_PREFILL)
            if req.ingest is not None:
                first, first_lp = self._ingest_install(
                    idx, req, blocks[2] if blocks else None)
            elif self._paged:
                shared, m, fresh = blocks
                first, first_lp = self._paged_admit_prefill(
                    idx, req, shared, m, fresh)
            else:
                first, first_lp = self._admit_prefill(idx, req)
        except hbm.HBMExhausted as e:
            # the ingest stage lease (or any admission-path lease)
            # could not be covered: this is MEMORY pressure, served as
            # a 429 shed of THIS request — never loop recovery. The
            # typed error rides the stream back (for P/D requests: over
            # the wire through the prefill worker to the client).
            if self._paged and blocks:
                shared, _, fresh = blocks
                self._slot_blocks[idx] = []
                self._table[idx, :] = 0
                self._cursors[idx] = 0
                self._touch("table")
                self._alloc.free(shared + fresh)
            slot.request = None
            self._shed_oom(req, e)
            self._obs_gauges()
            return
        except BaseException as e:  # noqa: BLE001 — the request is already
            # off the pending queue and owns no slot: fail ITS stream here,
            # then let _loop's handler deal with engine-level fallout.
            if self._paged and blocks:
                # the failed admission may have already installed the
                # slot's blocks/table/cursor (_paged_admit_prefill writes
                # them before the device error surfaces at int(tok)) —
                # clear them BEFORE freeing, or the stale table row would
                # direct this slot's frozen-cursor garbage writes into
                # blocks re-issued to another live stream. The slot holds
                # one reference on shared + fresh alike (taken in _admit
                # / alloc); freeing drops exactly that hold.
                shared, m, fresh = blocks
                self._slot_blocks[idx] = []
                self._table[idx, :] = 0
                self._cursors[idx] = 0
                self._touch("table")
                self._alloc.free(shared + fresh)
            # un-claim BEFORE re-raising: the loop's recovery handler
            # retires every slot holding a request, and this stream is
            # already failed right here — leaving the claim would
            # deliver it a second error and end its registry entry twice
            slot.request = None
            req.stream._q.put(GenerationError(f"prefill failed: {e!r}"))
            req.stream._q.put(None)
            self._obs_end(req.stream, "failed", stage="prefill",
                          error=repr(e))
            raise
        prefill_done = time.monotonic()
        req.stream.trace["prefill_done"] = prefill_done
        if self._tl is not None:
            self._tl.prefill(t0, prefill_done, idx, len(req.prompt),
                             req.stream.request_id, req.stream.trace_id)
        self._obs_span("tpu.admit-wait", req.enqueued_at, t0, req.stream,
                       {"slot": idx, "slo_class": req.slo_class})
        self._obs_span("tpu.prefill", t0, prefill_done, req.stream,
                       {"slot": idx, "prompt_len": len(req.prompt),
                        "slo_class": req.slo_class})
        if req.kv_sink is not None:
            # prefill-only: ship the tail the chunk hooks haven't sent
            # (the whole row for bucket prompts) BEFORE the first-token
            # delivery — frame order on the wire is the ingest
            # contract. A ship failure cancelled the stream; _deliver
            # retires the slot on that flag below.
            self._ship_range("cache", idx, req, len(req.prompt))
        self._prefix_store(idx, req)
        if self._spec_k:
            self._hist_set(idx, req.prompt)
        if self.metrics is not None:
            self.metrics.record_histogram("app_tpu_batch_wait_duration",
                                          t0 - req.enqueued_at, program="generate")
        slot.generated = 0
        # prefill-only requests deliver exactly the sampled first token
        # and retire — the DECODE pool owns the rest of the budget
        slot.remaining = 1 if req.kv_sink is not None else req.max_new
        self.total_requests += 1
        self._temps[idx] = req.temperature
        self._top_ks[idx] = req.top_k
        self._slot_seed[idx] = req.seed
        self._touch("temps", "top_ks", "seeds")
        if self._spec_k:
            self._hist_append(idx, int(first))
        self._deliver(idx, slot, first, first_lp)
        if slot.request is not None:  # not finished by the first token
            self._last_tokens[idx] = first
            self._active[idx] = True
            # device-side stop state: the budget mirrors slot.remaining
            # (tokens still allowed after the prefill's first one); the
            # EOS row arms the in-scan stop set. host_wins forces all
            # of it over whatever the device carry held for this slot.
            self._budgets[idx] = slot.remaining
            self._eos_row(idx, req.eos_id)
            if self._paged:
                # where the device's budget/capacity stop masks will
                # freeze this slot's cursor (EOS may stop earlier —
                # the over-advance is bounded by one reap)
                self._stop_cursors[idx] = min(
                    req.stream.prompt_len + slot.remaining,
                    self.max_seq - 2)
            # the slot's next sample sits at absolute position
            # pos_base + delivered-so-far (the prefill's first token
            # consumed pos_base itself)
            self._pos_abs[idx] = req.pos_base + slot.generated
            self._host_wins[idx] = True
            self._touch("active", "last_tokens", "host_wins", "budgets",
                        "eos", "pos")
        self._obs_gauges()

    def _eos_row(self, idx: int, eos_id) -> None:
        """Arm slot ``idx``'s on-device EOS stop set. Sets wider than
        EOS_MAX fall back to host-only retirement (the extra ids simply
        never match on device; the stream stays exact, the slot just
        burns junk steps until the reap notices)."""
        row = self._eos_mat[idx]
        row[:] = llama.EOS_PAD
        if eos_id is None:
            return
        ids = (eos_id,) if isinstance(eos_id, int) else tuple(eos_id)
        for j, t in zip(range(self.EOS_MAX), ids):
            row[j] = t

    def _deliver(self, idx: int, slot: _Slot, token: int,
                 lp: float | None = None) -> None:
        """Push one token to the consumer; retire the slot when finished."""
        req = slot.request
        if req.stream.cancelled.is_set():
            self._retire(idx, slot)
            return
        now = time.monotonic()
        if slot.generated == 0:  # first token: prefill_done -> first_put
            # is the prefix-store cost (a device row copy when an entry
            # is stored) — attributed separately from delivery wake-up
            req.stream.trace["first_put"] = now
            ttft = now - req.stream.trace["submit"]
            if self.metrics is not None:
                # the exemplar makes a dashboard's p99 TTFT bucket
                # resolve to the exact trace that populated it; the
                # label-key set is ONE set whether or not tenancy is
                # on (tenant="" = untenanted) so the series never
                # splits on deployment mode
                self.metrics.record_histogram(
                    "app_tpu_ttft_duration", ttft,
                    exemplar=req.stream.trace_id or None,
                    program="generate", slo_class=req.slo_class,
                    tenant=(req.tenant or ""
                            if self.tenancy is not None else ""))
            self._obs_stage(req.stream, "decode")
            if self._observe is not None:
                self._observe.recorder.record(
                    "first_token", request_id=req.stream.request_id,
                    trace_id=req.stream.trace_id, slot=idx,
                    slo_class=req.slo_class,
                    ttft_s=round(ttft, 6))
        # inter-token latency is recorded at the REAP level (_record_itl),
        # not here: a fused decode block delivers its K tokens back-to-back
        # in one host loop, and per-delivery gaps would report microsecond
        # burst artifacts instead of device cadence
        slot.last_token_t = now
        # _push: straight into a registered transport sink (zero-handoff
        # delivery — bytes leave on THIS thread, nonblocking) or the
        # stream queue for iterator consumers
        req.stream._push((token, lp) if req.logprobs else token)
        slot.generated += 1
        slot.remaining -= 1
        self.total_tokens += 1
        try:
            # durable-streams chaos seam: a seeded GENERATOR_MIDKILL
            # (every=N, limit=1) kills THIS stream after exactly N
            # delivered tokens — the in-process stand-in for a replica
            # SIGKILL mid-stream, replayable by digest. Only the one
            # stream dies (typed error + retire); the engine keeps
            # serving, exactly like a per-request failure.
            chaos.fire(chaos.GENERATOR_MIDKILL)
        except BaseException as e:  # noqa: BLE001 — per-request failure
            req.stream.failed = (f"chaos mid-stream kill after "
                                 f"{slot.generated} tokens")
            req.stream._q.put(GenerationError(
                f"mid-stream kill after {slot.generated} tokens: {e!r}"))
            self._retire(idx, slot)
            return
        if req.stream.obs_entry is not None:
            req.stream.obs_entry.tokens = slot.generated
        if self.metrics is not None:
            self.metrics.increment_counter("app_tpu_tokens_generated_total")
        at_eos = req.eos_id is not None and (
            token in req.eos_id if isinstance(req.eos_id, frozenset)
            else token == req.eos_id)
        # cursor positions used so far: prompt_len + generated
        at_capacity = req.stream.prompt_len + slot.generated >= self.max_seq - 1
        if at_eos or slot.remaining <= 0 or at_capacity:
            self._retire(idx, slot)

    def _retire(self, idx: int, slot: _Slot) -> None:
        stream = slot.request.stream
        now = time.monotonic()
        first = stream.trace.get("first_put")
        decode_s = (now - first) if first is not None else 0.0
        tps = slot.generated / decode_s if decode_s > 0 else 0.0
        if self.metrics is not None and slot.generated > 1:
            # throughput needs at least one inter-token interval
            self.metrics.set_gauge("app_tpu_tokens_per_second", tps,
                                   program="generate")
        if first is not None and slot.generated > 0:
            self._obs_span("tpu.decode", first, now, stream,
                           {"slot": idx, "tokens": slot.generated,
                            "slo_class": slot.request.slo_class})
        event = ("failed" if stream.failed is not None
                 else "cancelled" if stream.cancelled.is_set()
                 else "finished")
        fields = {"slot": idx, "tokens": slot.generated,
                  "duration_s": round(now - stream.trace["submit"], 6),
                  # throughput needs at least one inter-token interval —
                  # a 1-token stream's first_put->retire gap is
                  # microseconds and would report ~1e6 tok/s
                  "tokens_per_s": (round(tps, 3)
                                   if slot.generated > 1 else None)}
        if stream.failed is not None:
            fields["error"] = stream.failed
        self._obs_end(stream, event, **fields)
        slot.request.stream._push(None)
        slot.request = None
        self._active[idx] = False
        self._temps[idx] = 0.0
        self._top_ks[idx] = 0
        self._slot_adapter[idx] = 0
        self._budgets[idx] = 0
        self._slot_seed[idx] = 0
        self._pos_abs[idx] = 0
        self._eos_mat[idx, :] = llama.EOS_PAD
        # host wins the next dispatch's merge for this slot: a host-only
        # retirement (cancel, deadline, paged starvation) deactivates a
        # slot the device carry still believes is live — without this
        # an already-pipelined block would be the LAST junk it emits,
        # but the carry would keep it running forever
        self._host_wins[idx] = True
        self._touch("active", "temps", "top_ks", "adapters", "budgets",
                    "eos", "host_wins")
        if self._paged:
            # freed blocks may be re-issued immediately; the retired
            # slot's frozen-cursor garbage writes go to the trash block
            # because its table row zeroes BEFORE the next dispatch
            if self._slot_blocks[idx]:
                self._alloc.free(self._slot_blocks[idx])
                self._slot_blocks[idx] = []
            self._table[idx, :] = 0
            self._cursors[idx] = 0
            self._stop_cursors[idx] = 0
            self._touch("table")
        self._obs_gauges()

    def _loop(self) -> None:
        # the decode dispatch pipeline: oldest-first deque of in-flight
        # fused blocks. Depth 1 reproduces the old dispatch->overlap->
        # reap loop exactly; at depth 2 the loop keeps a SECOND block
        # queued on the device stream while reaping the first, so the
        # host-side reap/delivery/admission work (the ~23% per-block
        # dispatch gap BENCH_CANDIDATE.json measured) overlaps device
        # compute instead of idling it.
        pipe: "deque[_Inflight]" = deque()
        while not self._closed:
            try:
                if pipe or self._active.any() or not self._pending.empty():
                    with self._device_lock:
                        if not pipe:
                            # synchronous admission pass — the only one
                            # allowed to run a chunk lattice (its
                            # interleaved decode blocks need a fully
                            # reaped loop)
                            self._lattice_deferred = False
                            self._admit()
                        chaos.fire(chaos.GENERATOR_STEP)
                        depth = self._target_depth()
                        while len(pipe) < depth:
                            inflight = self._tick(decode_only=bool(pipe))
                            if inflight is None:
                                break
                            pipe.append(inflight)
                        self._note_depth(len(pipe))
                    if not pipe:
                        continue
                    # serve admissions WHILE the oldest block runs on
                    # device, then fetch its results — see
                    # _admit_inflight for why this is the TTFT fix
                    self._admit_inflight(pipe[0])
                    with self._device_lock:
                        inflight = pipe.popleft()
                        self._reaps += 1
                        if pipe:
                            # >= 1 block still queued on-device: the
                            # inter-block host gap is zero by
                            # construction — record it so the A/B gap
                            # p50 reflects the pipelining win
                            self._overlapped_reaps += 1
                            self._record_gap(0.0)
                        else:
                            # the stream ran dry when this block's
                            # outputs came ready; the next dispatch
                            # closes the gap
                            self._idle_from = (inflight.ready_t
                                               or time.monotonic())
                        inflight.reap()
                else:
                    self._work.wait(timeout=0.05)
                    self._work.clear()
            except BaseException as e:  # noqa: BLE001 — waiters must not hang
                # unwind EVERY in-flight dispatch first: their output
                # futures (and the donated cache chained through them)
                # died with the failure — reaping one would only
                # re-raise the same error; recovery below reseeds ONCE
                # for however many dispatches were in flight
                pipe.clear()
                if self._closed:
                    return
                if self.logger is not None:
                    self.logger.error({"event": "generation loop failed",
                                       "error": repr(e)})
                err = GenerationError(f"generation failed: {e!r}")
                # A failed prefill/step may have consumed the DONATED cache
                # buffer; continuing would serve every later request an
                # opaque "donated buffer" error. Recovery runs in three
                # phases, ordered so consumers neither observe stale
                # state NOR hang behind device work:
                #   1. host-side invariants (mirrors, PRNG epoch, prefix
                #      index) — pure Python, cannot hang;
                #   2. error delivery — waiters fail fast with every
                #      host-observable invariant already consistent;
                #   3. device reallocation — may block indefinitely on a
                #      WEDGED device, which is exactly why it runs after
                #      delivery. No admission can race it: only this
                #      loop thread admits, and it is here.
                with self._device_lock:
                    # device-mirror buffers may have died with the
                    # failed dispatch — rebuild them all on next use
                    self._mirror.clear()
                    self._pack = None
                    self._pack_dirty = True
                    self._last_dev = None
                    self._idle_from = None
                    self._host_wins[:] = True
                    self._recoveries += 1
                    if self._prefix_idx is not None:
                        # paged entries reference blocks of the OLD
                        # pool and would restore all-zero KV on a hit
                        self._prefix_idx.clear()
                    if self._kvc is not None:
                        # tiered recovery: T0 entries die with the pool
                        # (they'd match prompts against fresh zeroed
                        # rows), but T1 host snapshots and T2 shared
                        # blocks are device-independent and SURVIVE —
                        # the next admission rewarns the new pool from
                        # them instead of paying a full prefill
                        self._kvc.clear_device()
                # under the device lock: _retire mutates _active/_table/
                # _cursors, and warmup()/swap_adapter() on OTHER threads
                # hold the lock while reading slot state — an unlocked
                # retire here could free a slot mid-warmup-prefill
                with self._device_lock:
                    for idx, slot in enumerate(self._slots):
                        if slot.request is not None:
                            slot.request.stream.failed = repr(e)
                            slot.request.stream._q.put(err)
                            self._retire(idx, slot)
                try:
                    with self._device_lock:
                        if self.mesh is not None:
                            # warm device-loss re-placement: rebuild
                            # the mesh over live devices, re-place
                            # params, recompute shardings, rebuild the
                            # compiled surface — the reallocs below
                            # then land placed on the NEW mesh and
                            # re-settle the same per-shard lease keys
                            self._replace_mesh()
                        # the PRNG key chains THROUGH dispatches now: an
                        # async failure leaves self._key bound to the
                        # failed computation's error-state output, and
                        # every later program would consume it and
                        # re-raise forever — reseed from the host,
                        # salted so recoveries don't replay the stream
                        self._key = jax.random.PRNGKey(
                            self._seed + self._recoveries)
                        if self._rep_sh is not None:
                            # (GL202 suppressed: 16-byte key — see
                            # the mesh-init placement above)
                            self._key = jax.device_put(self._key, self._rep_sh)  # noqa: GL202, E501
                        if self._pool is not None:
                            # _pool_store_jit donates the pool buffer —
                            # a failed store leaves it consumed/poisoned
                            def _realloc_pool():
                                pool = llama.init_cache(
                                    self.cfg, self._kvc.slots,
                                    self.max_seq, dtype=self._kv_dtype)
                                if self._pool_sh is not None:
                                    pool = jax.device_put(pool,
                                                          self._pool_sh)
                                return jax.block_until_ready(pool)

                            # re-lease + re-account (set semantics over
                            # the lease group — mesh pools re-settle
                            # the same per-shard keys, never double-
                            # counting): the donated old pool died with
                            # the failed dispatch, and the arbiter's
                            # reclaim-then-retry covers a recovery that
                            # lands while HBM is contended
                            if self.mesh is not None:
                                self._pool = hbm.alloc_sharded(
                                    "kvcache-t0", _realloc_pool,
                                    owner=self, tag="pool",
                                    priority=hbm.PRI_CACHE,
                                    reclaim=self._hbm_pool_reclaim,
                                    devices=self._dev_labels)
                            else:
                                self._pool = hbm.alloc(
                                    "kvcache-t0", _realloc_pool,
                                    owner=self, tag="pool",
                                    priority=hbm.PRI_CACHE,
                                    reclaim=self._hbm_pool_reclaim)
                        if self._paged:
                            from ..models.paged_llama import init_paged_cache

                            def _realloc_cache():
                                return init_paged_cache(
                                    self.cfg, self.n_slots,
                                    self._alloc.n_blocks, self._block_t,
                                    dtype=self._kv_dtype)

                            cache_reclaim = self._hbm_paged_reclaim
                            if hasattr(self, "_scratch"):
                                # the chunk jits donate the scratch row
                                # too — a failed chunk dispatch leaves it
                                # consumed, bricking every later
                                # long-prompt admission

                                def _realloc_scratch():
                                    s = llama.init_cache(
                                        self.cfg, 1, self.max_seq,
                                        dtype=self._kv_dtype)
                                    if self._scratch_sh is not None:
                                        s = jax.device_put(
                                            s, self._scratch_sh)
                                    return jax.block_until_ready(s)

                                if self.mesh is not None:
                                    self._scratch = hbm.alloc_sharded(
                                        "engine", _realloc_scratch,
                                        owner=self, tag="scratch",
                                        priority=hbm.PRI_SCRATCH,
                                        devices=self._dev_labels)
                                else:
                                    self._scratch = hbm.alloc(
                                        "engine", _realloc_scratch,
                                        owner=self, tag="scratch",
                                        priority=hbm.PRI_SCRATCH)
                        else:
                            def _realloc_cache():
                                return llama.init_cache(self.cfg,
                                                        self.n_slots,
                                                        self.max_seq,
                                                        dtype=self._kv_dtype)

                            cache_reclaim = None

                        def _realloc_placed():
                            cache = _realloc_cache()
                            if self._cache_sh is not None:
                                cache = jax.device_put(cache,
                                                       self._cache_sh)
                            return jax.block_until_ready(cache)

                        if self.mesh is not None:
                            self.cache = hbm.alloc_sharded(
                                "engine", _realloc_placed, owner=self,
                                tag="cache", priority=hbm.PRI_SERVING,
                                reclaim=cache_reclaim,
                                devices=self._dev_labels)
                        else:
                            self.cache = hbm.alloc(
                                "engine", _realloc_placed, owner=self,
                                tag="cache", priority=hbm.PRI_SERVING,
                                reclaim=cache_reclaim)
                    if self.logger is not None:
                        self.logger.warn({"event": "generation cache "
                                          "reallocated after device failure"})
                except BaseException as e2:  # noqa: BLE001
                    self.down = f"cache reallocation failed: {e2!r} " \
                                f"(after: {e!r})"
                    if self.logger is not None:
                        self.logger.error({"event": "generation engine down",
                                           "error": self.down})
                if self.down is not None:
                    # fail queued requests too — their consumers block on
                    # the stream and no later iteration will admit them
                    down_err = GenerationError(
                        f"generation engine is down: {self.down}")
                    while True:
                        try:
                            req = self._pending.get_nowait()
                        except queue.Empty:
                            break
                        req.stream._q.put(down_err)
                        req.stream._q.put(None)
                        self._obs_end(req.stream, "failed", error=self.down)
                    return

    def _admit_inflight(self, inflight: _Inflight) -> None:
        """Admit new arrivals while a dispatched tick executes on device.

        Dispatches are async: until the tick's outputs are ready, the
        old loop sat in device_get — which on the tunneled backend holds
        the GIL, parking every submitter thread, and serialized
        (delivery + admission + prefill dispatch) AFTER the block, so a
        request arriving mid-block paid up to a whole extra block of
        TTFT (the r3 gRPC gap). Here the loop thread instead waits on
        the submit event and runs admissions NOW: the new request's
        prefill queues on the device stream right behind the in-flight
        block, making its first token cost (remaining block + prefill)
        — the hardware floor. Readiness is polled via jax.Array
        .is_ready(); if the probe is unsupported the reap just blocks
        like the old loop. The deadline bounds the poll so a wedged
        device surfaces its error through the blocking reap rather than
        a silent spin."""
        deadline = time.monotonic() + 60.0
        poll = self._admit_window or 1e-3
        while not self._closed and time.monotonic() < deadline:
            try:
                if all(a.is_ready() for a in inflight.arrays):
                    inflight.ready_t = time.monotonic()
                    return
            except Exception:  # no readiness probe on this backend
                return
            started = 0
            if not self._pending.empty():
                with self._device_lock:
                    started = self._admit(defer_lattice=True)
            if started:
                continue  # more may be queued behind the ones admitted
            # nothing admitted (queue empty, no free slot, pool
            # pressure, or a lattice request deferred to the reap):
            # WAIT — looping straight back would busy-spin on the GIL
            # and the device lock for the whole block, starving the
            # very submitter/consumer threads this loop exists to serve
            self._work.clear()
            self._work.wait(poll)

    def _target_depth(self) -> int:
        """Pipeline depth for the next top-up — the engine-side facts
        feeding resilience.DecodePipelinePolicy. Also surfaced by
        stats() so tests and dashboards see the same verdict the loop
        acts on."""
        return self._pipeline.target(
            latency_waiting=self._pending.qsize_class(SLO_LATENCY) > 0,
            lattice_deferred=self._lattice_deferred,
            spec_decode=bool(self._spec_k))

    def _note_depth(self, depth: int) -> None:
        if depth == self._depth_now:
            return
        self._depth_now = depth
        if self.metrics is not None:
            self.metrics.set_gauge("app_tpu_pipeline_depth", float(depth),
                                   program="generate")
        if self._tl is not None:
            self._tl.pipeline_depth(depth)

    def _note_dispatch(self, now: float) -> None:
        """Close an open inter-block gap: the device stream ran dry at
        ``_idle_from`` and this dispatch is the first work queued
        since."""
        if self._idle_from is None:
            return
        gap, self._idle_from = max(0.0, now - self._idle_from), None
        self._record_gap(gap, now)

    def _record_gap(self, gap: float, now: float | None = None) -> None:
        self._gap_samples.append(gap)
        if self.metrics is not None:
            self.metrics.record_histogram("app_tpu_dispatch_gap_duration",
                                          gap, program="generate")
        if self._tl is not None and now is not None and gap > 0.0:
            self._tl.dispatch_gap(now - gap, now)

    def _tick(self, decode_only: bool = False) -> "_Inflight | None":
        """Dispatch one serving tick: a speculative verify pass when the
        engine can use one (spec enabled, every active slot greedy and
        clear of capacity, at least one slot has a draft), else a decode
        block. Returns the in-flight handle (reap delivers) or None.
        ``decode_only``: a pipeline top-up behind an un-reaped block —
        verify windows are built from host-delivered history, which
        does not exist yet (the depth policy already pins spec engines
        to depth 1; this is the structural guard)."""
        if not decode_only and self._spec_k and self._spec_eligible():
            drafts = {idx: self._draft(idx)
                      for idx in range(self.n_slots) if self._active[idx]}
            drafted = sum(d is not None for d in drafts.values())
            # Coverage gate: slots WITHOUT drafts emit 1 token per verify
            # pass vs decode_block per decode dispatch — one repetitive
            # stream must not drag a batch of non-repetitive ones into
            # K-times-slower cadence. Verify only when at least half the
            # active slots would actually speculate.
            if drafted > 0 and 2 * drafted >= len(drafts):
                return self._verify_tick(drafts)
        return self._decode_tick()

    def _spec_eligible(self) -> bool:
        W = self._spec_k + 1
        saw_active = False
        for idx, slot in enumerate(self._slots):
            if not self._active[idx]:
                continue
            req = slot.request
            if req is None or req.temperature > 0:
                return False  # sampling slots need the decode sampler
            if req.stream.prompt_len + slot.generated + W > self.max_seq:
                return False  # would scatter past capacity (llama.
                # verify_step's capacity contract) — the slot retires soon
            saw_active = True
        return saw_active

    def _verify_tick(self, drafts: dict) -> "_Inflight | None":
        """Dispatch one verify pass: window = [last_token, K drafts] per
        slot (zero drafts for slots with no lookup match — they still
        emit their 1 guaranteed token). The reap mirrors _decode_tick's:
        emitted tokens stream in order, retirement mid-window discards
        the rest."""
        W = self._spec_k + 1
        window = np.zeros((self.n_slots, W), np.int32)
        window[:, 0] = self._last_tokens
        for idx, d in drafts.items():
            if d is not None:
                window[idx, 1:] = d
        # the verify pass is greedy-only: the key argument is unused, so
        # pass the live key as-is — no split dispatch, no chain needed
        if self._paged:
            self._ensure_blocks(W)  # window rows span up to W positions
            if not self._active.any():
                return None
            toks, lps, emit, self.cache = self._verify_jit(
                self.cache, self.params, jnp.asarray(window),
                self._dev("active", self._active), self._key,
                self._dev("table", self._table), self._adapters())
        else:
            toks, lps, emit, self.cache = self._verify_jit(
                self.cache, self.params, jnp.asarray(window),
                self._dev("active", self._active), self._key,
                self._adapters())
        # Dispatch-time snapshots: in-flight admissions mutate _active /
        # slot.request before the reap runs, and this window's tokens
        # belong to the slots AS DISPATCHED — a slot that retired and
        # was re-admitted mid-flight must not receive them.
        snap_active = self._active.copy()
        snap_reqs = [s.request for s in self._slots]
        return _Inflight((toks, lps, emit), functools.partial(
            self._verify_reap, toks, lps, emit, snap_active, snap_reqs,
            time.monotonic()))

    # invoked through _Inflight.reap, always under the engine's device
    # lock (see _loop) — the partial hides that from static call-graph
    # inference  # gl: holds self._device_lock
    def _verify_reap(self, toks, lps, emit, snap_active, snap_reqs,
                     t0: float = 0.0) -> None:
        toks_np, lps_np, emit_np = jax.device_get((toks, lps, emit))
        if self._tl is not None:
            self._tl.verify_block(
                t0, time.monotonic(),
                tuple(int(i) for i in np.flatnonzero(snap_active)),
                self._spec_k + 1)
        self._spec_windows += int(snap_active.sum())
        self._spec_emitted += int(emit_np.sum())
        emit_l = emit_np.tolist()
        if self._paged:
            # device cursors advanced by emit (accepted tokens only;
            # zero for slots outside the dispatch mask, so in-flight
            # admissions — cursor set by their own prefill — are safe)
            for idx in range(self.n_slots):
                self._cursors[idx] += emit_l[idx]
        toks_l, lps_l = toks_np.tolist(), lps_np.tolist()
        for idx, slot in enumerate(self._slots):
            if not snap_active[idx] or slot.request is not snap_reqs[idx]:
                continue
            if self._expire_decoding(idx, slot):
                continue
            self._record_itl(slot, emit_l[idx])
            for k in range(emit_l[idx]):
                if not self._active[idx]:
                    break  # retired mid-window (EOS/budget/cancel)
                t = toks_l[idx][k]
                self._last_tokens[idx] = t
                self._hist_append(idx, t)
                self._deliver(idx, slot, t, lps_l[idx][k])
        # a verify pass advanced host state outside the decode carry
        # chain: host wins the next decode dispatch's merge, so sync
        # the budget mirror to what the deliveries left behind
        for idx in np.flatnonzero(snap_active):
            s = self._slots[idx]
            self._budgets[idx] = s.remaining if s.request is not None else 0
            # absolute sampling position mirrors the delivered count
            # (verify passes are greedy, but the mirror must stay true
            # for the next decode dispatch's host_wins merge)
            self._pos_abs[idx] = (s.request.pos_base + s.generated
                                  if s.request is not None else 0)
            if self._paged:
                self._stop_cursors[idx] = (
                    min(int(self._cursors[idx]) + s.remaining,
                        self.max_seq - 2)
                    if s.request is not None else 0)
        self._host_wins |= snap_active
        self._touch("last_tokens", "host_wins", "budgets", "pos")

    def _decode_tick(self) -> "_Inflight | None":
        """Dispatch one fused decode block; the reap fetches [K, B]
        tokens + the emitted mask and delivers in step order. A slot
        that finishes (EOS/budget/capacity) at step k self-deactivates
        ON DEVICE (llama.decode_stop_mask in the scan carry), so the
        waste of an already-finished stream is bounded within ONE block
        even when a second block was dispatched before this one's
        tokens reached the host (pipeline depth 2)."""
        if not self._active.any():
            return None
        if self._paged:
            self._ensure_blocks()  # may retire starving slots
            if not self._active.any():
                return None
        if self._last_dev is None:  # first block / post-recovery:
            # no previous dispatch to chain from — build the slot-state
            # carry from the host arrays
            self._last_dev = self._host_carry()
        t_dispatch = time.monotonic()
        self._note_dispatch(t_dispatch)
        toks, lps, emitted, self._last_dev, self._key, self.cache = \
            self._step_jit(self.cache, self.params, self._dispatch_pack(),
                           self._last_dev, self._key)
        if self._paged:
            # advance bounded by each slot's device stop cursor: the
            # scan freezes a slot there (budget/capacity), so the host
            # view must not run past it while un-reaped blocks pile up
            # behind the pipeline. EOS stops land wherever they land —
            # that over-advance is bounded by one reap.
            adv = np.minimum(
                self.decode_block,
                np.maximum(self._stop_cursors - self._cursors, 0))
            adv = np.where(self._stop_cursors > 0, adv, self.decode_block)
            self._cursors[self._active] += adv[self._active]
        if self._host_wins.any():
            self._host_wins[:] = False
            self._touch("host_wins")
        # snapshots: see _verify_tick — this block's tokens belong to
        # the slots as dispatched, not as mutated by in-flight admissions
        snap_active = self._active.copy()
        snap_reqs = [s.request for s in self._slots]
        return _Inflight((toks, lps, emitted), functools.partial(
            self._decode_reap, toks, lps, emitted, snap_active, snap_reqs,
            t_dispatch))

    # invoked through _Inflight.reap, always under the engine's device
    # lock (see _loop)  # gl: holds self._device_lock
    def _decode_reap(self, toks, lps, emitted, snap_active, snap_reqs,
                     t0: float = 0.0) -> None:
        toks_np, lps_np, emit_np = jax.device_get((toks, lps, emitted))
        if self._tl is not None:
            # one ring event per fused block, fanned out to per-slot
            # slices only at export time — the hot path pays one append
            self._tl.decode_block(
                t0, time.monotonic(),
                tuple(int(i) for i in np.flatnonzero(snap_active)),
                self.decode_block)
        if self.metrics is not None:
            self.metrics.set_gauge("app_tpu_batch_fill",
                                   float(self._active.sum()) / self.n_slots,
                                   program="generate")
        # bulk-convert once: per-element int()/float() on numpy scalars
        # costs real milliseconds per reap at high slot counts
        toks_l, lps_l = toks_np.tolist(), lps_np.tolist()
        emit_l = emit_np.tolist()
        counts = emit_np.sum(axis=0)  # real tokens per slot this block
        for idx, slot in enumerate(self._slots):
            if snap_active[idx] and self._active[idx] \
                    and slot.request is snap_reqs[idx]:
                if self._expire_decoding(idx, slot):
                    continue
                if counts[idx]:
                    self._record_itl(slot, int(counts[idx]))
        for k in range(len(toks_l)):
            trow, lrow, erow = toks_l[k], lps_l[k], emit_l[k]
            for idx, slot in enumerate(self._slots):
                if not snap_active[idx] or not self._active[idx] \
                        or slot.request is not snap_reqs[idx] \
                        or not erow[idx]:
                    # the emitted mask replays the device stop masks:
                    # tokens a self-deactivated slot carried (frozen
                    # repeats) are never delivered, keeping the stream
                    # identical to host-side retirement
                    continue
                self._last_tokens[idx] = trow[idx]
                if self._spec_k:
                    self._hist_append(idx, trow[idx])
                self._deliver(idx, slot, trow[idx], lrow[idx])
