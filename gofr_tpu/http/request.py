"""Transport-level HTTP request implementing the framework Request surface.

Reference: pkg/gofr/http/request.go:22-77 — query/path params, JSON ``Bind``
with body re-buffering, JWT claims accessor, hostname. The abstract Request
interface the handlers see is defined at pkg/gofr/request.go:10-16
(Context/Param/PathParam/Bind/HostName); pub/sub Messages implement the same
surface (datasource/pubsub/message.go:8-50) so one handler shape serves both.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Mapping
from urllib.parse import parse_qs, unquote, urlsplit

from ..errors import BadRequest


class Request:
    def __init__(
        self,
        method: str = "GET",
        path: str = "/",
        headers: Mapping[str, str] | None = None,
        body: bytes = b"",
        path_params: Mapping[str, str] | None = None,
        remote_addr: str = "",
    ):
        self.method = method.upper()
        split = urlsplit(path)
        # decode %XX escapes so path params and query params are consistent
        self.path = unquote(split.path) or "/"
        self.query: dict[str, list[str]] = parse_qs(split.query, keep_blank_values=True)
        # header lookup is case-insensitive
        self._headers = {k.lower(): v for k, v in (headers or {}).items()}
        self.body = body
        self.path_params: dict[str, str] = dict(path_params or {})
        self.remote_addr = remote_addr
        self.claims: dict[str, Any] | None = None  # set by OAuth middleware

    # -- framework Request interface ---------------------------------------
    def param(self, key: str, default: str = "") -> str:
        """First query-string value (reference request.go Param)."""
        vals = self.query.get(key)
        return vals[0] if vals else default

    def params(self, key: str) -> list[str]:
        return self.query.get(key, [])

    def path_param(self, key: str, default: str = "") -> str:
        return self.path_params.get(key, default)

    def header(self, key: str, default: str = "") -> str:
        return self._headers.get(key.lower(), default)

    @property
    def headers(self) -> dict[str, str]:
        return dict(self._headers)

    def host_name(self) -> str:
        proto = self._headers.get("x-forwarded-proto", "http")
        return f"{proto}://{self._headers.get('host', '')}"

    def content_type(self) -> str:
        return self._headers.get("content-type", "")

    def bind(self, into: type | None = None) -> Any:
        """Deserialize the JSON body; optionally into a dataclass
        (reference request.go:41-48 Bind unmarshals into a target struct)."""
        if not self.body:
            raise BadRequest("request body is empty")
        try:
            data = json.loads(self.body)
        except json.JSONDecodeError as e:
            raise BadRequest(f"invalid JSON body: {e}") from e
        if into is None:
            return data
        if dataclasses.is_dataclass(into):
            names = {f.name for f in dataclasses.fields(into)}
            if not isinstance(data, dict):
                raise BadRequest("JSON body must be an object")
            return into(**{k: v for k, v in data.items() if k in names})
        if callable(into):
            return into(data)
        raise BadRequest(f"cannot bind into {into!r}")

    def get_claims(self) -> dict[str, Any]:
        """JWT claims placed by OAuth middleware
        (reference request.go:50-66 GetClaims)."""
        return self.claims or {}
