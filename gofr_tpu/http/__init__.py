"""HTTP transport: server, router, middleware, request/responder.

Reference: pkg/gofr/http/ (+ middleware/, response/) and httpServer.go.
"""

from .request import Request
from .responder import Responder, Raw, FileResponse, ResponseWriter
from .router import Router, Route
from .server import HTTPServer

__all__ = [
    "Request",
    "Responder",
    "Raw",
    "FileResponse",
    "ResponseWriter",
    "Router",
    "Route",
    "HTTPServer",
]
