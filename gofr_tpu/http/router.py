"""Router with path templates and a middleware chain.

Reference: pkg/gofr/http/router.go:13-34 wraps gorilla/mux and installs the
Tracer -> Logging -> CORS -> Metrics middleware chain. Here routes are
``/path/{param}`` templates compiled to regexes; middleware are
``Callable[[Handler], Handler]`` wrappers applied outermost-first, exactly the
order the reference uses.
"""

from __future__ import annotations

import re
from typing import Callable

from .request import Request
from .responder import ResponseWriter

# A transport handler: consumes the request, fills the writer.
Handler = Callable[[Request, ResponseWriter], None]
Middleware = Callable[[Handler], Handler]

_PARAM_RE = re.compile(r"\{([a-zA-Z_][a-zA-Z0-9_]*)\}")


def compile_template(path: str) -> re.Pattern:
    parts: list[str] = []
    idx = 0
    for m in _PARAM_RE.finditer(path):
        parts.append(re.escape(path[idx:m.start()]))
        parts.append(f"(?P<{m.group(1)}>[^/]+)")
        idx = m.end()
    parts.append(re.escape(path[idx:]))
    return re.compile("^" + "".join(parts) + "/?$")


class Route:
    def __init__(self, method: str, path: str, handler: Handler):
        self.method = method.upper()
        self.path = path
        self.pattern = compile_template(path)
        self.handler = handler


class Router:
    def __init__(self) -> None:
        self.routes: list[Route] = []
        self.middleware: list[Middleware] = []
        self._catch_all: Handler | None = None
        self._compiled: Handler | None = None

    def add(self, method: str, path: str, handler: Handler) -> None:
        self.routes.append(Route(method, path, handler))
        self._compiled = None

    def use(self, mw: Middleware) -> None:
        """Append middleware (reference router.go:19-24 UseMiddleware)."""
        self.middleware.append(mw)
        self._compiled = None

    def set_catch_all(self, handler: Handler) -> None:
        """404 fallthrough route (reference handler.go:57 catchAllHandler)."""
        self._catch_all = handler
        self._compiled = None

    # -- dispatch -----------------------------------------------------------
    def _dispatch(self, req: Request, w: ResponseWriter) -> None:
        path_matched = False
        for route in self.routes:
            m = route.pattern.match(req.path)
            if m is None:
                continue
            path_matched = True
            if route.method == req.method:
                req.path_params.update(m.groupdict())
                # route template for low-cardinality metrics labels
                req.matched_route = route.path  # type: ignore[attr-defined]
                route.handler(req, w)
                return
        if self._catch_all is not None:
            self._catch_all(req, w)
            return
        w.status = 405 if path_matched else 404
        w.set_header("Content-Type", "application/json")
        w.write(b'{"error":{"message":"route not found"}}' if w.status == 404
                else b'{"error":{"message":"method not allowed"}}')

    def handler(self) -> Handler:
        """Compose middleware around dispatch; first-added runs outermost."""
        if self._compiled is None:
            h: Handler = self._dispatch
            for mw in reversed(self.middleware):
                h = mw(h)
            self._compiled = h
        return self._compiled

    def __call__(self, req: Request, w: ResponseWriter) -> None:
        self.handler()(req, w)
