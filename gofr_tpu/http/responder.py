"""Response serialization: JSON envelope ``{"data": ...}`` / ``{"error": ...}``.

Reference: pkg/gofr/http/responder.go:19-57 (Respond + HTTPStatusFromError)
and pkg/gofr/http/response/ (Raw, File).
"""

from __future__ import annotations

import dataclasses
import json
import mimetypes
import os
from typing import Any

from ..errors import HTTPError, status_from_error


class ResponseWriter:
    """Accumulates status/headers/body; the server flushes it to the socket.
    Also plays the reference's StatusResponseWriter role
    (middleware/logger.go:14-31) — middleware reads ``status`` after the
    handler ran."""

    def __init__(self) -> None:
        self.status: int = 200
        self.headers: dict[str, str] = {}
        self.body: bytes = b""
        self._streaming: bool = False
        self._chunks: list[bytes] = []

    def set_header(self, key: str, value: str) -> None:
        self.headers[key] = value

    def write(self, data: bytes) -> None:
        self.body += data

    def write_chunk(self, data: bytes) -> None:
        """Streaming (chunked/SSE) support — no reference equivalent; needed
        for token streaming over HTTP."""
        self._streaming = True
        self._chunks.append(data)

    def stream_from(self, source) -> None:
        """Drain a chunk source (any iterable of bytes). The live HTTP
        server replaces this per-request with a zero-handoff writer
        that lets a push-capable source (``GenStream.map(...)``, see
        gofr_tpu.wire.PushStream) deliver chunks on the producing
        thread; this default just iterates, which keeps handler tests
        and non-streaming servers working unchanged."""
        for chunk in source:
            self.write_chunk(bytes(chunk))


class Raw:
    """Bypass the envelope: serialize ``data`` as-is
    (reference response/raw.go)."""

    def __init__(self, data: Any):
        self.data = data


class FileResponse:
    """Serve file bytes with a content type (reference response/file.go)."""

    def __init__(self, content: bytes, content_type: str | None = None, name: str = ""):
        self.content = content
        self.name = name
        if content_type is None and name:
            content_type = mimetypes.guess_type(name)[0]
        self.content_type = content_type or "application/octet-stream"

    @classmethod
    def from_path(cls, path: str) -> "FileResponse":
        with open(path, "rb") as f:
            return cls(f.read(), name=os.path.basename(path))


def _jsonable(data: Any) -> Any:
    if dataclasses.is_dataclass(data) and not isinstance(data, type):
        return dataclasses.asdict(data)
    if hasattr(data, "to_dict"):
        return data.to_dict()
    if isinstance(data, (list, tuple)):
        return [_jsonable(d) for d in data]
    if isinstance(data, dict):
        return {k: _jsonable(v) for k, v in data.items()}
    if isinstance(data, bytes):
        return data.decode("utf-8", "replace")
    return data


class Responder:
    """Serializes (data, error) to the wire (reference responder.go:19-45)."""

    def __init__(self, writer: ResponseWriter):
        self.writer = writer

    def respond(self, data: Any, error: BaseException | None = None) -> None:
        w = self.writer
        if error is not None:
            status = status_from_error(error)
            detail = (error.to_dict() if isinstance(error, HTTPError)
                      else {"message": str(error) or "internal server error"})
            w.status = status
            # errors may carry response headers (TooManyRequests ->
            # Retry-After; drain -> Retry-After): honest backpressure
            # the client-side retry policy reads
            for k, v in getattr(error, "headers", {}).items():
                w.set_header(k, str(v))
            w.set_header("Content-Type", "application/json")
            w.write(json.dumps({"error": detail}, default=str).encode())
            return
        if isinstance(data, FileResponse):
            w.set_header("Content-Type", data.content_type)
            w.write(data.content)
            return
        if isinstance(data, Raw):
            w.set_header("Content-Type", "application/json")
            w.write(json.dumps(_jsonable(data.data), default=str).encode())
            return
        w.set_header("Content-Type", "application/json")
        w.write(json.dumps({"data": _jsonable(data)}, default=str).encode())
