"""HTTP middleware chain: tracer, logging+recovery, CORS, metrics, auth.

Reference: pkg/gofr/http/middleware/ —
  - tracer.go:14-30   extract W3C traceparent, start span "METHOD /path"
  - logger.go:42-117  status-capturing request log with trace/span ids and
                      microsecond latency, X-Correlation-ID header, panic
                      recovery -> 500 JSON
  - cors.go:5-19      Access-Control-Allow-* headers, short-circuit OPTIONS
  - metrics.go:20-41  app_http_response histogram labeled path/method/status
  - basic_auth.go, apikey_auth.go, oauth.go — the three auth schemes
"""

from __future__ import annotations

import base64
import hmac
import json
import threading
import time
from typing import Callable, Iterable

from ..errors import HTTPError, format_retry_after
from ..resilience import (Deadline, deadline_scope, parse_http_timeout,
                          parse_slo_class, slo_scope)
from .request import Request
from .responder import ResponseWriter
from .router import Handler, Middleware


class RequestLog:
    """Structured request log entry (reference middleware/logger.go:33-40)."""

    def __init__(self, trace_id: str, span_id: str, method: str, uri: str,
                 status: int, duration_us: int, ip: str):
        self.trace_id = trace_id
        self.span_id = span_id
        self.method = method
        self.uri = uri
        self.status = status
        self.duration_us = duration_us
        self.ip = ip

    def log_fields(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "method": self.method,
            "uri": self.uri,
            "response": self.status,
            "duration": self.duration_us,
            "ip": self.ip,
        }

    def pretty_print(self) -> str:
        return (f"{self.trace_id[:8]} {self.status} {self.duration_us:>8}µs "
                f"{self.method:<7} {self.uri}")


def get_ip_address(req: Request) -> str:
    """reference middleware/logger.go:75-92 getIPAddress."""
    fwd = req.header("X-Forwarded-For")
    if fwd:
        return fwd.split(",")[0].strip()
    return req.remote_addr


def tracer_middleware(tracer) -> Middleware:
    def mw(next_h: Handler) -> Handler:
        def wrapped(req: Request, w: ResponseWriter) -> None:
            span = tracer.start_span(
                f"{req.method} {req.path}",
                traceparent=req.header("traceparent") or None,
                attributes={"http.method": req.method, "http.target": req.path},
            )
            try:
                next_h(req, w)
                span.set_attribute("http.status_code", w.status)
            finally:
                span.end()
        return wrapped
    return mw


def logging_middleware(logger) -> Middleware:
    """Request log + panic recovery (reference logger.go:42-73 and :94-117)."""
    from .. import tracing

    def mw(next_h: Handler) -> Handler:
        def wrapped(req: Request, w: ResponseWriter) -> None:
            start = time.monotonic_ns()
            span = tracing.current_span()
            trace_id = span.trace_id if span else ""
            span_id = span.span_id if span else ""
            if trace_id:
                w.set_header("X-Correlation-ID", trace_id)
            try:
                next_h(req, w)
            except Exception as e:  # recovery: never let a handler kill the server
                logger.error({"event": "panic recovered", "error": repr(e), "uri": req.path})
                w.status = 500
                w.headers.setdefault("Content-Type", "application/json")
                w.body = b'{"error":{"message":"internal server error"}}'
            finally:
                dur_us = (time.monotonic_ns() - start) // 1000
                logger.info(RequestLog(trace_id, span_id, req.method, req.path,
                                       w.status, dur_us, get_ip_address(req)))
        return wrapped
    return mw


def cors_middleware(allowed_origin: str = "*",
                    allowed_headers: str = ("Authorization, Content-Type, "
                                            "x-requested-with, origin, "
                                            "true-client-ip, X-Correlation-ID"),
                    allowed_methods: str = "GET, POST, PUT, PATCH, DELETE, OPTIONS") -> Middleware:
    def mw(next_h: Handler) -> Handler:
        def wrapped(req: Request, w: ResponseWriter) -> None:
            w.set_header("Access-Control-Allow-Origin", allowed_origin)
            w.set_header("Access-Control-Allow-Headers", allowed_headers)
            w.set_header("Access-Control-Allow-Methods", allowed_methods)
            if req.method == "OPTIONS":
                w.status = 200
                return
            next_h(req, w)
        return wrapped
    return mw


def deadline_middleware(header: str = "X-Request-Timeout") -> Middleware:
    """Parse the request's timeout header into an AMBIENT deadline
    (resilience.deadline_scope) for the handler's thread — the HTTP
    mirror of gRPC's ``grpc-timeout``. Downstream, ``ctx.tpu.predict``
    and ``generate`` cap their waits to the remaining budget and the
    dispatcher drops the item unexecuted if it expires while queued
    (-> 504 with ``app_tpu_expired_dropped_total`` incremented)."""
    def mw(next_h: Handler) -> Handler:
        def wrapped(req: Request, w: ResponseWriter) -> None:
            timeout = parse_http_timeout(req.header(header))
            if timeout is None:
                return next_h(req, w)
            with deadline_scope(Deadline.after(timeout)):
                next_h(req, w)
        return wrapped
    return mw


def slo_class_middleware(header: str = "X-SLO-Class") -> Middleware:
    """Parse the request's SLO class header into the AMBIENT class
    (resilience.slo_scope) for the handler's thread — the HTTP mirror
    of gRPC's ``slo-class`` metadata. Downstream, ``ctx.tpu.predict``
    and ``generate`` pick it up: ``throughput`` (aliases: batch, bulk,
    offline) marks the request as deprioritizable batch work — longer
    queueing for fuller batches, shed/browned-out first under overload
    — while anything else (including no header) keeps the full
    latency-class SLO (docs/advanced-guide/serving-scheduler.md)."""
    from .. import tracing

    def mw(next_h: Handler) -> Handler:
        def wrapped(req: Request, w: ResponseWriter) -> None:
            with slo_scope(parse_slo_class(req.header(header))) as cls:
                span = tracing.current_span()
                if span is not None:
                    # the tail sampler's per-class slow-tail estimate
                    # keys on the ROOT span's slo_class; tagging here
                    # (inside the tracer middleware) puts it there
                    span.set_attribute("slo_class", cls)
                next_h(req, w)
        return wrapped
    return mw


def tenant_middleware(resolver: Callable[[], object] | None = None,
                      header: str = "X-Tenant-Id") -> Middleware:
    """Parse the request's tenant header into the AMBIENT tenant
    (tenancy.tenant_scope) for the handler's thread — the HTTP mirror
    of gRPC's ``x-tenant-id`` metadata. ``resolver`` is a LAZY callable
    returning the engine's TenantPlane (or None): the middleware chain
    is built before the container wires the engine, and tenancy may be
    off entirely. With a plane installed the raw header canonicalizes
    through the registry (unknown ids collapse to the default spec, so
    one id per CONFIGURED tenant bounds label cardinality downstream);
    without one the header still scopes — wide events and traces carry
    it — but no quota/weight/cache policy applies
    (docs/advanced-guide/multi-tenancy.md)."""
    from .. import tracing
    from ..tenancy.registry import tenant_scope

    def mw(next_h: Handler) -> Handler:
        def wrapped(req: Request, w: ResponseWriter) -> None:
            raw = (req.header(header) or "").strip()
            plane = resolver() if resolver is not None else None
            tid = raw
            if plane is not None and raw:
                try:
                    tid = plane.resolve(raw).tenant_id
                except Exception:
                    tid = raw
            with tenant_scope(tid or None) as tenant:
                span = tracing.current_span()
                if span is not None:
                    span.set_attribute("tenant", tenant)
                next_h(req, w)
        return wrapped
    return mw


def drain_middleware(is_draining: Callable[[], bool],
                     retry_after: Callable[[], float | None]) -> Middleware:
    """Readiness gate for graceful shutdown: once the app starts
    draining, NEW requests get 503 + Retry-After immediately (load
    balancers stop routing; clients back off) while requests already
    inside a handler run to completion on their own threads. The
    liveness probe stays 200 — the process is healthy, just leaving."""
    def mw(next_h: Handler) -> Handler:
        def wrapped(req: Request, w: ResponseWriter) -> None:
            if is_draining() and req.path != "/.well-known/alive":
                w.status = 503
                ra = retry_after()
                if ra is not None:
                    w.set_header("Retry-After", format_retry_after(ra))
                w.set_header("Content-Type", "application/json")
                w.write(b'{"error":{"message":"server draining"}}')
                return
            next_h(req, w)
        return wrapped
    return mw


def inflight_middleware(registry) -> Middleware:
    """Register every request in the in-flight registry for the lifetime
    of its handler, so /debug/requests can answer "what is this server
    doing right now?" (x/net/trace style). Runs inside the tracer
    middleware so the entry carries the request's trace id."""
    from .. import tracing

    def mw(next_h: Handler) -> Handler:
        def wrapped(req: Request, w: ResponseWriter) -> None:
            span = tracing.current_span()
            entry = registry.add(
                "http", f"{req.method} {req.path}",
                span.trace_id if span else "", stage="handler")
            try:
                next_h(req, w)
            finally:
                registry.remove(entry)
        return wrapped
    return mw


def metrics_middleware(metrics) -> Middleware:
    from .. import tracing

    def mw(next_h: Handler) -> Handler:
        def wrapped(req: Request, w: ResponseWriter) -> None:
            start = time.monotonic()
            try:
                next_h(req, w)
            finally:
                # label by route template, not raw URI, to bound cardinality
                # (the reference gets this via mux route templates); unmatched
                # requests share one fixed label for the same reason
                path = getattr(req, "matched_route", None) or "unmatched"
                span = tracing.current_span()
                metrics.record_histogram(
                    "app_http_response", time.monotonic() - start,
                    exemplar=span.trace_id if span is not None else None,
                    path=path, method=req.method, status=str(w.status),
                )
        return wrapped
    return mw


def _unauthorized(w: ResponseWriter, message: str = "Unauthorized") -> None:
    w.status = 401
    w.set_header("Content-Type", "application/json")
    w.write(json.dumps({"error": {"message": message}}).encode())


_WELL_KNOWN_SKIP = ("/.well-known/health", "/.well-known/alive", "/metrics")


def basic_auth_middleware(users: dict[str, str] | None = None,
                          validate: Callable[[str, str], bool] | None = None) -> Middleware:
    """reference middleware/basic_auth.go:16-58 — map of user->password or a
    validation function."""
    def check(user: str, password: str) -> bool:
        if validate is not None:
            return validate(user, password)
        expected = (users or {}).get(user)
        # compare bytes: compare_digest raises TypeError on non-ASCII str
        return expected is not None and hmac.compare_digest(
            expected.encode(), password.encode())

    def mw(next_h: Handler) -> Handler:
        def wrapped(req: Request, w: ResponseWriter) -> None:
            if req.path in _WELL_KNOWN_SKIP:
                return next_h(req, w)
            header = req.header("Authorization")
            if not header.startswith("Basic "):
                return _unauthorized(w)
            try:
                decoded = base64.b64decode(header[6:]).decode()
                user, _, password = decoded.partition(":")
            except Exception:
                return _unauthorized(w, "invalid authorization header")
            if not check(user, password):
                return _unauthorized(w)
            next_h(req, w)
        return wrapped
    return mw


def apikey_auth_middleware(keys: Iterable[str] = (),
                           validate: Callable[[str], bool] | None = None) -> Middleware:
    """reference middleware/apikey_auth.go:7-41 — X-API-KEY header."""
    keyset = set(keys)

    def mw(next_h: Handler) -> Handler:
        def wrapped(req: Request, w: ResponseWriter) -> None:
            if req.path in _WELL_KNOWN_SKIP:
                return next_h(req, w)
            key = req.header("X-API-KEY")
            if not key:
                return _unauthorized(w)
            ok = validate(key) if validate is not None else key in keyset
            if not ok:
                return _unauthorized(w)
            next_h(req, w)
        return wrapped
    return mw


class JWKSKeyProvider:
    """Background-refreshed JWKS key cache
    (reference middleware/oauth.go:47-84: refresh goroutine + JWKS parsing
    :126-180). Fetching uses urllib; RSA verification uses ``cryptography``
    when available and falls back to rejecting RS256 otherwise."""

    def __init__(self, jwks_url: str, refresh_interval: float = 300.0, http_get=None):
        self.jwks_url = jwks_url
        self.refresh_interval = refresh_interval
        self._keys: dict[str, dict] = {}
        self._lock = threading.Lock()
        self._http_get = http_get or self._default_get
        self._stop = threading.Event()
        self.refresh()
        self._thread = threading.Thread(target=self._loop, daemon=True, name="jwks-refresh")
        self._thread.start()

    @staticmethod
    def _default_get(url: str) -> bytes:
        import urllib.request

        with urllib.request.urlopen(url, timeout=5) as r:
            return r.read()

    def _loop(self) -> None:
        while not self._stop.wait(self.refresh_interval):
            self.refresh()

    def refresh(self) -> None:
        try:
            data = json.loads(self._http_get(self.jwks_url))
            keys = {k.get("kid", ""): k for k in data.get("keys", [])}
            with self._lock:
                self._keys = keys
        except Exception:
            pass

    def get(self, kid: str) -> dict | None:
        with self._lock:
            return self._keys.get(kid)

    def shutdown(self) -> None:
        self._stop.set()


def _b64url_decode(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def verify_jwt(token: str, key_provider: JWKSKeyProvider) -> dict:
    """Validate an RS256 JWT against JWKS keys; returns claims.
    Reference: middleware/oauth.go:86-123."""
    try:
        header_b64, payload_b64, sig_b64 = token.split(".")
        header = json.loads(_b64url_decode(header_b64))
        payload = json.loads(_b64url_decode(payload_b64))
        signature = _b64url_decode(sig_b64)
    except Exception as e:
        raise HTTPError("invalid token", 401) from e

    if header.get("alg") != "RS256":
        raise HTTPError("unsupported signing algorithm", 401)
    jwk = key_provider.get(header.get("kid", ""))
    if jwk is None:
        raise HTTPError("unknown signing key", 401)

    try:
        from cryptography.hazmat.primitives.asymmetric import padding, rsa
        from cryptography.hazmat.primitives import hashes
    except ImportError as e:  # pragma: no cover - env-dependent
        raise HTTPError("RS256 verification unavailable", 401) from e

    n = int.from_bytes(_b64url_decode(jwk["n"]), "big")
    e = int.from_bytes(_b64url_decode(jwk["e"]), "big")
    pub = rsa.RSAPublicNumbers(e, n).public_key()
    try:
        pub.verify(signature, f"{header_b64}.{payload_b64}".encode(),
                   padding.PKCS1v15(), hashes.SHA256())
    except Exception as ex:
        raise HTTPError("invalid token signature", 401) from ex

    exp = payload.get("exp")
    if exp is not None and time.time() > float(exp):
        raise HTTPError("token expired", 401)
    return payload


def oauth_middleware(key_provider: JWKSKeyProvider) -> Middleware:
    def mw(next_h: Handler) -> Handler:
        def wrapped(req: Request, w: ResponseWriter) -> None:
            if req.path in _WELL_KNOWN_SKIP:
                return next_h(req, w)
            header = req.header("Authorization")
            if not header.startswith("Bearer "):
                return _unauthorized(w)
            try:
                req.claims = verify_jwt(header[7:], key_provider)
            except HTTPError as e:
                return _unauthorized(w, e.message)
            next_h(req, w)
        return wrapped
    return mw
