"""Threaded HTTP/1.1 server flushing Router-produced responses.

Reference: pkg/gofr/httpServer.go:12-36 wraps net/http.Server on HTTP_PORT
(default 8000, default.go:4) with a 5s read-header timeout. Python
equivalent: a ThreadingHTTPServer with a per-request dispatch into the
router. Supports chunked streaming responses (needed for token streaming;
the reference has no HTTP streaming path).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import chaos
from ..wire import SocketWriter, WAKE
from .request import Request
from .responder import ResponseWriter
from .router import Router


def _chunk(data: bytes) -> bytes:
    return b"%x\r\n" % len(data) + data + b"\r\n"


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    router: Router = None  # type: ignore[assignment]
    logger = None
    timeout = 5  # read timeout, mirrors the reference's ReadHeaderTimeout

    # silence default stderr access logs — the logging middleware owns this
    def log_message(self, fmt: str, *args) -> None:
        pass

    def _handle(self) -> None:
        chaos.fire(chaos.HTTP_REQUEST)
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        req = Request(
            method=self.command,
            path=self.path,
            headers=dict(self.headers.items()),
            body=body,
            remote_addr=self.client_address[0],
        )
        w = ResponseWriter()
        stream_started = threading.Event()
        # streaming writes bypass wfile: one SocketWriter per request
        # carries status+headers+first chunk in a single vectored write
        # and lets a zero-handoff sink park bytes nonblocking (wfile is
        # an unbuffered per-write sendall)
        raw: list[SocketWriter] = []

        def _writer() -> SocketWriter:
            if not raw:
                raw.append(SocketWriter(self.connection))
            return raw[0]

        def _stream_head() -> bytes:
            """Status line + headers, assembled by hand so they can ride
            in the same syscall as the first chunk (BaseHTTPRequestHandler
            flushes its header buffer on end_headers)."""
            phrase = self.responses.get(w.status, ("", ""))[0]
            head = [f"{self.protocol_version} {w.status} {phrase}",
                    f"Server: {self.version_string()}",
                    f"Date: {self.date_time_string()}"]
            head += [f"{k}: {v}" for k, v in w.headers.items()]
            head.append("Transfer-Encoding: chunked")
            return ("\r\n".join(head) + "\r\n\r\n").encode("latin-1")

        def _emit_chunk(data: bytes, block: bool) -> bool:
            if not stream_started.is_set():
                stream_started.set()
                # headers + first chunk: ONE write, one packet on the
                # wire — the HTTP mirror of the gRPC HEADERS+DATA
                # coalescing on the first-token path
                return _writer().write([_stream_head(), _chunk(data)],
                                       block=block)
            return _writer().write(_chunk(data), block=block)

        try:
            # streaming: if a handler writes chunks, flush them live
            original_write_chunk = w.write_chunk
            original_stream_from = w.stream_from

            def live_chunk(data: bytes) -> None:
                _emit_chunk(data, block=True)

            def live_stream_from(source) -> None:
                """Zero-handoff chunk streaming: a push-capable source
                (GenStream.map(...)) delivers each chunk on the
                PRODUCING thread via a nonblocking sink; this handler
                thread only waits for end-of-stream and flushes."""
                w._streaming = True

                wake = getattr(source, "wake", None)

                def sink(data: bytes) -> bool:
                    if not _emit_chunk(bytes(data), block=False) \
                            and wake is not None:
                        # bytes parked in the writer backlog have no
                        # other waker until the next chunk — rouse this
                        # handler thread to flush them
                        wake()
                    return True

                set_sink = getattr(source, "set_sink", None)
                if set_sink is not None:
                    set_sink(sink)
                try:
                    for chunk in source:  # declined items + end detection
                        if chunk is WAKE:
                            _writer().flush()  # drain sink-parked bytes
                            continue
                        live_chunk(bytes(chunk))
                finally:
                    clear = getattr(source, "clear_sink", None)
                    if clear is not None:
                        clear()
                _writer().flush()  # drain bytes the sink parked

            w.write_chunk = live_chunk  # type: ignore[method-assign]
            w.stream_from = live_stream_from  # type: ignore[method-assign]
            self.router(req, w)
            w.write_chunk = original_write_chunk  # type: ignore[method-assign]
            w.stream_from = original_stream_from  # type: ignore[method-assign]
        except (BrokenPipeError, ConnectionResetError):
            return
        except Exception as e:  # router middleware should have caught this
            if self.logger is not None:
                self.logger.error({"event": "unhandled server error", "error": repr(e)})
            w = ResponseWriter()
            w.status = 500
            w.set_header("Content-Type", "application/json")
            w.write(b'{"error":{"message":"internal server error"}}')

        try:
            if stream_started.is_set():
                # blocking terminal chunk: drains any sink backlog first
                _writer().write(b"0\r\n\r\n", block=True)
                return
            self.send_response(w.status)
            for k, v in w.headers.items():
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(w.body)))
            self.end_headers()
            if self.command != "HEAD":
                self.wfile.write(w.body)
        except (BrokenPipeError, ConnectionResetError):  # noqa: GL303
            pass  # client hung up while we wrote its response: there
            # is no one left to route the failure to

    do_GET = do_POST = do_PUT = do_PATCH = do_DELETE = do_OPTIONS = do_HEAD = _handle


class HTTPServer:
    def __init__(self, router: Router, port: int = 8000, logger=None, host: str = "0.0.0.0"):
        self.router = router
        self.port = port
        self.host = host
        self.logger = logger
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        handler_cls = type("BoundHandler", (_Handler,),
                           {"router": self.router, "logger": self.logger})
        self._server = ThreadingHTTPServer((self.host, self.port), handler_cls)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]  # resolve port 0
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name=f"http-server-{self.port}")
        self._thread.start()
        if self.logger is not None:
            self.logger.info({"event": "http server started", "port": self.port})

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self.logger is not None:
            self.logger.info({"event": "http server stopped", "port": self.port})
