"""Dependency container: the one object carrying every shared resource.

Reference: pkg/gofr/container/container.go:26-38 (Container with embedded
Logger, Services, metricsManager, PubSub, Redis, SQL) and :44-126
(``NewContainer(conf)`` wiring everything from config with graceful
degradation — a down datasource logs and stays None instead of failing
startup). Health aggregation: container/health.go:5-25. The TPU engine is a
first-class datasource here — the whole point of the framework.
"""

from __future__ import annotations

from typing import Any

from . import metrics as gmetrics
from . import tracing
from .config import Config, EnvConfig
from .datasource import Health, STATUS_DOWN, STATUS_UP
from .glog import Logger, LogLevel, new_logger


class Container:
    def __init__(self, config: Config | None = None, logger: Logger | None = None):
        self.config: Config = config if config is not None else EnvConfig()
        self.app_name = self.config.get_or_default("APP_NAME", "gofr-app")
        self.app_version = self.config.get_or_default("APP_VERSION", "dev")

        self.logger: Logger = logger if logger is not None else new_logger(
            LogLevel.parse(self.config.get("LOG_LEVEL"))
        )
        self.metrics = gmetrics.Manager(logger=self.logger)
        gmetrics.register_framework_metrics(self.metrics)
        # tail-sampled when exporting (TPU_TRACE_SAMPLE); the metrics
        # handle feeds app_tpu_spans_dropped_total from the bounded
        # export buffer
        self.tracer = tracing.tracer_from_config(self.config, self.app_name,
                                                 metrics=self.metrics)
        # Inference flight recorder + in-flight registry + serving
        # timeline (observe/): always on, shared by HTTP middleware and
        # the TPU datasource, rendered by the /debug pages on the
        # metrics server.
        from .observe import ClockRegistry, Observe, timeline_from_config

        self.observe = Observe(
            metrics=self.metrics, tracer=self.tracer,
            max_events=self.config.get_int("DEBUG_EVENT_BUFFER", 2048),
            timeline=timeline_from_config(self.config),
            clock=ClockRegistry(
                window=self.config.get_int("TPU_OBS_CLOCK_WINDOW", 64)))

        # Datasources — wired from config, graceful degradation throughout
        self.redis = None
        self.sql = None
        self.pubsub = None
        self.tpu = None
        self.services: dict[str, Any] = {}
        self._remote_level_poller = None

        self._wire_datasources()
        self._wire_remote_log_level()

    # -- wiring -------------------------------------------------------------
    def _wire_datasources(self) -> None:
        cfg, log = self.config, self.logger
        if cfg.get("REDIS_HOST"):
            try:
                from .datasource.redisclient import new_redis_client

                self.redis = new_redis_client(cfg, log, self.metrics)
            except Exception as e:
                log.error({"event": "redis connect failed", "error": repr(e)})
        if cfg.get("DB_DIALECT") or cfg.get("DB_HOST"):
            try:
                from .datasource.sql import new_sql

                self.sql = new_sql(cfg, log, self.metrics)
            except Exception as e:
                log.error({"event": "sql connect failed", "error": repr(e)})
        backend = (cfg.get("PUBSUB_BACKEND") or "").upper()
        if backend:
            try:
                from .datasource.pubsub import new_pubsub_client

                self.pubsub = new_pubsub_client(backend, cfg, log, self.metrics)
            except Exception as e:
                log.error({"event": "pubsub connect failed", "backend": backend, "error": repr(e)})
        if cfg.get("TPU_MODEL") or cfg.get_bool("TPU_ENABLED"):
            try:
                from .tpu import new_engine_from_config

                self.tpu = new_engine_from_config(cfg, log, self.metrics,
                                                  observe=self.observe)
            except Exception as e:
                log.error({"event": "tpu engine init failed", "error": repr(e)})

    def _wire_remote_log_level(self) -> None:
        """Reference: logging/dynamicLevelLogger.go wired at
        container/container.go:64-67 — poll REMOTE_LOG_URL for level changes."""
        url = self.config.get("REMOTE_LOG_URL")
        if not url:
            return
        try:
            from .remote_level import RemoteLevelPoller

            interval = self.config.get_float("REMOTE_LOG_FETCH_INTERVAL", 15.0)
            self._remote_level_poller = RemoteLevelPoller(self.logger, url, interval)
        except Exception as e:
            self.logger.error({"event": "remote log level init failed", "error": repr(e)})

    # -- service registry (container/container.go:130) ----------------------
    def register_service(self, name: str, svc: Any) -> None:
        self.services[name] = svc

    def get_http_service(self, name: str) -> Any:
        return self.services.get(name)

    def get_publisher(self):
        return self.pubsub

    def get_subscriber(self):
        return self.pubsub

    # -- health (container/health.go:5-25) ----------------------------------
    def health(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "name": self.app_name,
            "version": self.app_version,
            "status": STATUS_UP,
        }
        for name, ds in (("redis", self.redis), ("sql", self.sql),
                         ("pubsub", self.pubsub), ("tpu", self.tpu)):
            if ds is None:
                continue
            try:
                h: Health = ds.health_check()
                out[name] = h.to_dict()
                if h.status == STATUS_DOWN:
                    out["status"] = STATUS_DOWN
            except Exception as e:
                out[name] = {"status": STATUS_DOWN, "details": {"error": repr(e)}}
                out["status"] = STATUS_DOWN
        services = {}
        for name, svc in self.services.items():
            try:
                services[name] = svc.health_check().to_dict()
            except Exception as e:
                services[name] = {"status": STATUS_DOWN, "details": {"error": repr(e)}}
        if services:
            out["services"] = services
        return out

    def close(self) -> None:
        # registered service clients first: a CircuitBreaker whose target
        # already shut down keeps a recovery-probe thread alive (5 s
        # health probes against a dead port) until its close() stops it —
        # the post-suite ERROR-log leak VERDICT r3 weak #6 flagged
        for svc in self.services.values():
            if hasattr(svc, "close"):
                try:
                    svc.close()
                except Exception:
                    pass
        for ds in (self.redis, self.sql, self.pubsub, self.tpu):
            if ds is not None and hasattr(ds, "close"):
                try:
                    ds.close()
                except Exception:
                    pass
        if self._remote_level_poller is not None:
            self._remote_level_poller.stop()
        if self.tracer is not None and self.tracer.exporter is not None:
            self.tracer.exporter.shutdown()  # final span flush
