"""Overload-safe serving primitives: deadlines, admission control, brownout.

No reference equivalent (the reference's resilience surface is the
client-side circuit breaker, pkg/gofr/service/circuit_breaker.go; nothing
server-side sheds load). This module is the serving-side discipline of
Dean & Barroso's "The Tail at Scale" applied to GoFr's one-Context
handler model:

  - ``Deadline``: one absolute-monotonic expiry threaded from the wire
    (gRPC ``grpc-timeout`` / HTTP ``X-Request-Timeout``) to the chip
    (batcher items, generation requests) and back. The transport parses
    it once and opens a ``deadline_scope``; everything downstream —
    handler, ``ctx.tpu.predict``, ``generate`` — reads the ambient
    deadline without per-call plumbing, and the dispatcher DROPS
    already-expired items before burning device time on a caller that
    is gone.
  - ``AdmissionGate``: a bounded gate in front of the batcher queue and
    the generation slot queue. Under overload every queued request gets
    slower; the gate instead fails the excess FAST
    (``TooManyRequests`` -> 429 / ``RESOURCE_EXHAUSTED``) with a
    ``Retry-After`` estimate, keeping admitted-request latency flat and
    goodput at capacity (proved by ``tools/chaos_bench.py``).
  - Brownout: between "healthy" and "shedding" there is a window where
    the gate caps ``max_new_tokens`` so each admitted stream costs
    fewer decode iterations — degrading answer length before
    availability.
  - SLO classes: every request carries a serving class —
    ``latency`` (interactive, the default) or ``throughput`` (batch/
    offline, tagged via the ``X-SLO-Class`` header / ``slo-class``
    gRPC metadata). The class rides the same ambient-threading-local
    channel as the deadline, and overload degrades CLASSES IN ORDER:
    the gate sheds and brownouts throughput-class at a fraction of the
    latency-class bounds, so batch traffic absorbs pressure before an
    interactive request feels it (docs/advanced-guide/
    serving-scheduler.md).

Thread model: the ambient deadline and SLO class are
``threading.local`` (handlers run one-per-thread on both transports,
like ``tracing.current_span``); the gate's EWMA state is guarded by
one small lock and is touched only at admission/dispatch, never per
token.
"""

from __future__ import annotations

import contextlib
import threading
import time

from .errors import DeadlineExceeded, TooManyRequests

__all__ = [
    "AdmissionGate",
    "Deadline",
    "DeadlineExceeded",
    "DecodePipelinePolicy",
    "SLO_CLASSES",
    "SLO_LATENCY",
    "SLO_THROUGHPUT",
    "TooManyRequests",
    "current_deadline",
    "current_slo_class",
    "deadline_scope",
    "parse_http_timeout",
    "parse_slo_class",
    "slo_scope",
]


class Deadline:
    """An absolute expiry on the monotonic clock.

    Built once at the transport edge and carried by reference; every
    layer asks the same object ``remaining()``/``expired()`` so clock
    reads stay consistent and the budget shrinks as work progresses
    (the grpc-timeout contract: the deadline covers the WHOLE request,
    not each hop)."""

    __slots__ = ("at",)

    def __init__(self, at: float):
        self.at = float(at)

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(time.monotonic() + float(seconds))

    def remaining(self) -> float:
        """Seconds left; <= 0 once expired."""
        return self.at - time.monotonic()

    def expired(self) -> bool:
        return time.monotonic() >= self.at

    def budget(self, timeout: float | None) -> float:
        """Tighten a layer's own timeout to what the deadline allows."""
        rem = self.remaining()
        return rem if timeout is None else min(timeout, rem)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Deadline(in {self.remaining() * 1e3:.1f}ms)"


_scope = threading.local()


def current_deadline() -> Deadline | None:
    """The ambient deadline opened by the transport for this handler
    thread (None outside any scope)."""
    return getattr(_scope, "deadline", None)


@contextlib.contextmanager
def deadline_scope(deadline: Deadline | None):
    """Make ``deadline`` ambient for the calling thread. Nested scopes
    keep the TIGHTER deadline (a handler-set sub-deadline may shrink
    the budget, never extend the caller's)."""
    prev = getattr(_scope, "deadline", None)
    if deadline is not None and prev is not None and prev.at < deadline.at:
        deadline = prev
    _scope.deadline = deadline if deadline is not None else prev
    try:
        yield deadline
    finally:
        _scope.deadline = prev


class DecodePipelinePolicy:
    """Depth policy for the generator's decode dispatch pipeline.

    ``depth`` is the configured ceiling (TPU_DECODE_PIPELINE): how many
    fused decode blocks may be in flight on the device stream at once.
    Depth 2 is the steady-state win — the host reaps block N while
    block N+1 computes, so the device never idles between blocks — but
    a deeper queue also means anything dispatched NEXT (a latency-class
    admission's prefill, a chunk-lattice slice) waits behind more queued
    compute. ``target()`` is consulted before every pipeline top-up and
    collapses to 1 exactly when that wait would cost an SLO:

      - a latency-class request is waiting for admission (its prefill
        must queue behind at most ONE in-flight block, keeping TTFT at
        the SLO_BENCH floor);
      - a chunk-lattice admission was deferred by the in-flight pass
        (the lattice needs a fully reaped loop — its interleaved decode
        blocks re-decode from host token state);
      - speculative decoding is active (verify windows are built from
        host-delivered history, which only exists after a reap).

    Pure and lock-free: callers pass the facts, the policy returns a
    depth — the generator owns WHEN to ask, this owns the answer (and
    stats()/tests read the same answer, so the decision is observable
    and deterministic)."""

    __slots__ = ("depth",)

    def __init__(self, depth: int = 2):
        self.depth = max(1, int(depth))

    def target(self, *, latency_waiting: bool = False,
               lattice_deferred: bool = False,
               spec_decode: bool = False) -> int:
        if latency_waiting or lattice_deferred or spec_decode:
            return 1
        return self.depth


# -- SLO classes ------------------------------------------------------------
# Two classes, not N priorities: the scheduler's contract is a latency
# SLO for interactive traffic and a drain guarantee for batch traffic.
# More levels would just be a priority queue with extra starvation
# surface; everything downstream (batcher pickup, gate degradation,
# metric labels) keys on these two strings.
SLO_LATENCY = "latency"
SLO_THROUGHPUT = "throughput"
SLO_CLASSES = (SLO_LATENCY, SLO_THROUGHPUT)

_THROUGHPUT_ALIASES = frozenset({"throughput", "batch", "bulk", "offline",
                                 "best-effort", "besteffort"})


def parse_slo_class(val: str | None) -> str:
    """``X-SLO-Class`` header / ``slo-class`` gRPC metadata -> class.
    Unknown or absent values are LATENCY: untagged traffic keeps the
    full SLO (opting INTO deprioritization must be explicit — a typo in
    a batch job's header costs capacity, never an interactive user's
    latency)."""
    if not val:
        return SLO_LATENCY
    return (SLO_THROUGHPUT if val.strip().lower() in _THROUGHPUT_ALIASES
            else SLO_LATENCY)


def current_slo_class() -> str:
    """The ambient SLO class opened by the transport for this handler
    thread (latency outside any scope)."""
    return getattr(_scope, "slo_class", None) or SLO_LATENCY


@contextlib.contextmanager
def slo_scope(slo_class: str | None):
    """Make ``slo_class`` ambient for the calling thread. None keeps
    the enclosing scope's class (transports call this unconditionally);
    a nested explicit class WINS — a handler may re-class its own
    downstream work, e.g. fan-out prefetches as throughput."""
    prev = getattr(_scope, "slo_class", None)
    _scope.slo_class = slo_class if slo_class is not None \
        else (prev or SLO_LATENCY)
    try:
        yield _scope.slo_class
    finally:
        _scope.slo_class = prev


_HTTP_TIMEOUT_UNITS = (("ms", 1e-3), ("us", 1e-6), ("s", 1.0), ("m", 60.0))


def parse_http_timeout(val: str | None) -> float | None:
    """``X-Request-Timeout`` header -> seconds. Accepts a bare float
    (seconds) or a unit suffix: ``50ms``, ``2s``, ``250us``, ``1m``.
    Malformed/non-positive values are ignored (None) — a bad client
    header must never fail the request itself."""
    if not val:
        return None
    val = val.strip().lower()
    scale = 1.0
    for suffix, s in _HTTP_TIMEOUT_UNITS:
        if val.endswith(suffix):
            val, scale = val[: -len(suffix)], s
            break
    try:
        seconds = float(val) * scale
    except ValueError:
        return None
    return seconds if seconds > 0 else None


class AdmissionGate:
    """Bounded admission with early shedding and a brownout band.

    One gate fronts one queue (a program's coalescing batcher, or the
    generation engine's pending queue). ``admit(depth)`` raises
    ``TooManyRequests`` when either bound is crossed:

      - ``max_queue_depth``: more than this many waiters queued;
      - ``max_queue_delay``: the EWMA of observed queue wait exceeds
        this — the "every request is already slow" signal that depth
        alone misses when service time varies.

    The wait EWMA is fed by the dispatcher (``note_wait``) with each
    batch's oldest-item wait / each admission's queue wait, so the gate
    tracks the latency a NEW arrival would actually experience. The
    shed's ``Retry-After`` is that same estimate — honest backpressure
    a client-side retry policy (service/retry.py) can obey.

    Brownout: with ``brownout_delay`` configured, ``cap_tokens`` caps
    ``max_new_tokens`` while the wait EWMA sits above the threshold —
    shorter answers per admitted stream instead of shed streams.

    SLO-class degradation order: throughput-class requests see every
    bound scaled by ``throughput_factor`` (default 0.5) — half the
    queue depth, half the delay budget, brownout at half the wait
    threshold. Under rising load the gate therefore sheds and
    brownouts BATCH traffic first, and latency-class requests keep the
    full bounds until throughput is fully squeezed out. Factor 1.0
    restores class-blind gating.

    Both bounds disabled (0) -> the gate admits everything and costs
    one attribute read per request.
    """

    # EWMA smoothing for the observed-wait estimate: heavy enough to
    # ride out one odd batch, light enough to track a load swing within
    # a few dispatches.
    ALPHA = 0.3

    def __init__(self, max_queue_depth: int = 0, max_queue_delay: float = 0.0,
                 brownout_delay: float = 0.0, brownout_max_new: int = 32,
                 throughput_factor: float = 0.5,
                 name: str = "", metrics=None, tracer=None, logger=None):
        self.max_queue_depth = int(max_queue_depth)
        self.max_queue_delay = float(max_queue_delay)
        self.brownout_delay = float(brownout_delay)
        self.brownout_max_new = int(brownout_max_new)
        # clamp to (0, 1]: 0 would shed ALL throughput traffic even at
        # idle, and > 1 would invert the degradation order
        self.throughput_factor = min(1.0, max(0.01, float(throughput_factor)))
        self.name = name
        self.metrics = metrics
        self.tracer = tracer
        self.logger = logger
        self.enabled = self.max_queue_depth > 0 or self.max_queue_delay > 0
        self._lock = threading.Lock()
        self._wait_ewma = 0.0
        # per-class brownout band state (edge-logged, gauge-backed):
        # throughput's band engages earlier under class degradation
        self._brownout_on = {c: False for c in SLO_CLASSES}
        self.sheds = 0
        self.sheds_by_class = {c: 0 for c in SLO_CLASSES}
        self.brownout_capped = 0

    def clone(self, name: str) -> "AdmissionGate":
        """A fresh gate with the same bounds and telemetry plumbing but
        its OWN state — one gate must front one queue, so a multi-program
        engine clones its configured gate per program (a shared wait
        EWMA would let a backlogged program shed a healthy one's
        traffic)."""
        return AdmissionGate(
            max_queue_depth=self.max_queue_depth,
            max_queue_delay=self.max_queue_delay,
            brownout_delay=self.brownout_delay,
            brownout_max_new=self.brownout_max_new,
            throughput_factor=self.throughput_factor,
            name=name, metrics=self.metrics, tracer=self.tracer,
            logger=self.logger)

    # -- dispatcher side ------------------------------------------------------
    def note_wait(self, wait_s: float) -> None:
        """Feed one observed queue wait (seconds) into the estimate."""
        with self._lock:
            self._wait_ewma += self.ALPHA * (wait_s - self._wait_ewma)

    @property
    def estimated_wait(self) -> float:
        return self._wait_ewma

    # -- admission side -------------------------------------------------------
    def admit(self, depth: int, program: str = "",
              slo_class: str = SLO_LATENCY, tenant: str = "") -> None:
        """Admit or raise ``TooManyRequests``. ``depth`` is the queue's
        CURRENT depth (the caller reads it lock-free; an off-by-a-few
        race only moves the shed boundary by that much).
        Throughput-class requests are judged against bounds scaled by
        ``throughput_factor`` — they shed FIRST as load rises.
        ``tenant`` only labels the shed telemetry (pass it when a
        tenancy plane is installed); global pressure bounds stay
        tenant-blind."""
        if not self.enabled:
            return
        f = (self.throughput_factor if slo_class == SLO_THROUGHPUT else 1.0)
        wait = self._wait_ewma
        over_depth = (self.max_queue_depth > 0
                      and depth >= max(1, int(self.max_queue_depth * f)))
        over_delay = (self.max_queue_delay > 0 and depth > 0
                      and wait > self.max_queue_delay * f)
        if not (over_depth or over_delay):
            return
        self._shed(depth, wait, program, slo_class, tenant=tenant)

    def admit_tenant(self, spec, quotas, program: str = "",
                     slo_class: str = SLO_LATENCY) -> None:
        """Per-tenant quota admission (rps token bucket + concurrency),
        routed through the gate's one shed-bookkeeping path. Over-quota
        raises ``TooManyRequests`` with ``reason=tenant_quota`` — a 429
        scoped to THIS tenant while everyone else keeps flowing, which
        is the opposite failure shape from a global queue shed. On
        success the quota is CONSUMED; the caller must release the
        concurrency slot at the request's terminal
        (``quotas.release(tenant_id)``)."""
        why, retry_after = quotas.check(spec)
        if why is None:
            return
        tid = spec.tenant_id
        self._record_shed(program, slo_class,
                          {"reason": "tenant_quota", "quota": why},
                          tenant=tid)
        raise TooManyRequests(
            f"{self.name or 'admission'}: tenant {tid!r} over {why} "
            f"quota — shed ({slo_class})",
            retry_after=max(0.05, retry_after), reason="tenant_quota")

    def _record_shed(self, program: str, slo_class: str,
                     attributes: dict, trace_id: str = "",
                     tenant: str = "") -> None:
        """The one shed-bookkeeping path (queue pressure AND memory
        pressure): counters, the ``app_tpu_shed_total`` increment
        exemplar'd by the request's trace, and the zero-length
        ``tpu.shed`` marker span — so the two pressure kinds can never
        drift apart in what they record. ``trace_id`` overrides the
        ambient-span lookup for callers off the handler thread (the
        generation loop)."""
        self.sheds += 1
        if slo_class in self.sheds_by_class:
            self.sheds_by_class[slo_class] += 1
        now = time.monotonic()
        if not trace_id and (self.metrics is not None
                             or self.tracer is not None):
            from . import tracing

            span = tracing.current_span()  # the shed caller's request
            trace_id = span.trace_id if span is not None else ""
        if self.metrics is not None:
            try:
                # the tenant label exists only on tenancy-enabled
                # deployments — without a plane the series names stay
                # bit-identical to pre-tenancy builds
                labels = {"program": program or self.name,
                          "slo_class": slo_class}
                if tenant:
                    labels["tenant"] = tenant
                self.metrics.increment_counter(
                    "app_tpu_shed_total", exemplar=trace_id or None,
                    **labels)
            except Exception:
                pass
        if self.tracer is not None:
            try:
                # zero-length marker span: the request's trace shows
                # WHERE it died and WHY (queue state or memory reason)
                attrs = {**attributes,
                         "program": program or self.name,
                         "slo_class": slo_class}
                if tenant:
                    attrs.setdefault("tenant", tenant)
                self.tracer.record_span(
                    "tpu.shed", now, now, trace_id=trace_id or None,
                    attributes=attrs)
            except Exception:
                pass

    def _shed(self, depth: int, wait: float, program: str,
              slo_class: str = SLO_LATENCY, tenant: str = "") -> None:
        # honest Retry-After: the current wait estimate, floored so a
        # zero-estimate early shed doesn't invite an instant retry storm
        self._record_shed(program, slo_class,
                          {"queue_depth": depth,
                           "wait_ewma_ms": round(wait * 1e3, 3)},
                          tenant=tenant)
        raise TooManyRequests(
            f"{self.name or 'admission'}: queue depth {depth}, "
            f"estimated wait {wait * 1e3:.0f}ms — shed ({slo_class})",
            retry_after=max(0.05, wait))

    def shed_memory(self, program: str = "",
                    slo_class: str = SLO_LATENCY,
                    retry_after: float = 1.0,
                    trace_id: str = "") -> TooManyRequests:
        """Route an HBM-arbiter allocation failure through the gate's
        shed surface: same counters (``sheds``/``sheds_by_class``/
        ``app_tpu_shed_total``), same ``tpu.shed`` marker span, same
        429 + ``Retry-After`` contract as a queue shed — with
        ``reason: hbm`` attached so dashboards can split memory
        pressure from queue pressure. RETURNS the error (the caller
        decides whether to raise it or deliver it into a stream that
        already exists); the arbiter's own ``app_tpu_hbm_shed_total``
        is counted by ``hbm.note_shed`` at the raise site, not here.
        ``trace_id``: the request's trace when the caller is off the
        handler thread (the generation loop), else the ambient span
        is used."""
        self._record_shed(program, slo_class, {"reason": "hbm"},
                          trace_id=trace_id)
        return TooManyRequests(
            f"{self.name or 'admission'}: device memory exhausted — "
            f"shed ({slo_class})", retry_after=max(0.05, retry_after),
            reason="hbm")

    def cap_tokens(self, max_new_tokens: int,
                   slo_class: str = SLO_LATENCY) -> int:
        """Brownout: cap a generation request's token budget while the
        queue-wait estimate sits above ``brownout_delay``. Throughput-
        class requests brown out at ``brownout_delay *
        throughput_factor`` — answer length degrades for batch traffic
        a full band before interactive traffic is touched."""
        if self.brownout_delay <= 0:
            return max_new_tokens
        wait = self._wait_ewma
        active = self._refresh_brownout(wait)[slo_class]
        if not active or max_new_tokens <= self.brownout_max_new:
            return max_new_tokens
        self.brownout_capped += 1
        if self.metrics is not None:
            try:
                self.metrics.increment_counter("app_tpu_brownout_capped_total",
                                               slo_class=slo_class)
            except Exception:
                pass
        return self.brownout_max_new

    def _band_threshold(self, slo_class: str) -> float:
        return self.brownout_delay * (
            self.throughput_factor if slo_class == SLO_THROUGHPUT else 1.0)

    def _refresh_brownout(self, wait: float) -> dict:
        """Recompute EVERY class's band state from the current wait
        estimate (band state is PER CLASS — throughput engages a full
        factor earlier, and keying one flag on mixed traffic would flap
        the gauge/log). Refreshing all classes on any observation is
        what lets a class whose traffic vanished — e.g. throughput
        fully shed by admit() and never reaching here — still CLEAR
        once the estimate recovers. Emits the per-class gauge AND the
        pre-existing unlabeled any-class series on each edge."""
        states = {c: wait > self._band_threshold(c) for c in SLO_CLASSES}
        if states != self._brownout_on:
            with self._lock:
                changed = {c: a for c, a in states.items()
                           if a != self._brownout_on.get(c, False)}
                if changed:
                    self._brownout_on = states
                    for cls, active in changed.items():
                        if self.metrics is not None:
                            try:
                                self.metrics.set_gauge(
                                    "app_tpu_brownout_active",
                                    1.0 if active else 0.0, slo_class=cls)
                            except Exception:
                                pass
                        if self.logger is not None:
                            self.logger.warn({
                                "event": "brownout " + ("entered" if active
                                                        else "cleared"),
                                "gate": self.name,
                                "slo_class": cls,
                                "wait_ewma_ms": round(wait * 1e3, 1)})
                    if self.metrics is not None:
                        try:  # the unlabeled series dashboards pinned
                            # before the per-class split keeps flowing
                            self.metrics.set_gauge(
                                "app_tpu_brownout_active",
                                1.0 if any(states.values()) else 0.0)
                        except Exception:
                            pass
        return states

    def stats(self) -> dict:
        # brownout_active derives LIVE from the estimate (not the
        # event-driven flags): it must read False after recovery even
        # if no request has touched cap_tokens since
        wait = self._wait_ewma
        active = (self.brownout_delay > 0
                  and any(wait > self._band_threshold(c)
                          for c in SLO_CLASSES))
        return {
            "enabled": self.enabled,
            "max_queue_depth": self.max_queue_depth,
            "max_queue_delay": self.max_queue_delay,
            "throughput_factor": self.throughput_factor,
            "wait_ewma_ms": round(wait * 1e3, 3),
            "sheds": self.sheds,
            "sheds_by_class": dict(self.sheds_by_class),
            "brownout_active": active,
            "brownout_capped": self.brownout_capped,
        }


def gate_from_config(cfg, name: str, metrics=None, tracer=None,
                     logger=None) -> AdmissionGate | None:
    """Build a gate from ``TPU_MAX_QUEUE_DEPTH`` / ``TPU_MAX_QUEUE_DELAY``
    / ``TPU_BROWNOUT_DELAY`` / ``TPU_BROWNOUT_MAX_NEW`` /
    ``TPU_SLO_THROUGHPUT_FACTOR`` (all bounds default off: enabling
    load shedding is a capacity-planning decision, not a framework
    default). Returns None when fully disabled."""
    depth = cfg.get_int("TPU_MAX_QUEUE_DEPTH", 0)
    delay = cfg.get_float("TPU_MAX_QUEUE_DELAY", 0.0)
    b_delay = cfg.get_float("TPU_BROWNOUT_DELAY", 0.0)
    if depth <= 0 and delay <= 0 and b_delay <= 0:
        return None
    return AdmissionGate(
        max_queue_depth=depth, max_queue_delay=delay,
        brownout_delay=b_delay,
        brownout_max_new=cfg.get_int("TPU_BROWNOUT_MAX_NEW", 32),
        throughput_factor=cfg.get_float("TPU_SLO_THROUGHPUT_FACTOR", 0.5),
        name=name, metrics=metrics, tracer=tracer, logger=logger)
