"""chaoswatch: the chaos-seam coverage harness — the runtime complement
of the gofrlint dist pass (GL301-GL304), the way lockwatch backs the
locks pass and hbmwatch backs the resources pass.

The static passes prove the code at each seam HANDLES failure; this
plugin proves the failure is still being REHEARSED. ``chaos.SEAMS``
declares every point where the fault harness can inject — and a seam
nobody drives in tests is a resilience claim that silently stopped
being checked (the declared seam outlives the test that exercised it,
or a new seam ships with no test at all).

Mechanism: wraps ``ChaosSchedule.fire`` for the session — the one
choke point every injection passes through, whether production code
called module-level ``chaos.fire(SEAM)`` with a schedule installed or
a test drove ``schedule.fire`` directly. Per seam it counts:

  fires       calls that reached the seam under an active schedule
  armed       fires where the schedule had a rule FOR that seam (the
              seam was actually a candidate for injection, not just
              traversed)
  injections  fires that raised an injected error

``pytest --chaoswatch`` (tests/conftest.py, or standalone
``-p gofr_tpu.testutil.chaoswatch``) prints the per-seam table at
session finish and FAILS the session if any seam declared in
``chaos.SEAMS`` recorded zero fires — coverage is judged against the
DECLARED set, so adding a seam to chaos.py without a test driving it
breaks the gate by construction.
"""

from __future__ import annotations

import threading

from ..chaos import SEAMS, ChaosSchedule

__all__ = ["SeamCoverageError", "SeamWatch"]


class SeamCoverageError(AssertionError):
    """Raised by the session gate: a declared seam never fired."""


class SeamWatch:
    """Counts ChaosSchedule.fire traffic per seam for a session.

    install() monkeypatches the unbound ``ChaosSchedule.fire`` (so
    every schedule instance — installed or driven directly — is
    observed); uninstall() restores it. Reentrant-safe: a second
    install() is a no-op."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.fires: dict[str, int] = {}
        self.armed: dict[str, int] = {}
        self.injections: dict[str, int] = {}
        self._orig = None

    def install(self) -> None:
        if self._orig is not None:
            return
        orig = ChaosSchedule.fire
        watch = self

        def fire(sched: ChaosSchedule, seam: str) -> None:
            with watch._lock:
                watch.fires[seam] = watch.fires.get(seam, 0) + 1
                if seam in sched._rules:
                    watch.armed[seam] = watch.armed.get(seam, 0) + 1
            try:
                orig(sched, seam)
            except BaseException:
                with watch._lock:
                    watch.injections[seam] = \
                        watch.injections.get(seam, 0) + 1
                raise

        self._orig = orig
        ChaosSchedule.fire = fire

    def uninstall(self) -> None:
        if self._orig is not None:
            ChaosSchedule.fire = self._orig
            self._orig = None

    def uncovered(self) -> list[str]:
        """Declared seams with zero fires this session."""
        with self._lock:
            return [s for s in SEAMS if not self.fires.get(s)]

    def table(self) -> list[tuple[str, int, int, int]]:
        """(seam, fires, armed, injections) over the union of declared
        and observed seams — a fired seam that is NOT declared still
        prints (it is a seam chaos.SEAMS forgot)."""
        with self._lock:
            seams = sorted(set(SEAMS) | set(self.fires))
            return [(s, self.fires.get(s, 0), self.armed.get(s, 0),
                     self.injections.get(s, 0)) for s in seams]


# -- pytest session mode ------------------------------------------------------
# Registered by tests/conftest.py under --chaoswatch, or standalone via
# `pytest -p gofr_tpu.testutil.chaoswatch --chaoswatch` (what the
# seeded-gap self-test uses, where no repo conftest is in scope).

try:
    import pytest
except ImportError:  # pragma: no cover — production import path
    pytest = None


if pytest is not None:
    class SessionWatchPlugin:
        def __init__(self) -> None:
            self.watch = SeamWatch()

        def pytest_sessionstart(self, session):
            self.watch.install()

        def pytest_sessionfinish(self, session, exitstatus):
            self.watch.uninstall()
            rows = self.watch.table()
            width = max(len(s) for s, *_ in rows)
            print(f"\nchaoswatch: seam coverage over "  # noqa: T201
                  f"{len(SEAMS)} declared seam(s)")
            print(f"  {'seam':<{width}}  {'fires':>7}  "  # noqa: T201
                  f"{'armed':>7}  {'injected':>8}")
            for seam, fires, armed, injected in rows:
                mark = "" if fires else "  <- NEVER FIRED"
                extra = "" if seam in SEAMS else "  <- NOT DECLARED"
                print(f"  {seam:<{width}}  {fires:>7}  "  # noqa: T201
                      f"{armed:>7}  {injected:>8}{mark}{extra}")
            missing = self.watch.uncovered()
            if missing:
                raise SeamCoverageError(
                    "chaoswatch: declared seam(s) with ZERO coverage "
                    "this session — a resilience claim is no longer "
                    "rehearsed: " + ", ".join(missing))

    def pytest_addoption(parser):  # standalone -p loading
        try:
            parser.addoption(
                "--chaoswatch", action="store_true", default=False,
                help="count ChaosSchedule.fire traffic per declared "
                     "seam; print the fire/injection table and FAIL "
                     "the session if any chaos.SEAMS entry never "
                     "fired — the fault-injection sibling of "
                     "--lockwatch/--hbmwatch")
        except ValueError:
            pass  # tests/conftest.py already registered it

    def pytest_configure(config):
        install_session_watch(config)

    def install_session_watch(config) -> None:
        """Idempotent: register the session plugin when --chaoswatch
        is on (called from the standalone plugin hook AND from
        tests/conftest.py)."""
        try:
            enabled = config.getoption("--chaoswatch")
        except ValueError:
            enabled = False
        if enabled and not config.pluginmanager.has_plugin(
                "chaoswatch-session"):
            plugin = SessionWatchPlugin()
            config._chaoswatch = plugin
            config.pluginmanager.register(plugin, "chaoswatch-session")
