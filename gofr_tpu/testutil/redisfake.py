"""In-memory RESP2 server: the miniredis-equivalent hermetic test seam.

Reference test strategy: datasource/redis/redis_test.go:48-52 boots a
miniredis speaking the real protocol in-process, so the client under test is
exercised over an actual socket. Same here — FakeRedisServer implements the
command subset the framework uses (strings, hashes, lists, expiry, INFO)
over real TCP.
"""

from __future__ import annotations

import fnmatch
import socket
import threading
import time
from typing import Any


class _Store:
    def __init__(self) -> None:
        self.data: dict[str, Any] = {}
        self.expires: dict[str, float] = {}
        self.lock = threading.RLock()
        self.stats = {"total_connections_received": 0, "total_commands_processed": 0}

    def _sweep(self, key: str) -> None:
        exp = self.expires.get(key)
        if exp is not None and time.monotonic() >= exp:
            self.data.pop(key, None)
            self.expires.pop(key, None)

    def get(self, key: str) -> Any:
        self._sweep(key)
        return self.data.get(key)

    def set(self, key: str, value: Any) -> None:
        self.data[key] = value
        self.expires.pop(key, None)


def _b(v) -> bytes:
    return v if isinstance(v, bytes) else str(v).encode()


class FakeRedisServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.store = _Store()
        self._srv = socket.create_server((host, port))
        self.host, self.port = self._srv.getsockname()[:2]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop, daemon=True,
                                        name="fake-redis")
        self._thread.start()

    # -- wire loop ----------------------------------------------------------
    def _accept_loop(self) -> None:
        self._srv.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            self.store.stats["total_connections_received"] += 1
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        buf = b""
        try:
            while not self._stop.is_set():
                cmd, buf = self._try_parse(buf)
                if cmd is None:
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                    continue
                reply = self._dispatch(cmd)
                conn.sendall(reply)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    @staticmethod
    def _try_parse(buf: bytes):
        """Parse one array-of-bulk-strings request; (None, buf) if incomplete."""
        if not buf.startswith(b"*") or b"\r\n" not in buf:
            return None, buf
        head, rest = buf.split(b"\r\n", 1)
        n = int(head[1:])
        args = []
        for _ in range(n):
            if not rest.startswith(b"$") or b"\r\n" not in rest:
                return None, buf
            lhead, rest = rest.split(b"\r\n", 1)
            ln = int(lhead[1:])
            if len(rest) < ln + 2:
                return None, buf
            args.append(rest[:ln])
            rest = rest[ln + 2:]
        return args, rest

    # -- replies ------------------------------------------------------------
    @staticmethod
    def _simple(s: str) -> bytes:
        return f"+{s}\r\n".encode()

    @staticmethod
    def _error(s: str) -> bytes:
        return f"-ERR {s}\r\n".encode()

    @staticmethod
    def _int(n: int) -> bytes:
        return f":{n}\r\n".encode()

    @staticmethod
    def _bulk(v) -> bytes:
        if v is None:
            return b"$-1\r\n"
        b = _b(v)
        return b"$" + str(len(b)).encode() + b"\r\n" + b + b"\r\n"

    @classmethod
    def _array(cls, items) -> bytes:
        return b"*" + str(len(items)).encode() + b"\r\n" + b"".join(
            cls._bulk(i) for i in items)

    # -- command dispatch ---------------------------------------------------
    def _dispatch(self, args: list[bytes]) -> bytes:
        s = self.store
        s.stats["total_commands_processed"] += 1
        cmd = args[0].decode().upper()
        # surrogateescape: VALUES may be arbitrary binary (KV cache
        # frames) — the text view must never throw; commands that care
        # about bytes read from ``raw`` anyway
        a = [x.decode("utf-8", "surrogateescape") for x in args[1:]]
        with s.lock:
            try:
                return self._run(cmd, a, args[1:])
            except RedisFakeError as e:
                return self._error(str(e))
            except Exception as e:
                return self._error(f"internal {e!r}")

    def _run(self, cmd: str, a: list[str], raw: list[bytes]) -> bytes:
        s = self.store
        if cmd == "PING":
            return self._simple("PONG")
        if cmd == "SET":
            s.set(a[0], raw[1])
            i = 2
            while i < len(a):
                if a[i].upper() == "PX":
                    s.expires[a[0]] = time.monotonic() + int(a[i + 1]) / 1000
                    i += 2
                elif a[i].upper() == "EX":
                    s.expires[a[0]] = time.monotonic() + int(a[i + 1])
                    i += 2
                else:
                    i += 1
            return self._simple("OK")
        if cmd == "GET":
            v = s.get(a[0])
            if v is not None and not isinstance(v, bytes):
                raise RedisFakeError("WRONGTYPE")
            return self._bulk(v)
        if cmd == "MGET":
            vals = []
            for k in a:
                v = s.get(k)
                vals.append(v if isinstance(v, bytes) else None)
            return self._array(vals)
        if cmd == "DEL":
            n = sum(1 for k in a if s.data.pop(k, None) is not None)
            return self._int(n)
        if cmd == "EXISTS":
            return self._int(sum(1 for k in a if s.get(k) is not None))
        if cmd in ("INCRBY", "DECRBY", "INCR", "DECR"):
            delta = int(a[1]) if len(a) > 1 else 1
            if cmd in ("DECRBY", "DECR"):
                delta = -delta
            cur = s.get(a[0])
            val = int(cur or 0) + delta
            s.set(a[0], _b(val))
            return self._int(val)
        if cmd == "PEXPIRE":
            if s.get(a[0]) is None:
                return self._int(0)
            s.expires[a[0]] = time.monotonic() + int(a[1]) / 1000
            return self._int(1)
        if cmd == "TTL":
            if s.get(a[0]) is None:
                return self._int(-2)
            exp = s.expires.get(a[0])
            return self._int(-1 if exp is None else max(0, int(exp - time.monotonic())))
        if cmd == "KEYS":
            return self._array([k for k in list(s.data)
                                if s.get(k) is not None and fnmatch.fnmatch(k, a[0])])
        if cmd == "HSET":
            h = s.get(a[0])
            if h is None:
                h = {}
                s.set(a[0], h)
            if not isinstance(h, dict):
                raise RedisFakeError("WRONGTYPE")
            added = 0
            for f, v in zip(a[1::2], raw[2::2]):
                added += 0 if f in h else 1
                h[f] = v
            return self._int(added)
        if cmd == "HGET":
            h = s.get(a[0]) or {}
            return self._bulk(h.get(a[1]) if isinstance(h, dict) else None)
        if cmd == "HGETALL":
            h = s.get(a[0]) or {}
            flat: list = []
            for k, v in h.items():
                flat += [k, v]
            return self._array(flat)
        if cmd == "HDEL":
            h = s.get(a[0]) or {}
            n = sum(1 for f in a[1:] if h.pop(f, None) is not None)
            return self._int(n)
        if cmd in ("LPUSH", "RPUSH"):
            lst = s.get(a[0])
            if lst is None:
                lst = []
                s.set(a[0], lst)
            if not isinstance(lst, list):
                raise RedisFakeError("WRONGTYPE")
            for v in raw[1:]:
                lst.insert(0, v) if cmd == "LPUSH" else lst.append(v)
            return self._int(len(lst))
        if cmd == "LRANGE":
            lst = s.get(a[0]) or []
            start, stop = int(a[1]), int(a[2])
            stop = len(lst) if stop == -1 else stop + 1
            return self._array(lst[start:stop])
        if cmd == "FLUSHDB":
            s.data.clear()
            s.expires.clear()
            return self._simple("OK")
        if cmd == "INFO":
            lines = ["# Stats"] + [f"{k}:{v}" for k, v in s.stats.items()]
            return self._bulk("\r\n".join(lines))
        raise RedisFakeError(f"unknown command '{cmd}'")

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except Exception:
            pass
        self._thread.join(timeout=1.0)


class RedisFakeError(Exception):
    pass
