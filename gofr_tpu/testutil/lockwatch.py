"""Lock-order watchdog: the runtime complement to gofrlint GL002.

``go test -race`` observes real executions; this is the Python serving
stack's equivalent for lock-ORDER bugs. A :class:`LockWatch` instruments
lock acquisitions and maintains the global acquisition-order graph at
runtime: acquiring B while holding A records the edge ``A -> B``, where
nodes are lock *sites* (the ``file:line`` that created the lock — the
lock's declaration, like a lockdep lock class, so every instance built
by the same constructor shares one node). An edge that closes a cycle
is an observed order INVERSION: two threads that hit the two orders
concurrently would deadlock, even if this run got lucky. Inversions are
recorded, never raised mid-acquire (raising inside an acquire could
itself wedge the program under test).

Two ways to instrument:

  - explicit: ``watch.lock()`` / ``watch.rlock()`` build watched locks
    registered only with that watch — what lockwatch's own tests use,
    so a deliberately seeded inversion never leaks into a
    session-level watch running over the same process;
  - ambient: ``watch.install()`` monkeypatches ``threading.Lock`` /
    ``threading.RLock`` so every lock created AFTERWARDS is watched
    (module-import-time locks predate it and stay raw). This is what
    ``pytest --lockwatch`` uses (tests/conftest.py): the tier-1
    threaded suite runs with the framework's locks observed and the
    session fails on any inversion.

Semantics (mirrors kernel lockdep where it translates):

  - only acquisitions that can BLOCK record edges — a
    ``blocking=False`` try-acquire cannot participate in a deadlock;
  - edges are recorded at ATTEMPT time: holding A and blocking on B is
    the hazard whether or not the acquire eventually succeeds;
  - re-acquiring a lock this thread already holds (RLock reentrancy)
    records nothing;
  - two locks from the SAME site never form an edge: per-connection
    sibling locks have no defined order and would false-positive;
  - ``Condition(watched_lock)`` works: the wait()-time full release
    and reacquire flow through ``_release_save``/``_acquire_restore``.
"""

from __future__ import annotations

import _thread
import threading
from typing import Any

__all__ = ["LockOrderViolation", "LockWatch", "Violation"]

# captured at import time, BEFORE any install() can monkeypatch it
# (tests/conftest.py imports this module first, then installs)
_RAW_RLOCK = threading.RLock


class LockOrderViolation(AssertionError):
    """Raised by :meth:`LockWatch.check` when inversions were observed."""


class Violation:
    """One observed order inversion: the edge that closed a cycle."""

    __slots__ = ("cycle", "edge", "thread", "prior")

    def __init__(self, cycle: list[str], edge: tuple[str, str],
                 thread: str, prior: dict[tuple[str, str], str]):
        self.cycle = cycle          # [A, B, ..., A] of lock sites
        self.edge = edge            # the (A, B) that closed it
        self.thread = thread        # thread that attempted the edge
        self.prior = prior          # existing edges of the cycle -> thread

    def __str__(self) -> str:
        lines = [f"lock-order inversion: {' -> '.join(self.cycle)}",
                 f"  new edge {self.edge[0]} -> {self.edge[1]} "
                 f"in thread {self.thread!r}"]
        for (a, b), thr in sorted(self.prior.items()):
            lines.append(f"  prior edge {a} -> {b} in thread {thr!r}")
        return "\n".join(lines)


def _thread_name() -> str:
    """current_thread().name WITHOUT threading.current_thread(): during
    Thread._bootstrap the thread is not yet in threading._active, so
    current_thread() constructs a _DummyThread — whose own Event then
    acquires a watched lock, which asks for the thread name again:
    infinite recursion, and the dying child leaves start() waiting on
    _started forever."""
    ident = _thread.get_ident()
    t = threading._active.get(ident)
    return t.name if t is not None else f"thread-{ident}"


def _caller_site() -> str:
    """file:line of the nearest frame outside lockwatch/threading."""
    import sys

    f = sys._getframe(1)
    here = __file__
    while f is not None:
        fn = f.f_code.co_filename
        if fn != here and not fn.endswith(("threading.py", "queue.py")):
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


class _WatchedLock:
    """A threading.Lock wrapper reporting to one LockWatch.

    Deliberately does NOT define ``_release_save``/``_acquire_restore``/
    ``_is_owned``: threading.Condition probes those by attribute access
    and must take its plain-lock fallback path (which flows through our
    acquire/release and keeps the bookkeeping intact)."""

    _reentrant = False

    def __init__(self, watch: "LockWatch", inner: Any, site: str):
        self._watch = watch
        self._inner = inner
        self.site = site
        self._owner: int | None = None  # ident of the holding thread

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            self._watch._note_attempt(self)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._watch._note_acquired(self)
        return ok

    def release(self) -> None:
        self._watch._note_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:
        kind = "RLock" if self._reentrant else "Lock"
        return f"<watched {kind} from {self.site}>"

    def _at_fork_reinit(self) -> None:
        self._inner._at_fork_reinit()


class _WatchedRLock(_WatchedLock):
    """RLock wrapper: adds the threading.Condition wait() protocol."""

    _reentrant = True

    def _release_save(self):
        # note BEFORE the inner release (same order as release()): once
        # the inner lock is free, a racing acquirer owns it, and our
        # late bookkeeping would clobber its ownership and get its live
        # held entry pruned as stale. The watch-side recursion DEPTH
        # rides on the saved state: wait() on an RLock held at depth n
        # must restore to depth n, or the first release() afterwards
        # pops the entry while the thread still owns the lock
        depth = self._watch._note_release(self, full=True)
        return (self._inner._release_save(), depth)

    def _acquire_restore(self, state) -> None:
        inner_state, depth = state
        self._watch._note_attempt(self)
        self._inner._acquire_restore(inner_state)
        self._watch._note_acquired(self, depth=depth)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


class LockWatch:
    """Runtime lock-acquisition-order recorder with inversion detection."""

    def __init__(self, name: str = "lockwatch"):
        self.name = name
        # raw allocator: with install() active, threading.Lock is OUR
        # factory — the watch's own mutex must never be watched
        self._mu = _thread.allocate_lock()
        self._tls = threading.local()
        self.graph: dict[str, set[str]] = {}           # site -> successors
        self.edges: dict[tuple[str, str], str] = {}    # edge -> thread name
        self.violations: list[Violation] = []
        self.acquisitions = 0
        self._sites: set[str] = set()   # every site ever acquired
        self._orig: tuple[Any, Any] | None = None

    # -- lock factories ------------------------------------------------------
    def lock(self, site: str | None = None) -> _WatchedLock:
        return _WatchedLock(self, _thread.allocate_lock(),
                            site or _caller_site())

    def rlock(self, site: str | None = None) -> _WatchedRLock:
        # ALWAYS the module-import-time raw ctor: threading.RLock may
        # currently be an ambient factory (this watch's own under
        # install(), or a session watch's under --lockwatch), and a
        # watched inner lock would double-report every acquisition into
        # that other watch
        return _WatchedRLock(self, _RAW_RLOCK(), site or _caller_site())

    # -- ambient instrumentation --------------------------------------------
    def install(self) -> None:
        """Patch threading.Lock/RLock so every lock created from now on
        is watched. Idempotent per watch; uninstall() restores."""
        if self._orig is not None:
            return
        self._orig = (threading.Lock, threading.RLock)

        def make_lock(*a: Any, **k: Any) -> _WatchedLock:
            return self.lock()

        def make_rlock(*a: Any, **k: Any) -> _WatchedLock:
            return self.rlock()

        threading.Lock = make_lock
        threading.RLock = make_rlock

    def uninstall(self) -> None:
        if self._orig is None:
            return
        threading.Lock, threading.RLock = self._orig
        self._orig = None

    def __enter__(self) -> "LockWatch":
        self.install()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.uninstall()

    # -- bookkeeping ---------------------------------------------------------
    def _held(self) -> list[list[Any]]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held  # entries: [lock, depth]

    def _note_attempt(self, lk: _WatchedLock) -> None:
        """About to BLOCK on ``lk``: record edges held -> lk and detect
        cycles. Runs before the inner acquire — holding A and blocking
        on B is the hazard even if this particular acquire times out."""
        held = self._held()
        ident = _thread.get_ident()
        # prune entries whose lock another thread has since released (a
        # plain Lock used as a HANDOFF: A acquires, B releases — legal,
        # and without pruning A's stale entry would later read as a
        # phantom self-deadlock and contribute bogus order edges)
        if any(e[0]._owner != ident for e in held):
            held[:] = [e for e in held if e[0]._owner == ident]
        for e in held:
            if e[0] is lk:
                if lk._reentrant:
                    return  # RLock re-acquire: no ordering information
                # blocking on a non-reentrant lock this thread already
                # holds: guaranteed self-deadlock — record it before the
                # inner acquire hangs
                with self._mu:
                    self.violations.append(Violation(
                        [lk.site, lk.site], (lk.site, lk.site),
                        _thread_name(), {}))
                return
        new_edges = [(e[0].site, lk.site) for e in held
                     if e[0].site != lk.site]
        if not new_edges:
            return
        thread = _thread_name()
        with self._mu:
            for a, b in new_edges:
                if (a, b) in self.edges:
                    continue
                cycle = self._find_path(b, a)
                self.graph.setdefault(a, set()).add(b)
                self.edges[(a, b)] = thread
                if cycle is not None:
                    full = [a, b] + cycle[1:]
                    prior = {
                        (full[i], full[i + 1]):
                            self.edges.get((full[i], full[i + 1]), "?")
                        for i in range(1, len(full) - 1)}
                    self.violations.append(
                        Violation(full, (a, b), thread, prior))

    def _find_path(self, src: str, dst: str) -> list[str] | None:
        """DFS path src -> dst in the current graph (caller holds _mu)."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self.graph.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _note_acquired(self, lk: _WatchedLock, depth: int = 1) -> None:
        held = self._held()
        lk._owner = _thread.get_ident()
        for e in held:
            if e[0] is lk:
                e[1] += depth
                return
        held.append([lk, max(1, depth)])
        with self._mu:   # shared counter: += is not atomic across threads
            self.acquisitions += 1
            self._sites.add(lk.site)

    def _note_release(self, lk: _WatchedLock, full: bool = False) -> int:
        """Returns the recursion depth being released (the FULL depth
        when ``full=True`` — _release_save threads it through the saved
        state so _acquire_restore can put it back)."""
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lk:
                depth = held[i][1]
                held[i][1] = 0 if full else held[i][1] - 1
                if held[i][1] <= 0:
                    held.pop(i)
                    lk._owner = None
                return depth if full else 1
        # not held by THIS thread: a cross-thread handoff release — mark
        # the lock free so the owner's stale entry is pruned on its next
        # attempt
        lk._owner = None
        return 1

    # -- reporting -----------------------------------------------------------
    def summary(self) -> dict:
        with self._mu:
            return {
                "watch": self.name,
                "acquisitions": self.acquisitions,
                # acquired sites UNION edge endpoints (an attempt that
                # never succeeded still contributes an edge)
                "sites": len(self._sites | set(self.graph)
                             | {b for s in self.graph.values() for b in s}),
                "edges": len(self.edges),
                "violations": [str(v) for v in self.violations],
            }

    def check(self) -> None:
        """Raise LockOrderViolation if any inversion was observed."""
        if self.violations:
            report = "\n\n".join(str(v) for v in self.violations)
            raise LockOrderViolation(
                f"{self.name}: {len(self.violations)} lock-order "
                f"inversion(s) observed:\n{report}")
