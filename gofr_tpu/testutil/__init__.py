"""Test utilities (reference: pkg/gofr/testutil/ — NewMockConfig
mock_config.go:11, NewMockLogger mock_logger.go:32, Stdout/StderrOutputForFunc
os.go:8-36)."""

from __future__ import annotations

import io
from contextlib import redirect_stderr, redirect_stdout
from typing import Callable

from ..config import MapConfig
from ..glog import Logger, LogLevel


def new_mock_config(values: dict[str, str] | None = None) -> MapConfig:
    return MapConfig(values)


class MockLogger(Logger):
    """Logger capturing output for assertions."""

    def __init__(self, level: LogLevel = LogLevel.DEBUG):
        self.out_buf = io.StringIO()
        self.err_buf = io.StringIO()
        super().__init__(level=level, out=self.out_buf, err=self.err_buf, pretty=False)

    @property
    def stdout(self) -> str:
        return self.out_buf.getvalue()

    @property
    def stderr(self) -> str:
        return self.err_buf.getvalue()


def new_mock_logger(level: LogLevel = LogLevel.DEBUG) -> MockLogger:
    return MockLogger(level)


def stdout_output_for(fn: Callable[[], None]) -> str:
    buf = io.StringIO()
    with redirect_stdout(buf):
        fn()
    return buf.getvalue()


def stderr_output_for(fn: Callable[[], None]) -> str:
    buf = io.StringIO()
    with redirect_stderr(buf):
        fn()
    return buf.getvalue()
