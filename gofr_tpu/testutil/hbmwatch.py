"""hbmwatch: the device-buffer leak harness — memory sibling of
lockwatch (gofrlint GL203/GL202's runtime complement).

Where lockwatch observes real lock acquisitions, hbmwatch observes
real device buffers: ``jax.live_arrays()`` is ground truth for every
array the process holds, and the hbm accounting registry
(``gofr_tpu/tpu/hbm.py``) says which subsystem CLAIMS which bytes.
Snapshots reconcile the two — declared bytes per subsystem (engine /
kvcache-t0 / lora / spec-decode / batcher), total live bytes, and the
unattributed remainder (dispatch temporaries, jit constants, anything
a subsystem allocated without accounting).

Two ways to use it:

  - **steady-state assertion** (the leak shape that killed the flat
    prefix cache: every request adds device state, nothing evicts):
    ``HBMWatch.assert_flat(fn, warmup=N, iters=M)`` runs ``fn`` — one
    request, one decode tick, one store/restore cycle — N warmup times
    (absorbing jit compiles, pool fills, caches reaching capacity),
    snapshots, runs M more, and raises :class:`HBMLeak` if live bytes
    grew. Used by ``tests/test_memory_regressions.py``.

  - **session mode**: ``pytest --hbmwatch`` (tests/conftest.py, or
    standalone ``-p gofr_tpu.testutil.hbmwatch``) snapshots around
    every test, prints the per-test leak deltas and the attribution
    table in the session summary, and FAILS the session when a test
    retains more than ``HBMWATCH_TEST_TOL_MB`` (default 32) or the
    whole session grows past ``HBMWATCH_SESSION_TOL_MB`` (default 64)
    after teardown — a closed engine must actually release its bytes.

Snapshots ``gc.collect()`` first: donated/dropped buffers are freed at
object collection, and without the collect a snapshot would read
garbage-pending bytes as leaks.
"""

from __future__ import annotations

import gc
import os
from typing import Any, Callable

__all__ = ["HBMLeak", "HBMWatch", "attribution", "live_device_bytes"]

_MB = 1 << 20


def live_device_bytes() -> int:
    """Total bytes of live, non-deleted jax arrays — ground truth for
    what the process holds on device right now."""
    import jax

    total = 0
    for a in jax.live_arrays():
        try:
            if getattr(a, "is_deleted", None) is not None and a.is_deleted():
                continue  # donated-away: no backing buffer
            total += int(a.nbytes)
        except Exception:
            continue
    return total


def attribution() -> dict:
    """Reconcile declared subsystem bytes against live ground truth."""
    from ..tpu import hbm

    accounted = hbm.live_bytes()
    live = live_device_bytes()
    return {
        "live_bytes": live,
        "accounted": accounted,
        "unattributed": live - sum(accounted.values()),
    }


class HBMLeak(AssertionError):
    """Raised on steady-state growth (or by the session gate)."""


def _fmt_mb(n: int) -> str:
    return f"{n / _MB:+.2f} MiB" if n < 0 else f"{n / _MB:.2f} MiB"


class HBMWatch:
    """Snapshot-based live-buffer tracker."""

    def __init__(self, name: str = "hbmwatch"):
        self.name = name
        self.deltas: dict[str, int] = {}  # nodeid -> retained bytes

    def snapshot(self) -> int:
        gc.collect()
        return live_device_bytes()

    def assert_flat(self, fn: Callable[[], Any], *, warmup: int = 2,
                    iters: int = 3, tol_bytes: int = 0,
                    label: str = "") -> int:
        """Run ``fn`` ``warmup`` times, snapshot, run ``iters`` more,
        and raise :class:`HBMLeak` if live device bytes grew past
        ``tol_bytes``. Returns the observed growth (<= tol on
        success). Warmup absorbs one-time growth — jit compiles
        materializing constants, pools/caches filling to capacity —
        so the assertion is about STEADY STATE, exactly the regime a
        serving process lives in."""
        for _ in range(max(0, warmup)):
            fn()
        base = self.snapshot()
        for _ in range(max(1, iters)):
            fn()
        grown = self.snapshot() - base
        if grown > tol_bytes:
            att = attribution()
            raise HBMLeak(
                f"{self.name}: steady-state device-byte growth"
                f"{' in ' + label if label else ''}: {_fmt_mb(grown)} "
                f"over {iters} iteration(s) after {warmup} warmup(s) "
                f"(tol {_fmt_mb(tol_bytes)})\n"
                f"  live={_fmt_mb(att['live_bytes'])} "
                f"accounted={ {k: _fmt_mb(v) for k, v in att['accounted'].items()} } "
                f"unattributed={_fmt_mb(att['unattributed'])}")
        return grown

    def record(self, nodeid: str, delta: int) -> None:
        self.deltas[nodeid] = delta

    def summary(self) -> dict:
        top = sorted(self.deltas.items(), key=lambda kv: -kv[1])[:10]
        return {
            "watch": self.name,
            "tests": len(self.deltas),
            "top_deltas": top,
            **attribution(),
        }


# -- pytest session mode ------------------------------------------------------
# Registered by tests/conftest.py under --hbmwatch, or standalone via
# `pytest -p gofr_tpu.testutil.hbmwatch --hbmwatch` (what the
# seeded-leak self-test uses, where no repo conftest is in scope).

try:
    import pytest
except ImportError:  # pragma: no cover — production import path
    pytest = None


if pytest is not None:
    class SessionWatchPlugin:
        def __init__(self) -> None:
            self.watch = HBMWatch("pytest-session")
            self.test_tol = int(float(os.environ.get(
                "HBMWATCH_TEST_TOL_MB", "32")) * _MB)
            self.session_tol = int(float(os.environ.get(
                "HBMWATCH_SESSION_TOL_MB", "64")) * _MB)
            self.start: int | None = None

        @pytest.hookimpl(hookwrapper=True)
        def pytest_runtest_protocol(self, item, nextitem):
            before = self.watch.snapshot()
            if self.start is None:
                self.start = before
            yield
            self.watch.record(item.nodeid,
                              self.watch.snapshot() - before)

        def pytest_sessionfinish(self, session, exitstatus):
            end = self.watch.snapshot()
            start = self.start if self.start is not None else end
            s = self.watch.summary()
            print(f"\nhbmwatch: {s['tests']} test(s), live device bytes "  # noqa: T201
                  f"{_fmt_mb(start)} -> {_fmt_mb(end)} "
                  f"(session delta {_fmt_mb(end - start)})")
            acc = s["accounted"]
            print("hbmwatch attribution: " + (", ".join(  # noqa: T201
                f"{k}={_fmt_mb(v)}" for k, v in acc.items()) or "(empty)")
                + f"; unattributed={_fmt_mb(s['unattributed'])}")
            for nodeid, d in s["top_deltas"]:
                if d > 0:
                    print(f"hbmwatch delta: {_fmt_mb(d):>12}  {nodeid}")  # noqa: T201
            failures = []
            leakers = [(n, d) for n, d in self.watch.deltas.items()
                       if d > self.test_tol]
            if leakers:
                lines = "\n".join(f"  {_fmt_mb(d)}  {n}"
                                  for n, d in leakers)
                failures.append(
                    f"test(s) retained live device bytes past "
                    f"{_fmt_mb(self.test_tol)}:\n{lines}")
            if end - start > self.session_tol:
                failures.append(
                    f"session live device bytes grew {_fmt_mb(end - start)} "
                    f"(tol {_fmt_mb(self.session_tol)}) — something "
                    f"closed did not release its buffers")
            if failures:
                raise HBMLeak("hbmwatch: " + "\n\n".join(failures))

    def pytest_addoption(parser):  # standalone -p loading
        try:
            parser.addoption(
                "--hbmwatch", action="store_true", default=False,
                help="snapshot live device bytes around every test "
                     "(jax.live_arrays + the hbm accounting registry); "
                     "print per-test leak deltas and FAIL the session "
                     "on retained growth — the memory sibling of "
                     "--lockwatch")
        except ValueError:
            pass  # tests/conftest.py already registered it

    def pytest_configure(config):
        install_session_watch(config)

    def install_session_watch(config) -> None:
        """Idempotent: register the session plugin when --hbmwatch is
        on (called from the standalone plugin hook AND from
        tests/conftest.py)."""
        try:
            enabled = config.getoption("--hbmwatch")
        except ValueError:
            enabled = False
        if enabled and not config.pluginmanager.has_plugin(
                "hbmwatch-session"):
            plugin = SessionWatchPlugin()
            config._hbmwatch = plugin
            config.pluginmanager.register(plugin, "hbmwatch-session")
