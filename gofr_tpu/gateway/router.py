"""Prefix-affinity routing: consistent hash ring + pressure-aware pick.

**Why consistent hashing on the first KV block chain.** Every turn of
a multi-turn conversation shares its first ``block`` prompt tokens, so
the chain hash of that block (``tpu/kvcache/first_block_hash`` — the
SAME hashing the radix index and the T2 fingerprint keys use) is a
session-stable key: hash it onto a ring of replica virtual nodes and
the whole session lands where its T0/T1 cache is warm, while distinct
sessions spread uniformly. Consistent (rather than modular) hashing
means a replica joining or leaving remaps only the ring arcs it
owned — the rest of the fleet keeps its warm traffic.

**The pick, in preference order** (``AffinityRouter.pick``):

  1. the affinity OWNER (first live ring successor), unless it is
     unroutable (down / draining / open breaker) or it is inside an
     hbm-shed hold AND the request is cache-heavy (prompt >=
     ``long_prefix`` tokens) — a memory-pressured replica is drained
     of the traffic class that costs it KV first, never hammered;
  2. further ring successors under the same rules (these keep SOME
     affinity: the same spill target for the same key);
  3. least-pressure routable replica (pressure score, then in-flight
     count as the tie-break);
  4. a down-but-probeable replica (reconnect window expired — real
     traffic is the recovery probe);
  5. nothing -> :class:`GatewayUnavailable` (typed 503 with the
     table's honest Retry-After).

Prompts shorter than one affinity block skip the ring entirely
(label ``short``): their key would change every turn, so pressure
balance IS the right placement for them.

**Retry budget** (:class:`RetryBudget`): failover is what turns one
replica's death into zero client-visible failures — and what turns a
DYING FLEET's correlated failures into a retry storm if unbounded.
The budget is a token bucket deposited per first attempt and
withdrawn per failover, so retries are capped at ``ratio`` of live
traffic (plus ``burst`` for isolated incidents). Drain re-picks are
deliberately NOT charged: a rolling deploy is an orderly, bounded
event the gateway must absorb silently even while the budget is
drained by a real incident elsewhere.
"""

from __future__ import annotations

import bisect
import hashlib
import threading

from .. import chaos
from ..errors import ServiceUnavailable, format_retry_after
from .table import Replica, ReplicaTable

__all__ = ["AffinityRouter", "GatewayUnavailable", "HashRing",
           "RetryBudget", "PICK_HIT", "PICK_SHORT", "PICK_SPILL"]

PICK_HIT = "hit"
PICK_SPILL = "spill"
PICK_SHORT = "short"


class GatewayUnavailable(ServiceUnavailable):
    """No routable replica (all down/draining/held) or the failover
    retry budget is spent: a typed 503 + Retry-After — the same shed
    discipline every other pressure surface in the framework uses."""

    def __init__(self, message: str, retry_after: float = 1.0):
        super().__init__(message)
        self.retry_after = retry_after
        self.headers = {"Retry-After": format_retry_after(retry_after)}


class HashRing:
    """Consistent hash ring over replica indices. ``vnodes`` virtual
    points per replica smooth the arc distribution (the classic
    Karger construction); points are derived from the replica
    ADDRESS, so every gateway instance fronting the same replica set
    builds the identical ring — affinity agrees across gateways with
    no coordination."""

    def __init__(self, addresses: list[str], vnodes: int = 64):
        points: list[tuple[int, int]] = []
        for idx, addr in enumerate(addresses):
            for v in range(max(1, int(vnodes))):
                digest = hashlib.sha256(f"{addr}#{v}".encode()).digest()
                points.append((int.from_bytes(digest[:8], "big"), idx))
        points.sort()
        self._hashes = [h for h, _ in points]
        self._owners = [o for _, o in points]
        self._n = len(addresses)

    def order(self, key: bytes) -> list[int]:
        """Replica indices in ring-successor preference order for
        ``key`` — position 0 is the affinity owner; later positions
        are the deterministic spill sequence."""
        h = int.from_bytes(hashlib.sha256(key).digest()[:8], "big")
        start = bisect.bisect_right(self._hashes, h)
        seen: list[int] = []
        for i in range(len(self._owners)):
            owner = self._owners[(start + i) % len(self._owners)]
            if owner not in seen:
                seen.append(owner)
                if len(seen) == self._n:
                    break
        return seen


class RetryBudget:
    """Token-bucket failover budget: ``deposit()`` per first attempt
    adds ``ratio`` tokens (capped at ``burst``), ``withdraw()`` per
    failover spends one. Deterministic, clock-free, thread-safe —
    the storm brake the failover contract names."""

    def __init__(self, ratio: float = 0.1, burst: float = 10.0):
        self.ratio = max(0.0, float(ratio))
        self.burst = max(1.0, float(burst))
        self._tokens = self.burst
        self._lock = threading.Lock()
        self.spent = 0
        self.denied = 0

    def deposit(self) -> None:
        with self._lock:
            self._tokens = min(self.burst, self._tokens + self.ratio)

    def withdraw(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self.spent += 1
                return True
            self.denied += 1
            return False

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def stats(self) -> dict:
        return {"tokens": round(self.tokens, 3), "ratio": self.ratio,
                "burst": self.burst, "spent": self.spent,
                "denied": self.denied}


class AffinityRouter:
    def __init__(self, table: ReplicaTable, *, block: int = 16,
                 vnodes: int = 64, long_prefix: int | None = None,
                 metrics=None):
        self.table = table
        self.block = max(1, int(block))
        # "cache-heavy": the class whose KV footprint is worth draining
        # off a memory-pressured replica first — default 4 blocks
        self.long_prefix = (4 * self.block if long_prefix is None
                            else int(long_prefix))
        self.ring = HashRing([r.address for r in table.replicas],
                             vnodes=vnodes)
        self.metrics = metrics
        self.picks = {PICK_HIT: 0, PICK_SPILL: 0, PICK_SHORT: 0}
        self._lock = threading.Lock()

    def _count(self, label: str) -> None:
        with self._lock:
            self.picks[label] += 1
        if self.metrics is not None:
            try:
                self.metrics.increment_counter(
                    "app_tpu_gateway_affinity_total", result=label)
            except Exception:
                pass

    def _usable(self, r: Replica, cache_heavy: bool) -> bool:
        if not r.routable():
            return False
        return not (cache_heavy and r.hbm_hold())

    @staticmethod
    def _least_pressure(cands: list[Replica]) -> Replica | None:
        best = None
        for r in cands:
            if best is None or (r.pressure(), r.inflight) \
                    < (best.pressure(), best.inflight):
                best = r
        return best

    def pick(self, key: bytes | None, prompt_len: int,
             exclude: frozenset | set = frozenset()) -> tuple[Replica, str]:
        """One routing decision. ``key`` is the first-block chain hash
        (None for sub-block prompts); ``exclude`` holds replica
        indices already tried by this request's failover loop.
        Raises :class:`GatewayUnavailable` when nothing is routable.
        Errors injected at the ``GATEWAY_PICK`` seam surface as that
        same typed 503 — a chaos schedule can starve pick N without
        ever crashing the gateway (the handler maps them)."""
        chaos.fire(chaos.GATEWAY_PICK)
        reps = self.table.replicas
        cache_heavy = prompt_len >= self.long_prefix
        if key is not None:
            order = self.ring.order(key)
            for pos, idx in enumerate(order):
                if idx in exclude:
                    continue
                r = reps[idx]
                if self._usable(r, cache_heavy):
                    label = PICK_HIT if pos == 0 else PICK_SPILL
                    self._count(label)
                    return r, label
        # pressure-balanced fallback (short prompts land here directly)
        cands = [r for r in reps
                 if r.idx not in exclude and self._usable(r, cache_heavy)]
        best = self._least_pressure(cands)
        if best is not None:
            label = PICK_SHORT if key is None else PICK_SPILL
            self._count(label)
            return best, label
        # last resort: a held replica for a cache-heavy request beats a
        # 503 IF it is otherwise routable (the hold is advice, the
        # request is real) — prefer the least-pressured one
        cands = [r for r in reps if r.idx not in exclude and r.routable()]
        best = self._least_pressure(cands)
        if best is not None:
            label = PICK_SHORT if key is None else PICK_SPILL
            self._count(label)
            return best, label
        # nothing routable: allow one lazy re-probe of a down replica
        # whose reconnect window expired (traffic as recovery probe)
        for r in reps:
            if r.idx not in exclude and r.probeable():
                self._count(PICK_SPILL)
                return r, PICK_SPILL
        raise GatewayUnavailable(
            "no routable replica (all down, draining, or already "
            "tried)", retry_after=self.table.retry_after_hint())

    def stats(self) -> dict:
        with self._lock:
            picks = dict(self.picks)
        total = sum(picks.values()) or 1
        return {"picks": picks,
                "affinity_hit_rate": round(picks[PICK_HIT] / total, 4),
                "block": self.block, "long_prefix": self.long_prefix}
