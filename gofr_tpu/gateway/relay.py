"""Forward + stream-relay mechanics for one gateway attempt.

One attempt = one HTTP connection to one replica, hand-rolled over a
raw socket. Hand-rolled on purpose: ``http.client``'s chunked reader
treats a TRUNCATED stream (peer closed before the terminal chunk —
exactly what a SIGKILLed replica looks like) as a clean EOF, which
would silently turn a mid-stream death into a shorter "successful"
response. The typed-503 contract needs the distinction, so the chunk
decoder here is explicit: a stream ends cleanly ONLY at the terminal
``0\\r\\n\\r\\n`` chunk (or the declared Content-Length); EOF anywhere
else raises.

The contract the failover loop (gateway/__init__.py) builds on:

  - :func:`forward` raises :class:`TransportLoss` for ANY failure
    before the replica commits a response (connect refused, send
    failure, EOF/timeout before response headers) — safe to retry
    elsewhere: nothing was delivered;
  - a COMPLETE non-2xx response comes back as a buffered
    :class:`ReplicaResponse` (sheds, drains, client errors — the
    loop decides whether to fail over or relay them);
  - a 2xx comes back live (``("stream", stream)``): the replica's
    HTTP server coalesces status+headers with the FIRST token chunk,
    so a 2xx in hand means the first token is already on the wire —
    reading it (:func:`first_line`) is the commit point after which
    failover would duplicate delivered tokens;
  - after commit, :func:`relay_lines` pipes replica lines to the
    client verbatim; a mid-stream loss terminates the (already-200)
    stream with one final typed error line
    ``{"error": {"message", "status": 503, "retry_after"}}`` — the
    ndjson mirror of the P/D relay's typed-503 contract
    (docs/advanced-guide/gateway.md documents the client side).
"""

from __future__ import annotations

import json
import socket

from ..errors import parse_retry_after
from .table import Replica

__all__ = ["ReplicaResponse", "ReplicaStream", "TransportLoss",
           "error_line", "first_line", "forward", "relay_lines"]


class TransportLoss(Exception):
    """The replica was lost before committing a response (or before
    its first token reached us): retriable by contract."""


class ReplicaResponse:
    """A buffered (non-streaming) replica reply."""

    __slots__ = ("status", "headers", "body")

    def __init__(self, status: int, headers: dict, body: bytes):
        self.status = int(status)
        self.headers = headers  # already lower-cased keys
        self.body = body

    def header(self, key: str, default: str = "") -> str:
        return self.headers.get(key.lower(), default)

    def retry_after(self) -> float | None:
        return parse_retry_after(self.header("Retry-After"))

    def message(self) -> str:
        try:
            return json.loads(self.body)["error"]["message"]
        except Exception:  # noqa: BLE001 — non-envelope body
            return self.body.decode("utf-8", "replace")[:200]


class ReplicaStream:
    """Line reader over a live replica response body that KNOWS the
    difference between a clean end and a truncation.

    ``next_line()`` returns one payload line (newline included), or
    ``None`` at a CLEAN end (terminal chunk / Content-Length
    satisfied), and raises :class:`TransportLoss` when the peer
    vanishes mid-body — the distinction ``http.client`` erases."""

    def __init__(self, sock: socket.socket, buffered: bytes, *,
                 chunked: bool, length: int | None):
        self._sock = sock
        self._raw = bytearray(buffered)  # undecoded wire bytes
        self._text = bytearray()         # decoded payload bytes
        self._chunked = chunked
        self._length = length  # remaining body bytes (non-chunked)
        self._state = "size" if chunked else "plain"
        self._chunk_left = 0
        self._decode()

    # -- chunked-transfer decoding -------------------------------------------
    def _decode(self) -> None:
        if not self._chunked:
            if self._raw:
                take = (len(self._raw) if self._length is None
                        else min(self._length, len(self._raw)))
                self._text += self._raw[:take]
                del self._raw[:take]
                if self._length is not None:
                    self._length -= take
            # checked OUTSIDE the raw-bytes branch: a Content-Length: 0
            # body must read as ended at construction, not block in
            # recv() waiting for bytes that will never come
            if self._length is not None and self._length <= 0:
                self._state = "end"
            return
        while True:
            if self._state == "size":
                i = self._raw.find(b"\r\n")
                if i < 0:
                    return
                size = int(bytes(self._raw[:i]).split(b";")[0] or b"0", 16)
                del self._raw[:i + 2]
                if size == 0:
                    self._state = "end"  # trailers ignored
                    return
                self._chunk_left = size
                self._state = "data"
            elif self._state == "data":
                if not self._raw:
                    return
                take = min(self._chunk_left, len(self._raw))
                self._text += self._raw[:take]
                del self._raw[:take]
                self._chunk_left -= take
                if self._chunk_left == 0:
                    self._state = "crlf"
            elif self._state == "crlf":
                if len(self._raw) < 2:
                    return
                del self._raw[:2]
                self._state = "size"
            else:
                return

    def next_line(self) -> bytes | None:
        while True:
            nl = self._text.find(b"\n")
            if nl >= 0:
                line = bytes(self._text[:nl + 1])
                del self._text[:nl + 1]
                return line
            if self._state == "end":
                if self._text:  # trailing partial line: still payload
                    line = bytes(self._text)
                    del self._text[:]
                    return line
                return None
            try:
                data = self._sock.recv(65536)
            except (OSError, ValueError) as e:
                raise TransportLoss(f"replica read: {e!r}") from e
            if not data:
                # EOF before the terminal chunk / declared length: the
                # replica DIED — never a clean (shorter) stream
                if self._chunked or (self._length or 0) > 0:
                    raise TransportLoss(
                        "replica closed mid-stream (truncated body)")
                # close-delimited body: EOF IS the end — loop back so
                # the "end" branch flushes a trailing partial line
                self._state = "end"
                continue
            self._raw += data
            self._decode()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _read_head(sock: socket.socket, buffered: bytearray) -> bytes:
    """Read up to the end of the response headers; returns the head
    bytes, leaving any body bytes in ``buffered``."""
    while b"\r\n\r\n" not in buffered:
        data = sock.recv(65536)
        if not data:
            raise TransportLoss("replica closed before response headers")
        buffered += data
    head, _, rest = bytes(buffered).partition(b"\r\n\r\n")
    del buffered[:]
    buffered += rest
    return head


def _parse_head(head: bytes) -> tuple[int, dict]:
    lines = head.decode("latin-1").split("\r\n")
    parts = lines[0].split(None, 2)
    status = int(parts[1])
    headers: dict[str, str] = {}
    for line in lines[1:]:
        k, _, v = line.partition(":")
        if _:
            headers[k.strip().lower()] = v.strip()
    return status, headers


def forward(replica: Replica, path: str, body: bytes, headers: dict,
            *, connect_timeout_s: float = 2.0,
            read_timeout_s: float = 120.0):
    """POST ``body`` to ``replica``. Returns ``("stream", stream)``
    for a 2xx (live) or ``("response", ReplicaResponse)`` for
    anything else (connection closed). Raises TransportLoss for any
    pre-response failure."""
    try:
        sock = socket.create_connection((replica.host, replica.port),
                                        timeout=connect_timeout_s)
    except OSError as e:
        raise TransportLoss(f"connect {replica.address}: {e!r}") from e
    try:
        # connect proved liveness fast; the response read gets the
        # longer budget (a long prefill sits between the request and
        # the first-token-carrying response headers)
        sock.settimeout(read_timeout_s)
        head = [f"POST {path} HTTP/1.1",
                f"Host: {replica.host}:{replica.port}",
                f"Content-Length: {len(body)}",
                "Connection: close"]
        head += [f"{k}: {v}" for k, v in headers.items()]
        sock.sendall(("\r\n".join(head) + "\r\n\r\n").encode("latin-1")
                     + body)
        buffered = bytearray()
        status, resp_headers = _parse_head(_read_head(sock, buffered))
    except TransportLoss:
        sock.close()
        raise
    except (OSError, ValueError) as e:
        sock.close()
        raise TransportLoss(f"request {replica.address}: {e!r}") from e
    chunked = "chunked" in resp_headers.get("transfer-encoding", "")
    length = resp_headers.get("content-length")
    length = int(length) if length is not None else None
    stream = ReplicaStream(sock, bytes(buffered), chunked=chunked,
                           length=length)
    if 200 <= status < 300:
        return "stream", stream
    # buffered reply: drain the body (bounded by the read timeout)
    body_parts = []
    try:
        while True:
            line = stream.next_line()
            if line is None:
                break
            body_parts.append(line)
    except TransportLoss as e:
        raise TransportLoss(
            f"response body {replica.address}: {e}") from e
    finally:
        stream.close()
    return "response", ReplicaResponse(status, resp_headers,
                                       b"".join(body_parts))


def first_line(stream: ReplicaStream) -> bytes:
    """Read the commit point: the replica's first token line. EOF or
    a transport error HERE is still pre-delivery — the caller may
    fail over."""
    line = stream.next_line()
    if line is None:
        raise TransportLoss("replica ended the stream before the "
                            "first token")
    return line


def error_line(message: str, status: int = 503,
               retry_after: float | None = None) -> bytes:
    detail: dict = {"message": message, "status": int(status)}
    if retry_after is not None:
        detail["retry_after"] = round(float(retry_after), 3)
    return (json.dumps({"error": detail}) + "\n").encode()


def relay_lines(first: bytes, stream: ReplicaStream, replica: Replica,
                *, retry_after: float = 1.0, on_loss=None):
    """Generator the gateway hands to ``ctx.stream``: the committed
    first line, then every further replica line verbatim, each
    flushed to the client as it arrives. A mid-stream replica loss
    (SIGKILL, network, truncation) emits ONE typed error line and
    ends the stream — the client sees tokens 1..k then a parseable
    typed 503, mirroring the P/D relay contract. The replica's
    in-flight count brackets the whole relay (drain observability)."""
    with replica._lock:
        replica.inflight += 1
    try:
        yield first
        while True:
            try:
                line = stream.next_line()
            except (TransportLoss, OSError) as e:
                if on_loss is not None:
                    on_loss(replica, e)
                yield error_line(
                    f"replica {replica.address} lost mid-stream",
                    status=503, retry_after=retry_after)
                return
            if line is None:
                return  # clean end: the terminal chunk arrived
            yield line
    finally:
        with replica._lock:
            replica.inflight -= 1
        stream.close()
