"""Replica table: health, drain, and memory-pressure state per replica.

The gateway's view of the cluster is this table. Each replica carries:

  - a ``service/`` client (circuit breaker wrapped) used for health
    polling — the SAME breaker discipline every inter-service call in
    the framework uses, so a dead replica costs microseconds, not
    connect timeouts;
  - a :class:`~gofr_tpu.service.reconnect.ReconnectBackoff` gating
    relay re-probes of a down replica (one real connect per backoff
    window — traffic itself is the recovery probe between health
    polls, and a down fleet never gets hammered);
  - drain state: a 503 from the replica (its ``drain_middleware``
    answering, or its health endpoint once readiness flips) marks it
    draining until the advertised ``Retry-After`` — the gateway stops
    routing NEW requests there the moment readiness drops, while
    streams already relaying finish on the old process (zero-loss
    rolling drain, docs/advanced-guide/gateway.md);
  - a decaying **memory-pressure score** fed by typed sheds: a 429
    with ``X-Shed-Reason: hbm`` scores a full point and holds the
    replica's ``Retry-After`` window; a plain queue shed scores a
    quarter point. The router reads the score to drain cache-heavy
    (long-prefix) traffic off a memory-pressured replica FIRST —
    short requests still land (they cost little KV), so pressure
    relief is graded, never a cliff.

Scores decay exponentially (half-life ``PRESSURE_HALF_LIFE_S``): a
replica that stops shedding earns its traffic back without any reset
call, on the same curve everywhere.
"""

from __future__ import annotations

import threading
import time

from ..errors import parse_retry_after
from ..service import (CircuitBreaker, CircuitBreakerOption, HealthOption,
                       ReconnectBackoff, new_http_service)

__all__ = ["Replica", "ReplicaTable",
           "PRESSURE_HBM", "PRESSURE_QUEUE", "PRESSURE_HALF_LIFE_S"]

#: score added per memory-typed shed (429 + X-Shed-Reason: hbm)
PRESSURE_HBM = 1.0
#: score added per plain queue shed (429 without a memory reason)
PRESSURE_QUEUE = 0.25
#: exponential decay half-life of the pressure score, seconds
PRESSURE_HALF_LIFE_S = 10.0

#: drain window assumed when a 503 carries no Retry-After
DEFAULT_DRAIN_S = 5.0

STATE_READY = "ready"
STATE_DRAINING = "draining"
STATE_DOWN = "down"


class Replica:
    """One serving replica's routing state. Mutators are called from
    handler threads (relay outcomes) AND the health-poll thread; every
    mutable field sits behind ``_lock``."""

    def __init__(self, idx: int, address: str, client, *,
                 clock=time.monotonic):
        self.idx = int(idx)
        self.address = address  # "host:port"
        host, _, port = address.rpartition(":")
        self.host = host
        self.port = int(port)
        self.client = client
        self.reconnect = ReconnectBackoff()
        self._clock = clock
        self._lock = threading.Lock()
        # optimistic start: the first poll (or first relay) corrects —
        # a gateway must route before its first health sweep completes
        self._healthy = True
        self._drain_until = 0.0
        self._hold_until = 0.0  # hbm Retry-After window
        self._pressure = 0.0
        self._pressure_ts = clock()
        self.inflight = 0
        self.relayed = 0
        self.sheds_hbm = 0
        self.sheds_queue = 0
        self.losses = 0

    # -- derived state --------------------------------------------------------
    @property
    def breaker_open(self) -> bool:
        layer = self.client
        while layer is not None:
            if isinstance(layer, CircuitBreaker):
                return layer.is_open
            layer = getattr(layer, "inner", None)
        return False

    def draining(self) -> bool:
        with self._lock:
            return self._clock() < self._drain_until

    def hbm_hold(self) -> bool:
        """Inside a memory-shed Retry-After window: the replica TOLD us
        when to come back with cache-heavy work — routing long-prefix
        traffic at it sooner is hammering, not balancing."""
        with self._lock:
            return self._clock() < self._hold_until

    def pressure(self) -> float:
        with self._lock:
            return self._decayed_locked()

    def _decayed_locked(self) -> float:
        dt = self._clock() - self._pressure_ts
        if dt > 0 and self._pressure > 0:
            self._pressure *= 0.5 ** (dt / PRESSURE_HALF_LIFE_S)
            self._pressure_ts += dt
        return self._pressure

    def routable(self) -> bool:
        """May NEW requests be routed here right now?"""
        with self._lock:
            healthy = self._healthy
            draining = self._clock() < self._drain_until
        return healthy and not draining and not self.breaker_open

    def probeable(self) -> bool:
        """A down replica out of its reconnect-backoff window: real
        traffic may re-probe it (lazy recovery between health polls)."""
        return not self.routable() and not self.draining() \
            and self.reconnect.blocked() == 0.0

    def state(self) -> str:
        if self.draining():
            return STATE_DRAINING
        if self.routable():
            return STATE_READY
        return STATE_DOWN

    # -- transitions ----------------------------------------------------------
    def note_shed(self, reason: str, retry_after: float | None) -> None:
        with self._lock:
            self._decayed_locked()
            if reason == "hbm":
                self.sheds_hbm += 1
                self._pressure += PRESSURE_HBM
                self._hold_until = max(
                    self._hold_until,
                    self._clock() + (retry_after or 1.0))
            else:
                self.sheds_queue += 1
                self._pressure += PRESSURE_QUEUE

    def mark_drain(self, retry_after: float | None = None) -> None:
        with self._lock:
            self._drain_until = self._clock() + (retry_after
                                                 or DEFAULT_DRAIN_S)

    def mark_down(self) -> None:
        with self._lock:
            self._healthy = False
            self.losses += 1
        self.reconnect.failure()

    def mark_up(self) -> None:
        with self._lock:
            self._healthy = True
            self._drain_until = 0.0
        self.reconnect.success()

    def retry_after_hint(self) -> float:
        """How soon is it worth trying THIS replica again — the honest
        component of a gateway-level 503's Retry-After."""
        with self._lock:
            drain = max(0.0, self._drain_until - self._clock())
        return max(drain, self.reconnect.blocked()) or 1.0

    def stats(self) -> dict:
        return {"address": self.address, "state": self.state(),
                "pressure": round(self.pressure(), 4),
                "hbm_hold": self.hbm_hold(),
                "breaker_open": self.breaker_open,
                "inflight": self.inflight, "relayed": self.relayed,
                "sheds_hbm": self.sheds_hbm,
                "sheds_queue": self.sheds_queue, "losses": self.losses}


class ReplicaTable:
    """The replica set + its background health poller.

    Health polling goes through the ``service/`` client chain (breaker
    + custom health endpoint), reading the replica's
    ``/.well-known/health``:

      - 2xx            -> up (clears down AND drain state)
      - 503            -> draining for the advertised Retry-After (the
                          ``drain_middleware`` readiness contract)
      - anything else / transport error / open breaker -> down

    Relay outcomes update the same state inline (a drain 503 or a
    connection loss re-routes the NEXT pick immediately); the poller
    is the recovery path and the steady-state confirmation.
    """

    def __init__(self, addresses: list[str], *, logger=None, metrics=None,
                 tracer=None, observe=None, poll_interval_s: float = 1.0,
                 breaker_threshold: int = 3,
                 breaker_interval_s: float = 2.0,
                 health_timeout_s: float = 2.0):
        if not addresses:
            raise ValueError("gateway needs at least one replica "
                             "(TPU_GATEWAY_REPLICAS=host:port,...)")
        self.logger = logger
        self.metrics = metrics
        self.observe = observe  # clock registry host (fleet alignment)
        self.poll_interval_s = float(poll_interval_s)
        self.replicas: list[Replica] = []
        for i, addr in enumerate(addresses):
            client = new_http_service(
                f"http://{addr}", logger, metrics,
                CircuitBreakerOption(threshold=breaker_threshold,
                                     interval=breaker_interval_s,
                                     start_background_probe=False),
                HealthOption("/.well-known/health"),
                tracer=tracer, timeout=health_timeout_s)
            self.replicas.append(Replica(i, addr, client))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def __len__(self) -> int:
        return len(self.replicas)

    # -- health polling -------------------------------------------------------
    def start(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self._poll_loop,
                                            name="gateway-health",
                                            daemon=True)
            self._thread.start()

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — the poller must survive
                if self.logger is not None:
                    self.logger.error({"event": "gateway health poll failed",
                                       "error": repr(e)})

    def poll_once(self) -> None:
        """One health sweep over every replica (public: tests and the
        bench drive it deterministically instead of sleeping)."""
        for r in self.replicas:
            self._poll_replica(r)
        self.push_metrics()

    def _poll_replica(self, r: Replica) -> None:
        was = r.state()
        t0 = time.time()
        try:
            resp = r.client.get("/.well-known/health")
        except Exception:  # noqa: BLE001 — open breaker / transport loss
            if r.state() != STATE_DOWN:
                r.mark_down()
            self._log_transition(r, was)
            return
        t3 = time.time()
        if resp.ok:
            r.mark_up()
            self._note_replica_clock(r, t0, t3, resp)
        elif resp.status_code == 503:
            ra = parse_retry_after(resp.header("Retry-After"))
            r.mark_drain(ra)
        else:
            if r.state() != STATE_DOWN:
                r.mark_down()
        self._log_transition(r, was)

    def _note_replica_clock(self, r: Replica, t0: float, t3: float,
                            resp) -> None:
        """The health poll as a free NTP carrier: the replica's health
        body stamps its send wall time (``obs.wall_s`` — t1 == t2, the
        handler stamps once) and advertises its metrics/debug port, so
        every poll refreshes the offset estimate and the peer's debug
        URL without a single extra connection."""
        clock = getattr(self.observe, "clock", None)
        if clock is None:
            return
        try:
            obs = (resp.json() or {}).get("obs") or {}
            wall = obs.get("wall_s")
            if wall is None:
                return  # pre-clock replica: nothing to sample
            mp = obs.get("metrics_port")
            url = (f"http://{r.address.split(':')[0]}:{int(mp)}"
                   if mp else None)
            clock.observe(f"replica:{r.address}", t0, float(wall),
                          float(wall), t3, debug_url=url)
        except Exception:
            pass  # telemetry must never fail the poller

    def _log_transition(self, r: Replica, was: str) -> None:
        now = r.state()
        if now != was and self.logger is not None:
            self.logger.info({"event": "gateway replica state",
                              "replica": r.address, "from": was, "to": now})

    def push_metrics(self) -> None:
        if self.metrics is None:
            return
        counts = {STATE_READY: 0, STATE_DRAINING: 0, STATE_DOWN: 0}
        try:
            for r in self.replicas:
                counts[r.state()] += 1
                self.metrics.set_gauge("app_tpu_gateway_pressure",
                                       r.pressure(), replica=r.address)
            for state, n in counts.items():
                self.metrics.set_gauge("app_tpu_gateway_replicas", n,
                                       state=state)
        except Exception:
            pass

    # -- aggregate reads ------------------------------------------------------
    def retry_after_hint(self) -> float:
        """Soonest any replica is worth retrying — the gateway-level
        503's honest Retry-After when nothing is routable."""
        return min((r.retry_after_hint() for r in self.replicas),
                   default=1.0)

    def stats(self) -> dict:
        return {"replicas": [r.stats() for r in self.replicas],
                "poll_interval_s": self.poll_interval_s}

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        for r in self.replicas:
            try:
                r.client.close()
            except Exception:
                pass
