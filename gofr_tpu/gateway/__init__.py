"""Prefix-affinity gateway: the gofr-native front door over N replicas.

``TPU_SERVING_ROLE=gateway`` turns an App into the cluster's router
(no engine, no jax compute — the replicas serve; this process makes
them robust AS A UNIT):

  - **replica table** (table.py): health polled through ``service/``
    clients wrapped in the framework circuit breaker; typed sheds
    (429 + ``X-Shed-Reason: hbm``) feed a decaying per-replica
    memory-pressure score;
  - **prefix-affinity routing** (router.py): consistent hash on the
    request's first KV block chain hash (the same block hashing the
    radix index and T2 fingerprint keys use), so multi-turn sessions
    land where their T0/T1 cache is warm — spilling to least-pressure
    on an unroutable or memory-held owner;
  - **failover with a retry budget**: a replica lost BEFORE the first
    token is retried transparently on another replica (nothing was
    delivered — safe), bounded by a token-bucket budget so a dying
    fleet can't amplify into a retry storm;
  - **durable streams (PR 18)**: the commit point moved from "first
    token" to "stream end" — a replica lost AFTER the first token is
    auto-resumed on a ring successor (whose T2 namespace covers the
    prompt+emitted block chain warm) by replaying the request as a
    ``continue_from`` continuation, spliced token-exact into the
    client's stream; the legacy typed 503 + Retry-After line only
    goes out when the retry budget / deadline / attempt cap is
    exhausted — and then it carries a resume token so the CLIENT can
    continue where the gateway could not;
  - **zero-loss rolling drain**: the moment a replica's readiness
    flips (its ``App.stop(grace_s)`` drain window), health polls and
    inline drain-503s stop NEW routing there while in-flight relays
    finish on the old process — a rolling restart of every replica
    loses nothing.

Chaos seams ``GATEWAY_PICK`` / ``GATEWAY_RELAY`` make pick starvation
and attempt-N replica loss deterministically injectable
(tests/test_gateway.py, tools/gateway_bench.py).

Config (read by :func:`gateway_from_config`; full rows in
docs/tpu/config-reference.md):

  TPU_GATEWAY_REPLICAS           host:port,host:port,...   (required)
  TPU_GATEWAY_PATH               forwarded route (default /generate)
  TPU_GATEWAY_BLOCK              affinity block tokens (default 16 —
                                 MUST match the replicas'
                                 TPU_KVCACHE_BLOCK)
  TPU_GATEWAY_LONG_PREFIX        cache-heavy threshold in tokens
                                 (default 4x block)
  TPU_GATEWAY_VNODES             ring virtual nodes/replica (64)
  TPU_GATEWAY_RETRY_RATIO        failover tokens earned per request
                                 (default 0.1 = retries <= 10% of
                                 traffic in steady state)
  TPU_GATEWAY_RETRY_BURST        failover token bucket cap (10)
  TPU_GATEWAY_HEALTH_INTERVAL_S  health poll cadence (1.0)
  TPU_GATEWAY_CONNECT_TIMEOUT_S  per-attempt connect budget (2.0)
  TPU_GATEWAY_STREAM_TIMEOUT_S   mid-stream stall bound (120)
  TPU_GATEWAY_BREAKER_THRESHOLD  health-client breaker threshold (3)
  TPU_GATEWAY_BREAKER_INTERVAL_S breaker recovery probe interval (2.0)
  TPU_RESUME                     post-commit auto-resume (default true)
  TPU_RESUME_MAX                 resume attempts per stream (default 3)
"""

from __future__ import annotations

import json
import random
import threading
import time
import uuid

from .. import chaos, tracing
from ..errors import BadRequest, DeadlineExceeded, HTTPError, TooManyRequests
from ..resilience import current_deadline, current_slo_class
from ..service.wrap import hop_context, set_header_default
from .relay import (ReplicaResponse, TransportLoss, error_line,
                    first_line, forward, relay_lines)
from .router import (AffinityRouter, GatewayUnavailable, HashRing,
                     RetryBudget)
from .table import Replica, ReplicaTable

__all__ = ["AffinityRouter", "Gateway", "GatewayUnavailable", "HashRing",
           "Replica", "ReplicaTable", "RetryBudget", "ROLE_GATEWAY",
           "gateway_from_config", "install_gateway", "parse_replicas"]

ROLE_GATEWAY = "gateway"

#: headers the gateway OWNS on the replica hop — hop-by-hop framing the
#: relay rewrites itself, plus the context headers it re-derives from
#: the ambient request (trace / SLO class / remaining deadline). Every
#: OTHER client header passes through verbatim.
_HOP_OWNED_HEADERS = frozenset({
    "host", "connection", "content-length", "transfer-encoding",
    "keep-alive", "te", "upgrade", "proxy-authorization",
    "proxy-connection", "accept-encoding", "traceparent", "tracestate",
    "x-request-timeout", "x-slo-class", "x-obs-hop",
})


def parse_replicas(spec: str | None) -> list[str]:
    """``TPU_GATEWAY_REPLICAS`` -> addresses. Accepts bare host:port
    and http://host:port forms; a malformed entry fails startup loudly
    (a front door with a typo'd replica list is a misdeployed
    cluster, the failure class that must never serve silently)."""
    out: list[str] = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if part.startswith("http://"):
            part = part[len("http://"):].rstrip("/")
        host, _, port = part.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"TPU_GATEWAY_REPLICAS entry {part!r}: "
                             "expected host:port")
        out.append(f"{host}:{int(port)}")
    if not out:
        raise ValueError("TPU_SERVING_ROLE=gateway requires "
                         "TPU_GATEWAY_REPLICAS=host:port,...")
    return out


class _ResumeCtx:
    """Everything the post-commit auto-resume loop needs about one
    request: the stamped forward payload, the affinity key, and the
    client headers to re-derive hop context from on each continuation.
    The request id and sampling seed are chosen HERE, before the first
    forward — a SIGKILLed replica emits nothing, so anything a resume
    needs must already be in the first attempt's body."""

    __slots__ = ("payload", "key", "plen", "rid",
                 "client_headers", "resumable")

    def __init__(self, payload: dict, key, plen: int,
                 client_headers: dict):
        self.payload = payload
        self.key = key
        self.plen = plen
        self.rid = payload.get("request_id")
        self.client_headers = client_headers
        # flips False the moment the stream breaks the cursor contract
        # (a cursor-less legacy replica, a splice gap): from then on
        # the gateway is the PR 14 transparent relay again
        self.resumable = True

    def body(self) -> bytes:
        return json.dumps(self.payload).encode()

    def resume_body(self, emitted: list) -> bytes:
        p = dict(self.payload)
        p["resume_from"] = len(emitted)
        p["emitted"] = list(emitted)
        return json.dumps(p).encode()


class Gateway:
    """The router + failover engine behind the gateway App's routes."""

    def __init__(self, table: ReplicaTable, *, path: str = "/generate",
                 block: int = 16, long_prefix: int | None = None,
                 vnodes: int = 64, retry_ratio: float = 0.1,
                 retry_burst: float = 10.0,
                 connect_timeout_s: float = 2.0,
                 stream_timeout_s: float = 120.0,
                 resume: bool = True, resume_max: int = 3,
                 logger=None, metrics=None, observe=None):
        self.table = table
        self.path = path
        self.block = max(1, int(block))
        self.router = AffinityRouter(table, block=self.block,
                                     long_prefix=long_prefix,
                                     vnodes=vnodes, metrics=metrics)
        self.budget = RetryBudget(ratio=retry_ratio, burst=retry_burst)
        self.connect_timeout_s = float(connect_timeout_s)
        self.stream_timeout_s = float(stream_timeout_s)
        self.resume = bool(resume)
        self.resume_max = max(0, int(resume_max))
        self.logger = logger
        self.metrics = metrics
        self.observe = observe  # wide-event recorder + clock registry
        self._lock = threading.Lock()
        self.outcomes = {"ok": 0, "shed": 0, "failed": 0, "midstream": 0}
        self.failovers = {"transport": 0, "drain": 0, "shed": 0}
        self.resumes = 0

    # -- bookkeeping ----------------------------------------------------------
    def _outcome(self, kind: str) -> None:
        with self._lock:
            self.outcomes[kind] += 1
        if self.metrics is not None:
            try:
                self.metrics.increment_counter(
                    "app_tpu_gateway_requests_total", outcome=kind)
            except Exception:
                pass

    def _failover(self, reason: str, replica: Replica) -> None:
        with self._lock:
            self.failovers[reason] += 1
        if self.metrics is not None:
            try:
                self.metrics.increment_counter(
                    "app_tpu_gateway_failovers_total", reason=reason)
            except Exception:
                pass
        if self.logger is not None:
            self.logger.info({"event": "gateway failover",
                              "reason": reason,
                              "replica": replica.address})

    def _exhausted(self) -> None:
        if self.metrics is not None:
            try:
                self.metrics.increment_counter(
                    "app_tpu_gateway_retry_exhausted_total")
            except Exception:
                pass

    # -- the forwarded-request context ---------------------------------------
    def _affinity_key(self, body: bytes,
                      tenant: str = "") -> tuple[bytes | None, int]:
        try:
            payload = json.loads(body)
            tokens = payload["tokens"]
            plen = len(tokens)
            adapter = int(payload.get("adapter", 0) or 0)
        except Exception as e:  # noqa: BLE001 — client error, typed 400
            raise BadRequest("gateway: body must be JSON with a "
                             "'tokens' array") from e
        if plen < self.block:
            return None, plen  # sub-block: affinity-less by design
        from ..tpu.kvcache import first_block_hash

        try:
            key = first_block_hash(tokens, self.block, adapter)
        except Exception as e:  # noqa: BLE001 — non-numeric tokens
            raise BadRequest("gateway: 'tokens' must be an array of "
                             "integers") from e
        if tenant:
            # tenants partition the fleet's prefix caches: the same
            # prompt prefix from two tenants lands on (usually)
            # different replicas, so one tenant's working set never
            # thrashes another's T0 rows fleet-wide
            key = key + b"|" + tenant.encode("utf-8", "replace")
        return key, plen

    def _forward_headers(self, client_headers: dict) -> tuple[dict, float]:
        """The replica-hop headers + the tightened read timeout. Client
        headers pass through (an authenticated cluster stays usable
        behind the front door: Authorization / API keys / custom
        headers reach the replica) EXCEPT the ones the gateway owns on
        this hop — connection framing, and the context headers it
        re-derives: W3C trace (the gateway's span continues the
        client's trace, so cross-process traces join through BOTH
        hops), SLO class, and the remaining deadline (the budget
        covers the WHOLE request, not each hop)."""
        hdrs = {k: v for k, v in client_headers.items()
                if k.lower() not in _HOP_OWNED_HEADERS}
        set_header_default(hdrs, "Content-Type", "application/json")
        span = tracing.current_span()
        if span is not None:
            hdrs["traceparent"] = span.traceparent()
        timeout = hop_context(hdrs, self.stream_timeout_s)
        return hdrs, timeout

    # -- the serving path -----------------------------------------------------
    def handle_generate(self, ctx):
        """The gateway's /generate: pick -> forward -> commit at first
        token -> relay; pre-commit failures fail over under the retry
        budget; post-commit failures terminate typed.

        Every terminal emits the gateway's own wide ``request`` event —
        a request shed HERE never reached an engine, so without this
        record it would vanish from every wide-event surface."""
        st = {"t0": time.monotonic(), "submit_wall": time.time(),
              "bd": {}, "replica": None, "route": None, "tried": 0,
              "shed_reason": None}
        try:
            out = self._relay_attempts(ctx, st)
        except TooManyRequests as e:
            self._wide_request("shed", st, error=repr(e))
            raise
        except GatewayUnavailable as e:
            self._wide_request("shed", st, error=repr(e))
            raise
        except BaseException as e:
            self._wide_request("failed", st, error=repr(e))
            raise
        self._wide_request("ok", st)
        return out

    def _wide_request(self, outcome: str, st: dict,
                      error: str | None = None) -> None:
        """The gateway's terminal wide event: same skeleton as the
        engine's (event/outcome/trace_id/slo_class lead), with the
        routing story — picked replica, affinity label, failover spend
        — and the gateway's critical-path segments (pick / connect /
        ttfb). Telemetry only: never raises into the relay."""
        try:
            now = time.monotonic()
            span = tracing.current_span()
            trace_id = span.trace_id if span is not None else ""
            wide: dict = {"event": "request", "outcome": outcome,
                          "trace_id": trace_id,
                          "slo_class": current_slo_class(),
                          "gateway": True, "replica": st["replica"],
                          "route": st["route"], "tried": st["tried"],
                          "failovers": max(0, st["tried"] - 1),
                          "duration_s": round(now - st["t0"], 6),
                          "submit_wall_s": round(st["submit_wall"], 6)}
            if st.get("resumes"):
                # a stream that died mid-relay and was spliced back is
                # its own terminal outcome — dashboards count resumes
                # without joining on fields
                wide["outcome"] = "resumed"
                wide["resume_count"] = st["resumes"]
                wide["resumed_at_cursor"] = st.get("resumed_at_cursor")
                if st.get("recompute_tokens") is not None:
                    wide["recompute_tokens"] = st["recompute_tokens"]
            bd = {k: round(v, 6) for k, v in st["bd"].items()}
            if bd:
                wide["breakdown"] = bd
            if st.get("shed_reason"):
                wide["shed_reason"] = st["shed_reason"]
            if error is not None:
                wide["error"] = error
            if self.metrics is not None and bd:
                for i, (seg, v) in enumerate(sorted(bd.items())):
                    try:
                        self.metrics.record_histogram(
                            "app_tpu_request_segment_duration", v,
                            exemplar=((trace_id or None) if i == 0
                                      else None),
                            segment=seg[:-2], program="gateway")
                    except Exception:
                        pass
            if self.observe is not None:
                self.observe.recorder.record(
                    "request", trace_id=trace_id,
                    **{k: v for k, v in wide.items()
                       if k not in ("event", "trace_id")})
            if self.logger is not None:
                self.logger.wide(wide)
        except Exception:
            pass  # telemetry must never take the relay down

    def _resume_ctx(self, ctx, body: bytes, key, plen) -> _ResumeCtx | None:
        """Stamp the forward body for durability: a request id (the
        dedup identity a resumed replay carries) and, for sampled
        requests, a pinned seed (resume-exact sampling re-keys on
        (seed, absolute position) — the continuation must draw from
        the same stream the dead replica did). None when resume is off
        or the body isn't the generate contract (the gateway stays a
        transparent relay for anything else)."""
        if not self.resume or self.resume_max <= 0:
            return None
        try:
            payload = json.loads(body)
        except Exception:  # noqa: BLE001 — unreachable after key parse
            return None
        if not isinstance(payload, dict) or not isinstance(
                payload.get("tokens"), list):
            return None
        if not payload.get("request_id"):
            payload["request_id"] = f"gw-{uuid.uuid4().hex[:16]}"
        if (payload.get("temperature") or 0) \
                and payload.get("seed") is None:
            payload["seed"] = random.getrandbits(31)
        return _ResumeCtx(payload, key, plen, dict(ctx.request.headers))

    def _relay_attempts(self, ctx, st: dict):
        body = ctx.request.body or b""
        key, plen = self._affinity_key(
            body, tenant=ctx.header("X-Tenant-Id").strip())
        rctx = self._resume_ctx(ctx, body, key, plen)
        if rctx is not None:
            body = rctx.body()
        headers, read_timeout = self._forward_headers(ctx.request.headers)
        # hop stamp: when THIS hop forwarded, on the gateway's wall
        # clock — /debug/request places the gateway->replica gap with it
        headers["X-Obs-Hop"] = repr(time.time())
        bd = st["bd"]
        self.budget.deposit()
        tried: set[int] = set()
        last_shed: ReplicaResponse | None = None
        n = len(self.table)
        while len(tried) < n:
            t_pick = time.monotonic()
            try:
                replica, label = self.router.pick(key, plen,
                                                  exclude=tried)
            except GatewayUnavailable:
                break
            except Exception as e:  # noqa: BLE001 — injected at the seam
                # a GATEWAY_PICK chaos error fails THIS decision typed,
                # never the gateway process
                self._outcome("shed")
                raise GatewayUnavailable(
                    f"gateway pick failed: {e!r}",
                    retry_after=self.table.retry_after_hint()) from e
            finally:
                bd["pick_s"] = bd.get("pick_s", 0.0) \
                    + (time.monotonic() - t_pick)
            tried.add(replica.idx)
            st["tried"] = len(tried)
            st["replica"], st["route"] = replica.address, label
            try:
                chaos.fire(chaos.GATEWAY_RELAY)
                t_conn = time.monotonic()
                kind, payload = forward(
                    replica, self.path, body, headers,
                    connect_timeout_s=self.connect_timeout_s,
                    read_timeout_s=read_timeout)
                bd["connect_s"] = bd.get("connect_s", 0.0) \
                    + (time.monotonic() - t_conn)
                if kind == "stream":
                    t_ttfb = time.monotonic()
                    try:
                        first = first_line(payload)
                    except BaseException:
                        payload.close()
                        raise
                    finally:
                        bd["ttfb_s"] = bd.get("ttfb_s", 0.0) \
                            + (time.monotonic() - t_ttfb)
            except Exception as e:  # noqa: BLE001 — attempt loss
                dl = current_deadline()
                if dl is not None and dl.remaining() <= 0:
                    # the CALLER's budget expired mid-attempt (the
                    # relay's read timeout tightens to it): a 504 on
                    # THIS request, never evidence against the replica
                    # — one impatient client must not mark a healthy
                    # fleet down or drain the shared failover budget
                    self._outcome("failed")
                    raise DeadlineExceeded(
                        "gateway: caller deadline expired during the "
                        f"attempt on {replica.address}") from e
                # TransportLoss or an injected GATEWAY_RELAY error:
                # nothing delivered, the replica is suspect
                replica.mark_down()
                if len(tried) >= n or not self.budget.withdraw():
                    self._exhausted()
                    self._outcome("shed")
                    raise GatewayUnavailable(
                        f"replica {replica.address} lost before first "
                        "token and the failover budget is spent",
                        retry_after=self.table.retry_after_hint()) from e
                self._failover("transport", replica)
                continue
            if kind == "stream":
                # the first token is in hand: requests_total counts
                # here, but with durable streams this is no longer the
                # commit point — the resume relay keeps the request
                # recoverable until the terminal chunk
                replica.mark_up()
                with replica._lock:
                    replica.relayed += 1
                self._outcome("ok")
                if rctx is not None:
                    ctx.stream(self._relay_resume(
                        st, first, payload, replica, rctx))
                else:
                    ctx.stream(relay_lines(
                        first, payload, replica,
                        retry_after=replica.reconnect.retry_after(),
                        on_loss=self._on_midstream_loss))
                return None
            r: ReplicaResponse = payload
            if r.status == 429:
                reason = r.header("X-Shed-Reason")
                replica.note_shed(reason, r.retry_after())
                st["shed_reason"] = reason or "queue"
                last_shed = r
                # a shed elsewhere may still serve — but a shedding
                # FLEET must not be retried into a storm: budget-gated
                if len(tried) < n:
                    if self.budget.withdraw():
                        self._failover("shed", replica)
                        continue
                    self._exhausted()
                break
            if r.status == 503:
                # the drain_middleware readiness contract: re-pick,
                # budget-FREE (a rolling deploy is an orderly event,
                # not a failure storm)
                replica.mark_drain(r.retry_after())
                self._failover("drain", replica)
                continue
            # any other status: the gateway is transparent
            self._outcome("failed")
            err = HTTPError(r.message(), status_code=r.status)
            err.headers = {k: v for k, v in r.headers.items()
                           if k in ("retry-after", "x-shed-reason")}
            raise err
        if last_shed is not None:
            # every failover avenue closed on a shed: relay it honestly
            # (the replica's Retry-After + reason survive the hop)
            self._outcome("shed")
            raise TooManyRequests(
                last_shed.message(),
                retry_after=last_shed.retry_after() or 1.0,
                reason=last_shed.header("X-Shed-Reason") or None)
        self._outcome("shed")
        raise GatewayUnavailable(
            "no replica could serve (all down, draining, or tried)",
            retry_after=self.table.retry_after_hint())

    # -- durable streams: the post-commit auto-resume relay -------------------
    def _relay_resume(self, st: dict, first: bytes, stream,
                      replica: Replica, rctx: _ResumeCtx):
        """``relay_lines``' durable twin: the commit point moves from
        "first token" to "stream end". Cursor-carrying lines are
        tracked as the client's authoritative emitted list; on a
        mid-stream loss (transport truncation, OR a typed error line
        carrying a resume token — the replica's engine declared the
        death itself) the loop re-picks via the ring, replays
        prompt+emitted as a ``continue_from`` continuation, validates
        the splice cursor, and keeps relaying: zero duplicate, zero
        missing tokens. Replayed-duplicate lines (cursor below the
        client's position) are swallowed, so even an over-replaying
        replica can't double-deliver. Only when resume is exhausted
        does the typed error line go out — carrying the resume token
        so the client can continue on its own."""
        emitted: list = [int(t) for t in
                         (rctx.payload.get("emitted") or [])]
        cur = (first, stream, replica)
        while True:
            line, strm, rep = cur
            loss: BaseException | None = None
            transport = False
            with rep._lock:
                rep.inflight += 1
            try:
                while line is not None:
                    try:
                        obj = json.loads(line)
                    except Exception:  # noqa: BLE001 — non-JSON payload
                        obj = None
                    if isinstance(obj, dict) and "token" in obj \
                            and "cursor" in obj:
                        cursor = int(obj["cursor"])
                        if cursor == len(emitted):
                            emitted.append(int(obj["token"]))
                            yield line
                        elif cursor < len(emitted):
                            pass  # replayed duplicate: client has it
                        else:
                            # cursor gap: the contract broke — stop
                            # trusting resume, stay a transparent relay
                            rctx.resumable = False
                            yield line
                    elif isinstance(obj, dict) and "error" in obj:
                        err = (obj["error"]
                               if isinstance(obj["error"], dict) else {})
                        if err.get("resume") is not None and \
                                int(err.get("status", 0)) in (429, 503):
                            # the replica PROCESS is alive (it spoke) —
                            # one engine stream died; resume without
                            # marking the replica down
                            loss = TransportLoss(
                                "replica ended mid-stream: "
                                + str(err.get("message", ""))[:200])
                            break
                        yield line
                        return  # terminal typed line: relay + end
                    else:
                        rctx.resumable = False  # cursor-less replica
                        yield line
                    try:
                        chaos.fire(chaos.GATEWAY_MIDSTREAM)
                        line = strm.next_line()
                    except (TransportLoss, OSError) as e:
                        loss, transport = e, True
                        break
                    except Exception as e:  # noqa: BLE001 — chaos seam
                        loss, transport = e, True
                        break
            finally:
                with rep._lock:
                    rep.inflight -= 1
                strm.close()
            if loss is None:
                return  # clean terminal chunk: the durable commit
            if transport:
                rep.mark_down()
            nxt = self._resume_attempt(st, rep, rctx, emitted,
                                       exclude_dead=transport)
            if nxt is None:
                self._on_midstream_loss(rep, loss)
                yield self._resume_error_line(rep, rctx, emitted)
                return
            cur = nxt

    def _resume_attempt(self, st: dict, dead: Replica,
                        rctx: _ResumeCtx, emitted: list, *,
                        exclude_dead: bool = True):
        """One auto-resume: budget + deadline + attempt-cap gated
        re-pick and continuation forward. Routing prefers the ring
        successor for the SAME affinity key — the replica whose T2
        namespace covers the prompt+emitted chain warm. Returns the
        next ``(first_line, stream, replica)`` or None when the typed
        line must go out after all. A replica whose engine killed one
        stream (typed loss) stays eligible — it is alive and has the
        warmest cache of anyone."""
        if not rctx.resumable or not emitted:
            return None
        if st.get("resumes", 0) >= self.resume_max:
            return None
        dl = current_deadline()
        if dl is not None and dl.remaining() <= 0:
            return None
        t0 = time.monotonic()
        try:
            headers, read_timeout = self._forward_headers(
                rctx.client_headers)
            headers["X-Obs-Hop"] = repr(time.time())
            body = rctx.resume_body(emitted)
            tried: set[int] = {dead.idx} if exclude_dead else set()
            n = len(self.table)
            attempts = 0
            while attempts < n:
                attempts += 1
                if not self.budget.withdraw():
                    self._exhausted()
                    return None
                try:
                    rep, label = self.router.pick(
                        rctx.key, rctx.plen + len(emitted),
                        exclude=tried)
                except Exception:  # noqa: BLE001 — nobody pickable
                    return None
                tried.add(rep.idx)
                st["tried"] = st.get("tried", 0) + 1
                try:
                    kind, payload = forward(
                        rep, self.path, body, headers,
                        connect_timeout_s=self.connect_timeout_s,
                        read_timeout_s=read_timeout)
                except Exception:  # noqa: BLE001 — attempt loss
                    rep.mark_down()
                    continue
                if kind != "stream":
                    r: ReplicaResponse = payload
                    if r.status == 429:
                        rep.note_shed(r.header("X-Shed-Reason"),
                                      r.retry_after())
                        continue
                    if r.status == 503:
                        rep.mark_drain(r.retry_after())
                        continue
                    return None  # non-retriable: typed line goes out
                try:
                    nfirst = first_line(payload)
                    obj = json.loads(nfirst)
                    if int(obj["cursor"]) > len(emitted):
                        raise ValueError("splice cursor gap")
                except Exception:  # noqa: BLE001 — broken splice
                    # 200 but not the resume contract (legacy replica
                    # regenerating from scratch): relaying would
                    # duplicate tokens — drop the attempt, not resume
                    payload.close()
                    continue
                rep.mark_up()
                with rep._lock:
                    rep.relayed += 1
                st["resumes"] = st.get("resumes", 0) + 1
                st["resumed_at_cursor"] = len(emitted)
                if isinstance(obj, dict) and "recompute" in obj:
                    st["recompute_tokens"] = int(obj["recompute"])
                st["replica"], st["route"] = rep.address, label
                self._note_resume(dead, rep, st.get("recompute_tokens"))
                return nfirst, payload, rep
            return None
        finally:
            st["bd"]["resume_s"] = st["bd"].get("resume_s", 0.0) \
                + (time.monotonic() - t0)

    def _note_resume(self, lost: Replica, to: Replica,
                     recompute) -> None:
        with self._lock:
            self.resumes += 1
        if self.metrics is not None:
            try:
                self.metrics.increment_counter(
                    "app_tpu_gateway_resumes_total")
                if recompute is not None:
                    span = tracing.current_span()
                    self.metrics.record_histogram(
                        "app_tpu_resume_recompute_tokens",
                        float(recompute),
                        exemplar=(span.trace_id if span is not None
                                  else None))
            except Exception:
                pass
        if self.logger is not None:
            self.logger.info({"event": "gateway stream resumed",
                              "from": lost.address, "to": to.address,
                              "recompute_tokens": recompute})

    def _resume_error_line(self, rep: Replica, rctx: _ResumeCtx,
                           emitted: list) -> bytes:
        """The exhausted-resume terminal: the legacy typed 503 line,
        plus the resume token when the stream is still continuable —
        the client (service/client.py) can pick up where the gateway's
        budget ran out."""
        retry_after = rep.reconnect.retry_after()
        if not rctx.resumable or not emitted:
            return error_line(f"replica {rep.address} lost mid-stream",
                              status=503, retry_after=retry_after)
        detail: dict = {
            "message": f"replica {rep.address} lost mid-stream and "
                       "auto-resume is exhausted",
            "status": 503, "retry_after": round(float(retry_after), 3)}
        resume: dict = {"request_id": rctx.rid, "cursor": len(emitted)}
        seed = rctx.payload.get("seed")
        if seed is not None:
            resume["seed"] = int(seed)
        try:
            from ..serving import resume_chain
            resume["chain"] = resume_chain(
                rctx.payload["tokens"], emitted, self.block,
                int(rctx.payload.get("adapter", 0) or 0))
        except Exception:
            pass  # fingerprint is advisory; the token works without it
        detail["resume"] = resume
        return (json.dumps({"error": detail}) + "\n").encode()

    def _on_midstream_loss(self, replica: Replica, err) -> None:
        replica.mark_down()
        # NOT an _outcome: this request already counted "ok" at its
        # commit point — requests_total stays one count per request
        # ("by terminal outcome"); mid-relay terminations get their
        # own counter (the stats dict keeps the key for /gateway/stats)
        with self._lock:
            self.outcomes["midstream"] += 1
        if self.metrics is not None:
            try:
                self.metrics.increment_counter(
                    "app_tpu_gateway_midstream_total")
            except Exception:
                pass
        if self.logger is not None:
            self.logger.warn({"event": "gateway replica lost mid-stream",
                              "replica": replica.address,
                              "error": repr(err)})

    # -- surfaces -------------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            outcomes = dict(self.outcomes)
            failovers = dict(self.failovers)
            resumes = self.resumes
        return {"path": self.path, "outcomes": outcomes,
                "failovers": failovers, "resumes": resumes,
                "budget": self.budget.stats(),
                "router": self.router.stats(),
                "table": self.table.stats()}

    def close(self) -> None:
        self.table.close()


def gateway_from_config(cfg, *, logger=None, metrics=None,
                        tracer=None, observe=None) -> Gateway:
    addresses = parse_replicas(cfg.get("TPU_GATEWAY_REPLICAS"))
    table = ReplicaTable(
        addresses, logger=logger, metrics=metrics, tracer=tracer,
        observe=observe,
        poll_interval_s=cfg.get_float("TPU_GATEWAY_HEALTH_INTERVAL_S", 1.0),
        breaker_threshold=cfg.get_int("TPU_GATEWAY_BREAKER_THRESHOLD", 3),
        breaker_interval_s=cfg.get_float("TPU_GATEWAY_BREAKER_INTERVAL_S",
                                         2.0),
        health_timeout_s=cfg.get_float("TPU_GATEWAY_CONNECT_TIMEOUT_S",
                                       2.0))
    block = cfg.get_int("TPU_GATEWAY_BLOCK", 16)
    long_prefix = cfg.get_int("TPU_GATEWAY_LONG_PREFIX", 0) or None
    return Gateway(
        table,
        path=cfg.get_or_default("TPU_GATEWAY_PATH", "/generate"),
        block=block, long_prefix=long_prefix,
        vnodes=cfg.get_int("TPU_GATEWAY_VNODES", 64),
        retry_ratio=cfg.get_float("TPU_GATEWAY_RETRY_RATIO", 0.1),
        retry_burst=cfg.get_float("TPU_GATEWAY_RETRY_BURST", 10.0),
        connect_timeout_s=cfg.get_float("TPU_GATEWAY_CONNECT_TIMEOUT_S",
                                        2.0),
        stream_timeout_s=cfg.get_float("TPU_GATEWAY_STREAM_TIMEOUT_S",
                                       120.0),
        resume=cfg.get_bool("TPU_RESUME", True),
        resume_max=cfg.get_int("TPU_RESUME_MAX", 3),
        logger=logger, metrics=metrics, observe=observe)


def install_gateway(app) -> Gateway:
    """Wire the gateway role into an App: build from config, register
    the forwarded route + the stats page, register each replica's
    health client in the container (the aggregated
    ``/.well-known/health`` lists them like any other dependency),
    and start the health poller when the app runs."""
    gw = gateway_from_config(app.config, logger=app.logger,
                             metrics=app.container.metrics,
                             tracer=app.container.tracer,
                             observe=app.container.observe)
    for r in gw.table.replicas:
        app.container.register_service(f"gateway-replica-{r.idx}",
                                       r.client)

    def generate(ctx):
        return gw.handle_generate(ctx)

    def stats(ctx):
        return gw.stats()

    app.post(gw.path, generate)
    app.get("/gateway/stats", stats)
    # the health poller starts in App.run (a constructed-but-never-run
    # gateway App must not poll replicas in the background)
    if app.logger is not None:
        app.logger.info({
            "event": "gateway role wired", "path": gw.path,
            "replicas": [r.address for r in gw.table.replicas]})
    return gw
