"""Migrations: ordered run-once schema/data/model changes with a version ledger.

Reference: pkg/gofr/migration/ —
  - ``Run(map[int64]Migrate, c)`` (migration.go:23-108): validate UP funcs,
    sort versions, read last applied from SQL/Redis, run each pending
    migration inside a transaction, record version + duration
  - SQL ledger table ``gofr_migrations`` (sql.go:142-158), rollback on
    failure (sql.go:102-112)
  - Redis hash ledger ``gofr_migrations`` (redis.go:53-67)
  - tx-scoped Datasource facade {SQL, Redis, PubSub} (datasource.go:3-9);
    pubsub exposes Create/DeleteTopic only (pubsub.go:5-24)

TPU extension (SURVEY §7 step 7): migrations are also the model/weight
version ledger — ``ds.tpu.register_model(...)`` records which model+weights
revision the app serves, so rollouts are ordered and auditable the same way
schema changes are.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

LEDGER_TABLE = "gofr_migrations"
LEDGER_HASH = "gofr_migrations"


@dataclasses.dataclass
class Migrate:
    """One migration: an UP function receiving the tx-scoped Datasource
    (reference migration.go:14-18; no DOWN — same as the reference)."""

    up: Callable[["Datasource"], None]


class _MigrationPubSub:
    """Topic admin only (reference pubsub.go:5-24)."""

    def __init__(self, client):
        self._client = client

    def create_topic(self, name: str) -> None:
        self._client.create_topic(name)

    def delete_topic(self, name: str) -> None:
        self._client.delete_topic(name)


class _MigrationTPU:
    """Model-version ledger facade: records weight/program revisions the
    way SQL migrations record schema revisions."""

    def __init__(self, engine):
        self._engine = engine
        self.registered: list[dict[str, Any]] = []

    def register_model(self, name: str, weights_path: str = "",
                       revision: str = "") -> None:
        entry = {"name": name, "weights_path": weights_path,
                 "revision": revision}
        self.registered.append(entry)
        if self._engine is not None and hasattr(self._engine, "note_model_version"):
            self._engine.note_model_version(**entry)


class Datasource:
    """What an UP function sees (reference datasource.go:3-9)."""

    def __init__(self, sql=None, redis=None, pubsub=None, tpu=None, logger=None):
        self.sql = sql
        self.redis = redis
        self.pubsub = _MigrationPubSub(pubsub) if pubsub is not None else None
        self.tpu = _MigrationTPU(tpu)
        self.logger = logger


class MigrationError(Exception):
    pass


def _ensure_sql_ledger(sql) -> None:
    """DDL per reference sql.go:142-158 (dialect-neutral subset)."""
    sql.execute(
        f"CREATE TABLE IF NOT EXISTS {LEDGER_TABLE} ("
        "version INTEGER PRIMARY KEY, "
        "method TEXT, "
        "start_time TEXT, "
        "duration_ms INTEGER)")


def _last_sql_version(sql) -> int:
    row = sql.query_row(f"SELECT MAX(version) AS v FROM {LEDGER_TABLE}")
    return int(row["v"]) if row and row["v"] is not None else 0


def _last_redis_version(redis) -> int:
    data = redis.hgetall(LEDGER_HASH)
    return max((int(v) for v in data.keys()), default=0)


def run(migrations: dict[int, Migrate | Callable], container) -> None:
    """Apply pending migrations in version order (reference migration.go:23-108)."""
    if not migrations:
        return
    log = container.logger

    normalized: dict[int, Migrate] = {}
    for version, m in migrations.items():
        if callable(m) and not isinstance(m, Migrate):
            m = Migrate(up=m)
        if m.up is None or not callable(m.up):
            raise MigrationError(f"migration {version} has no UP function")
        normalized[int(version)] = m

    sql, redis, pubsub, tpu = (container.sql, container.redis,
                               container.pubsub, container.tpu)

    last = 0
    if sql is not None:
        _ensure_sql_ledger(sql)
        last = max(last, _last_sql_version(sql))
    if redis is not None:
        last = max(last, _last_redis_version(redis))

    for version in sorted(normalized):
        if version <= last:
            continue
        m = normalized[version]
        start = time.time()
        ds = Datasource(sql=sql, redis=redis, pubsub=pubsub, tpu=tpu, logger=log)

        tx = sql.begin() if sql is not None else None
        if tx is not None:
            ds.sql = tx  # UP runs inside the transaction (migration.go:77-93)
        try:
            m.up(ds)
        except Exception as e:
            if tx is not None:
                tx.rollback()
            log.error({"event": "migration failed", "version": version,
                       "error": repr(e)})
            raise MigrationError(f"migration {version} failed: {e!r}") from e

        duration_ms = int((time.time() - start) * 1000)
        if tx is not None:
            # version row inside the same tx (reference sql.go:114-139); a
            # failing ledger write must roll the whole migration back — a
            # dangling open tx would swallow the NEXT statement on the shared
            # connection
            try:
                tx.execute(
                    f"INSERT INTO {LEDGER_TABLE} "
                    "(version, method, start_time, duration_ms) VALUES (?, ?, ?, ?)",
                    version, "UP",
                    time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(start)),
                    duration_ms)
                tx.commit()
            except Exception as e:
                try:
                    tx.rollback()
                except Exception:
                    pass
                log.error({"event": "migration ledger write failed",
                           "version": version, "error": repr(e)})
                raise MigrationError(
                    f"migration {version} ledger write failed: {e!r}") from e
        if redis is not None:
            # hash entry per reference redis.go:53-67
            import json as _json

            redis.hset(LEDGER_HASH, str(version), _json.dumps({
                "method": "UP",
                "startTime": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(start)),
                "duration_ms": duration_ms}))
        log.info({"event": "migration applied", "version": version,
                  "duration_ms": duration_ms})
