"""Per-request Context: request + container access behind one facade.

Reference: pkg/gofr/context.go:12-27 — Context embeds context.Context, the
transport-agnostic Request interface and *container.Container, plus Trace()
(:45) and Bind() (:53). Handlers receive exactly one of these regardless of
transport (HTTP, gRPC adapter, pub/sub message, CLI).
"""

from __future__ import annotations

import contextlib
from typing import Any

from .container import Container
from .errors import InternalServerError


class Context:
    def __init__(self, request: Any, container: Container, responder: Any = None):
        self.request = request
        self.container = container
        self._responder = responder

    # -- container facade ---------------------------------------------------
    @property
    def logger(self):
        return self.container.logger

    @property
    def metrics(self):
        return self.container.metrics

    @property
    def config(self):
        return self.container.config

    @property
    def redis(self):
        return self.container.redis

    @property
    def sql(self):
        return self.container.sql

    @property
    def tpu(self):
        """The TPU inference datasource — ``ctx.tpu.predict(...)``."""
        return self.container.tpu

    def get_http_service(self, name: str):
        return self.container.get_http_service(name)

    def get_publisher(self):
        return self.container.get_publisher()

    # -- request facade -----------------------------------------------------
    def param(self, key: str, default: str = "") -> str:
        return self.request.param(key, default)

    def path_param(self, key: str, default: str = "") -> str:
        return self.request.path_param(key, default)

    def bind(self, into: type | None = None) -> Any:
        """Deserialize the request body (reference context.go:53 Bind)."""
        return self.request.bind(into)

    def header(self, key: str, default: str = "") -> str:
        if hasattr(self.request, "header"):
            return self.request.header(key, default)
        return default

    @property
    def deadline(self):
        """The request's resilience.Deadline (parsed from
        ``X-Request-Timeout`` / gRPC ``grpc-timeout`` by the transport),
        or None. Ambient: ``ctx.tpu.predict``/``generate`` honor it
        without being passed it explicitly; read it here to budget your
        own work (``ctx.deadline.remaining()``)."""
        from .resilience import current_deadline

        return current_deadline()

    @property
    def slo_class(self) -> str:
        """The request's serving class (``latency`` default /
        ``throughput``), parsed from ``X-SLO-Class`` / gRPC
        ``slo-class`` by the transport. Ambient like the deadline:
        ``ctx.tpu.predict``/``generate`` pick it up automatically
        (docs/advanced-guide/serving-scheduler.md)."""
        from .resilience import current_slo_class

        return current_slo_class()

    @property
    def tenant(self) -> str:
        """The request's tenant id (``default`` when untagged), parsed
        from ``X-Tenant-Id`` / gRPC ``x-tenant-id`` by the transport and
        canonicalized through the tenant registry when one is
        configured. Ambient like the deadline and SLO class:
        ``ctx.tpu.predict``/``generate`` enforce the tenant's quota,
        fair-share weight and cache budget automatically
        (docs/advanced-guide/multi-tenancy.md)."""
        from .tenancy.registry import current_tenant

        return current_tenant()

    # -- streaming (no reference equivalent: the reference has no HTTP
    # streaming path; needed for token streaming over chunked responses) ----
    def stream(self, chunks, content_type: str = "application/x-ndjson") -> None:
        """Write an iterable of ``bytes`` chunks as a live chunked response.

            ctx.stream(json.dumps(x).encode() + b"\\n" for x in items)

        A push-capable source (``GenStream.map(encode)``) takes the
        zero-handoff fast path: each chunk is written by the PRODUCING
        thread (the TPU serving loop) without waking this handler
        thread — the same first-token latency fix as the gRPC
        ``ServerStream`` path."""
        if self._responder is None:
            raise InternalServerError(
                "streaming is only available on HTTP requests")
        w = self._responder.writer
        w.set_header("Content-Type", content_type)
        if hasattr(chunks, "set_sink"):
            w.stream_from(chunks)
            return
        for chunk in chunks:
            w.write_chunk(chunk)

    # -- tracing (reference context.go:45-51 Trace) --------------------------
    def trace(self, name: str):
        """Context manager opening a user span:

            with ctx.trace("expensive-work"):
                ...
        """
        tracer = self.container.tracer
        if tracer is None:
            return contextlib.nullcontext()
        return tracer.span(name)
