"""Multi-tenant serving plane: registry, fair queues, quotas, async lane.

One process, many tenants, many arrival paths. The pieces:

- :mod:`.registry` — ``TenantRegistry`` (hot-reloadable id -> spec
  table), ``tenant_scope``/``current_tenant`` ambient context,
  ``QuotaBook`` rps/concurrency quotas, and ``TenantPlane``, the wired
  enforcement object the engine carries.
- :mod:`.fair` — ``WeightedFairLine``, the deficit-round-robin
  per-tenant line nested inside each SLO class of the generator's
  pending queue.
- :mod:`.lane` — the pub/sub async inference consumer: bulk jobs in,
  tokens + resume checkpoints out to Redis, backpressured by the same
  admission gate as everything else.

Enable by pointing ``TPU_TENANTS`` at a registry JSON file (or
``TPU_TENANTS_INLINE`` at the document itself); without either, every
request is the anonymous default tenant and nothing here is on the
hot path.
"""

from .fair import WeightedFairLine
from .lane import AsyncLane, install_async_lane
from .registry import (
    DEFAULT_TENANT,
    QuotaBook,
    TenantPlane,
    TenantRegistry,
    TenantSpec,
    current_tenant,
    plane_from_config,
    tenant_scope,
)

__all__ = [
    "AsyncLane",
    "DEFAULT_TENANT",
    "QuotaBook",
    "TenantPlane",
    "TenantRegistry",
    "TenantSpec",
    "WeightedFairLine",
    "current_tenant",
    "install_async_lane",
    "plane_from_config",
    "tenant_scope",
]
