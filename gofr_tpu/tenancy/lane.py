"""The pub/sub async inference lane: bulk generation jobs as
throughput-class traffic.

The first non-HTTP arrival path for inference. Jobs are published to a
topic (``{"job_id": ..., "tokens": [...], "tenant": ..., ...}``), the
subscriber worker drains them through the SAME engine — same admission
gate, same batcher, same arbiter — as throughput-class traffic, and
writes tokens + resume checkpoints to Redis under ``async:{job_id}``.

Backpressure is admission, not memory: when the gate sheds (queue
depth, HBM pressure, tenant quota) the handler sleeps ``Retry-After``
and re-raises, the subscription manager skips the commit, and the
broker redelivers — at-least-once delivery IS the retry loop, so a
saturated replica slows the lane down instead of OOMing.

Checkpoints make redelivery cheap and exact: every ``checkpoint_every``
tokens the handler persists ``{"status": "running", "tokens": [...]}``;
a redelivered job (worker died, gate shed mid-run, replica restarted)
resumes via ``generate(continue_from=(prompt, emitted))`` — the warm
prefix cache covers prompt+emitted and only the tail recomputes, and
greedy/seeded sampling makes the continuation token-exact (the same
contract the durable-streams gateway resume rides). A job already
marked ``done`` commits immediately: results are idempotent.
"""

from __future__ import annotations

import json
import time

from ..errors import BadRequest, TooManyRequests
from ..resilience import SLO_THROUGHPUT, slo_scope
from ..wire import WAKE
from .registry import tenant_scope

__all__ = ["AsyncLane", "install_async_lane"]

DEFAULT_TOPIC = "inference-jobs"


class AsyncLane:
    """The subscriber-side consumer. One instance per App; register its
    ``handle`` with ``app.subscribe(topic, lane.handle)`` (or use
    :func:`install_async_lane`)."""

    def __init__(self, engine=None, *, store=None, checkpoint_every: int = 8,
                 retry_sleep_cap_s: float = 2.0, logger=None, metrics=None):
        self.engine = engine          # None -> ctx.tpu at handle time
        self.store = store            # None -> ctx.redis at handle time
        self.checkpoint_every = max(1, int(checkpoint_every))
        self.retry_sleep_cap_s = max(0.0, float(retry_sleep_cap_s))
        self.logger = logger
        self.metrics = metrics
        self.jobs_done = 0
        self.jobs_resumed = 0
        self.jobs_backpressured = 0

    # -- checkpoint store ----------------------------------------------------
    @staticmethod
    def _key(job_id: str) -> str:
        return f"async:{job_id}"

    def _load(self, store, job_id: str) -> dict | None:
        raw = store.get(self._key(job_id))
        if raw is None:
            return None
        if isinstance(raw, (bytes, bytearray)):
            raw = raw.decode("utf-8", "replace")
        try:
            doc = json.loads(raw)
        except ValueError:
            return None
        return doc if isinstance(doc, dict) else None

    def _save(self, store, job_id: str, status: str, tokens: list,
              tenant: str) -> None:
        store.set(self._key(job_id), json.dumps(
            {"status": status, "tokens": tokens, "tenant": tenant}))

    def _count(self, outcome: str) -> None:
        if self.metrics is None:
            return
        try:
            self.metrics.increment_counter("app_tpu_async_jobs_total",
                                           outcome=outcome)
        except Exception:
            pass  # telemetry must never take the lane down

    # -- the handler ---------------------------------------------------------
    def handle(self, ctx) -> None:
        job = ctx.bind()
        if not isinstance(job, dict) or not job.get("job_id") \
                or not isinstance(job.get("tokens"), list):
            raise BadRequest("async job must be JSON with 'job_id' and "
                             "a 'tokens' array")
        try:
            job_id = str(job["job_id"])
            tokens = [int(t) for t in job["tokens"]]
            tenant = str(job.get("tenant") or "") or None
            max_new = int(job.get("max_new", 16))
            temperature = float(job.get("temperature", 0.0) or 0.0)
            top_k = int(job.get("top_k", 0) or 0)
            adapter = int(job.get("adapter", 0) or 0)
            eos = job.get("eos")
            if isinstance(eos, list):
                eos = frozenset(int(t) for t in eos)
            elif eos is not None:
                eos = int(eos)
            seed = job.get("seed")
            seed = int(seed) if seed is not None else None
        except (TypeError, ValueError) as e:
            raise BadRequest(f"async job: malformed field: {e}") from e

        store = self.store if self.store is not None else ctx.redis
        if store is None:
            raise BadRequest(f"async job {job_id!r}: no result store "
                             "(Redis) configured")
        prior = self._load(store, job_id)
        if prior is not None and prior.get("status") == "done":
            self._count("dedup")
            return  # idempotent replay: commit without regenerating
        engine = self.engine if self.engine is not None else ctx.tpu
        if engine is None:
            raise BadRequest(f"async job {job_id!r}: no TPU engine "
                             "configured")
        emitted = [int(t) for t in (prior or {}).get("tokens", ())]
        continue_from = (tokens, emitted) if emitted else None
        if continue_from is not None:
            self.jobs_resumed += 1

        # jobs run as the job's tenant in the throughput lane — same
        # ambient channel the HTTP/gRPC edges use, so the gate, the
        # fair queue, and every per-tenant metric see this traffic
        with tenant_scope(tenant), slo_scope(SLO_THROUGHPUT):
            try:
                stream = engine.generate(
                    tokens, max_new_tokens=max_new,
                    temperature=temperature, top_k=top_k, eos_id=eos,
                    adapter=adapter, seed=seed,
                    continue_from=continue_from)
            except TooManyRequests as e:
                # admission backpressure: persist progress (a resumed
                # job keeps its emitted prefix), wait out Retry-After,
                # and leave the message uncommitted for redelivery
                self.jobs_backpressured += 1
                self._count("backpressured")
                if emitted:
                    self._save(store, job_id, "running", emitted, tenant
                               or "default")
                retry = float(getattr(e, "retry_after", 0.0) or 0.0)
                if retry > 0 and self.retry_sleep_cap_s > 0:
                    time.sleep(min(retry, self.retry_sleep_cap_s))
                raise
        since_save = 0
        try:
            for item in stream:
                if item is WAKE:
                    continue
                emitted.append(int(item[0] if isinstance(item, tuple)
                                   else item))
                since_save += 1
                if since_save >= self.checkpoint_every:
                    self._save(store, job_id, "running", emitted,
                               tenant or "default")
                    since_save = 0
        except BaseException:
            # mid-stream death: checkpoint what we have, then let the
            # redelivery resume token-exact from here
            try:
                self._save(store, job_id, "running", emitted,
                           tenant or "default")
            except Exception:
                pass
            self._count("interrupted")
            raise
        self._save(store, job_id, "done", emitted, tenant or "default")
        self.jobs_done += 1
        self._count("done")
        if self.logger is not None:
            self.logger.info({"event": "async job done", "job_id": job_id,
                              "tenant": tenant or "default",
                              "tokens": len(emitted),
                              "resumed": continue_from is not None})

    def stats(self) -> dict:
        return {"done": self.jobs_done, "resumed": self.jobs_resumed,
                "backpressured": self.jobs_backpressured}


def install_async_lane(app, topic: str | None = None, **kw) -> AsyncLane:
    """Register the async inference lane on an App's subscriber. The
    topic comes from ``TPU_TENANT_TOPIC`` (default ``inference-jobs``);
    checkpoint cadence from ``TPU_TENANT_CHECKPOINT_EVERY``."""
    topic = topic or app.config.get("TPU_TENANT_TOPIC") or DEFAULT_TOPIC
    kw.setdefault("checkpoint_every",
                  app.config.get_int("TPU_TENANT_CHECKPOINT_EVERY", 8))
    kw.setdefault("logger", app.logger)
    kw.setdefault("metrics", app.container.metrics)
    lane = AsyncLane(**kw)
    app.subscribe(topic, lane.handle)
    return lane
