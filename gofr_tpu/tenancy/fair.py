"""Deficit-round-robin line for per-tenant weighted fair scheduling.

``WeightedFairLine`` is a drop-in for the plain ``collections.deque``
each SLO class keeps inside the generator's ``_ClassPending``: same
``append`` / ``appendleft`` / ``popleft`` / ``__len__`` surface, but
internally one FIFO per tenant served deficit-round-robin over the
tenant's registry weight. Weights 2:1:1 under saturation pop
A, A, B, C, A, A, B, C, ... — deterministic, O(1) amortized, and a
tenant that isn't queued costs nothing (work-conserving: its unused
share flows to whoever is).

Cost is 1 per request (fairness over ADMISSION slots; decode-token
share then tracks queue weight because the batcher drains this line).
Quantum per round is the tenant's weight.

Locking: none here. The single owner (``_ClassPending``) already
serializes ``put`` / ``put_front`` / ``get_nowait`` under its own lock,
and the lock-free readers it exposes (``qsize`` et al.) only read
``len`` — ``_len`` is a plain int updated last, so those stay safe.

``appendleft`` exists for exactly one caller pattern: the batcher pops
a request, fails to place it (pool full / step budget), and pushes it
back to the FRONT. That undo must restore the pre-pop scheduler state
— same tenant at the head of the round with its pre-serve deficit —
or the retry loop would rotate the ring and break both fairness and
the FIFO-per-tenant ordering guarantee. We snapshot (tenant, deficit)
at each pop to make the undo exact.
"""

from __future__ import annotations

from collections import deque

__all__ = ["WeightedFairLine"]

_DEFAULT = "default"


def _tenant_of(req) -> str:
    # Requests predating tenancy (tests build them with object.__new__)
    # carry no tenant attribute: they all share the default line, which
    # collapses the scheduler to plain FIFO.
    return getattr(req, "tenant", None) or _DEFAULT


def _weight_of(req) -> int:
    try:
        return max(1, int(getattr(req, "tenant_weight", 1)))
    except (TypeError, ValueError):
        return 1


class WeightedFairLine:
    __slots__ = ("_lines", "_weight", "_deficit", "_order", "_len",
                 "_last")

    def __init__(self):
        self._lines: dict[str, deque] = {}
        self._weight: dict[str, int] = {}
        self._deficit: dict[str, float] = {}
        self._order: deque = deque()  # active tenants, round-robin ring
        self._len = 0
        self._last: tuple[str, float] | None = None  # pop undo snapshot

    # -- deque surface -------------------------------------------------------
    def append(self, req) -> None:
        tid = _tenant_of(req)
        self._weight[tid] = _weight_of(req)
        line = self._lines.get(tid)
        if line is None:
            line = self._lines[tid] = deque()
            self._order.append(tid)
            # a fresh arrival starts with one full quantum so it is
            # servable immediately and the first round already runs at
            # the configured ratio (2:1:1 pops A,A,B,C from pop one)
            self._deficit[tid] = self._weight[tid]
        line.append(req)
        self._len += 1

    def popleft(self):
        if self._len == 0:
            raise IndexError("pop from an empty WeightedFairLine")
        while True:
            tid = self._order[0]
            line = self._lines.get(tid)
            if not line:
                # stale head (emptied via an exceptional path): drop it
                self._order.popleft()
                self._lines.pop(tid, None)
                self._deficit.pop(tid, None)
                continue
            d = self._deficit[tid]
            if d < 1:
                d += self._weight.get(tid, 1)
                if d < 1:
                    # can't serve this round even after a refill (only
                    # possible with exotic weights); send to the back
                    self._deficit[tid] = d
                    self._order.rotate(-1)
                    continue
                self._deficit[tid] = d
            self._last = (tid, self._deficit[tid])
            self._deficit[tid] = d = self._deficit[tid] - 1
            req = line.popleft()
            self._len -= 1
            if not line:
                self._order.popleft()
                self._lines.pop(tid, None)
                self._deficit.pop(tid, None)
            elif d < 1:
                self._order.rotate(-1)
            return req

    def appendleft(self, req) -> None:
        """Front-of-line undo for the single-consumer pop/put_front
        contract: restores the request AND the scheduler position so
        the next popleft re-serves it from the same round state."""
        tid = _tenant_of(req)
        line = self._lines.get(tid)
        if line is None:
            line = self._lines[tid] = deque()
            self._deficit[tid] = self._weight.get(tid, 1)
        line.appendleft(req)
        self._len += 1
        self._weight.setdefault(tid, _weight_of(req))
        last = self._last
        if last is not None and last[0] == tid:
            # exact undo of the matching popleft: head of ring,
            # pre-serve deficit
            if self._order and self._order[0] == tid:
                pass
            elif tid in self._order:
                # popleft rotated us to the back; bring us home
                while self._order[0] != tid:
                    self._order.rotate(1)
            else:
                self._order.appendleft(tid)
            self._deficit[tid] = last[1]
            self._last = None
        elif tid not in self._order:
            self._order.appendleft(tid)

    def __len__(self) -> int:
        return self._len

    def __bool__(self) -> bool:
        return self._len > 0

    # -- introspection -------------------------------------------------------
    def by_tenant(self) -> dict[str, int]:
        return {tid: len(line) for tid, line in self._lines.items()
                if line}
