"""Tenant registry + ambient tenant scope + per-tenant quota book.

The registry is the one table every enforcement point reads: tenant id
-> LoRA adapter index, SLO-class default, queue weight (the DRR share
in the pending line), rps / concurrency quotas (AdmissionGate's
per-tenant bound), and cache-budget share (the T0 fraction the prefix
cache lets this tenant keep resident before its blocks evict first).

Resolution is transport-edge work: the HTTP middleware reads
``X-Tenant-Id``, the gRPC server reads ``x-tenant-id`` metadata, and
both open a ``tenant_scope`` — the same ambient threading-local channel
``deadline_scope``/``slo_scope`` ride, so ``generate()``/``predict()``
pick the tenant up without per-call plumbing. UNKNOWN ids resolve to
the default tenant's spec (shared line, shared quota): label
cardinality on every per-tenant metric series is bounded by the
registry, never by what clients send.

File-driven registries hot-reload on mtime (throttled): edit the JSON,
the next resolve() sees the new weights/quotas — no restart, same
contract as remote-log-level-change.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time

from ..errors import TooManyRequests
from ..resilience import SLO_LATENCY, parse_slo_class

__all__ = [
    "DEFAULT_TENANT",
    "QuotaBook",
    "TenantPlane",
    "TenantRegistry",
    "TenantSpec",
    "current_tenant",
    "plane_from_config",
    "tenant_scope",
]

DEFAULT_TENANT = "default"

_scope = threading.local()


def current_tenant() -> str:
    """The ambient tenant id opened by the transport for this handler
    thread (the default tenant outside any scope)."""
    return getattr(_scope, "tenant", None) or DEFAULT_TENANT


@contextlib.contextmanager
def tenant_scope(tenant: str | None):
    """Make ``tenant`` ambient for the calling thread. None keeps the
    enclosing scope's tenant (transports call this unconditionally); a
    nested explicit tenant WINS — e.g. the async lane re-tags the
    consumer thread per job."""
    prev = getattr(_scope, "tenant", None)
    _scope.tenant = tenant if tenant else (prev or DEFAULT_TENANT)
    try:
        yield _scope.tenant
    finally:
        _scope.tenant = prev


class TenantSpec:
    """One tenant's row: identity plus every enforcement knob. All
    quotas default OFF (0 = unlimited) — a registry that only names
    tenants still buys per-tenant fairness, metrics, and affinity."""

    __slots__ = ("tenant_id", "adapter", "slo_class", "weight", "rps",
                 "max_concurrency", "cache_share")

    def __init__(self, tenant_id: str, *, adapter: int = 0,
                 slo_class: str | None = None, weight: int = 1,
                 rps: float = 0.0, max_concurrency: int = 0,
                 cache_share: float = 0.0):
        self.tenant_id = str(tenant_id)
        self.adapter = max(0, int(adapter))
        # None = no class default; anything else normalizes through the
        # same alias table the X-SLO-Class header uses
        self.slo_class = parse_slo_class(slo_class) if slo_class else None
        self.weight = max(1, int(weight))
        self.rps = max(0.0, float(rps))
        self.max_concurrency = max(0, int(max_concurrency))
        self.cache_share = min(1.0, max(0.0, float(cache_share)))

    @classmethod
    def from_dict(cls, row: dict) -> "TenantSpec":
        tid = row.get("tenant_id") or row.get("id") or row.get("name")
        if not tid:
            raise ValueError("tenant row needs a tenant_id/id/name")
        return cls(tid, adapter=row.get("adapter", 0),
                   slo_class=row.get("slo_class"),
                   weight=row.get("weight", 1),
                   rps=row.get("rps", 0.0),
                   max_concurrency=row.get("max_concurrency", 0),
                   cache_share=row.get("cache_share", 0.0))

    def as_dict(self) -> dict:
        return {"tenant_id": self.tenant_id, "adapter": self.adapter,
                "slo_class": self.slo_class, "weight": self.weight,
                "rps": self.rps, "max_concurrency": self.max_concurrency,
                "cache_share": self.cache_share}


class TenantRegistry:
    """tenant id -> TenantSpec, with an always-present default spec.

    ``path`` makes the registry FILE-DRIVEN: the JSON document is
    ``{"tenants": [row, ...], "default": row?}`` and resolve() rechecks
    the file's mtime at most every ``reload_s`` seconds — a changed
    file swaps the whole table atomically (one dict assignment), so
    concurrent resolvers see either the old or the new registry, never
    a half-loaded one."""

    def __init__(self, specs=(), *, default: TenantSpec | None = None,
                 path: str | None = None, reload_s: float = 0.5,
                 logger=None):
        self.path = path
        self.reload_s = max(0.05, float(reload_s))
        self.logger = logger
        self.default = default or TenantSpec(DEFAULT_TENANT)
        self._specs: dict[str, TenantSpec] = {
            s.tenant_id: s for s in specs}
        self._mtime = 0.0
        self._next_check = 0.0
        self._reload_lock = threading.Lock()
        self.reloads = 0
        if path:
            self._reload(force=True)

    @classmethod
    def from_json(cls, doc, **kw) -> "TenantRegistry":
        if isinstance(doc, str):
            doc = json.loads(doc)
        specs = [TenantSpec.from_dict(r) for r in doc.get("tenants", ())]
        default = (TenantSpec.from_dict({"tenant_id": DEFAULT_TENANT,
                                         **doc["default"]})
                   if doc.get("default") else None)
        return cls(specs, default=default, **kw)

    def _reload(self, force: bool = False) -> None:
        with self._reload_lock:
            try:
                mtime = os.stat(self.path).st_mtime
            except OSError:
                return
            if not force and mtime == self._mtime:
                return
            try:
                with open(self.path, encoding="utf-8") as f:
                    doc = json.load(f)
                specs = {s.tenant_id: s for s in
                         (TenantSpec.from_dict(r)
                          for r in doc.get("tenants", ()))}
                default = (TenantSpec.from_dict(
                    {"tenant_id": DEFAULT_TENANT, **doc["default"]})
                    if doc.get("default") else TenantSpec(DEFAULT_TENANT))
            except (OSError, ValueError, KeyError, TypeError) as e:
                # a malformed edit must never take resolution down:
                # keep serving the last good table and say so
                if self.logger is not None:
                    self.logger.error({
                        "event": "tenant registry reload failed",
                        "path": self.path, "error": repr(e)})
                self._mtime = mtime  # don't re-parse the same bad file
                return
            self._specs = specs
            self.default = default
            if self._mtime and mtime != self._mtime:
                self.reloads += 1
                if self.logger is not None:
                    self.logger.info({
                        "event": "tenant registry reloaded",
                        "path": self.path, "tenants": len(specs)})
            self._mtime = mtime

    def _maybe_reload(self) -> None:
        if self.path is None:
            return
        now = time.monotonic()
        if now < self._next_check:
            return
        self._next_check = now + self.reload_s
        self._reload()

    def resolve(self, tenant_id: str | None) -> TenantSpec:
        """The spec for ``tenant_id``; unknown/absent ids get the
        DEFAULT spec (its canonical id, not the raw string — bounded
        metric-label cardinality is part of the contract)."""
        self._maybe_reload()
        if tenant_id:
            spec = self._specs.get(str(tenant_id).strip())
            if spec is not None:
                return spec
        return self.default

    def tenants(self) -> list[TenantSpec]:
        self._maybe_reload()
        return [*self._specs.values(), self.default]

    def __len__(self) -> int:
        return len(self._specs)

    def stats(self) -> dict:
        return {"tenants": sorted(self._specs),
                "path": self.path, "reloads": self.reloads}


class QuotaBook:
    """Per-tenant admission quotas: a token bucket per tenant for rps
    and a live concurrency count. ``check()`` CONSUMES on success (one
    token + one concurrency slot); the caller releases the slot at the
    request's terminal. One small lock; touched once per request, never
    per token."""

    def __init__(self):
        self._lock = threading.Lock()
        # tenant -> [tokens, last_refill_monotonic]
        self._buckets: dict[str, list] = {}
        self._active: dict[str, int] = {}

    def check(self, spec: TenantSpec) -> tuple[str | None, float]:
        """Try to admit one request for ``spec``'s tenant. Returns
        (None, 0) on admission (quota consumed), else
        (reason, retry_after_s) with NOTHING consumed."""
        tid = spec.tenant_id
        with self._lock:
            if spec.max_concurrency > 0 and \
                    self._active.get(tid, 0) >= spec.max_concurrency:
                return "concurrency", 0.25
            if spec.rps > 0:
                now = time.monotonic()
                cap = max(1.0, spec.rps)
                b = self._buckets.get(tid)
                if b is None:
                    b = self._buckets[tid] = [cap, now]
                tokens = min(cap, b[0] + (now - b[1]) * spec.rps)
                if tokens < 1.0:
                    b[0], b[1] = tokens, now
                    return "rps", max(0.05, (1.0 - tokens) / spec.rps)
                b[0], b[1] = tokens - 1.0, now
            self._active[tid] = self._active.get(tid, 0) + 1
            return None, 0.0

    def release(self, tenant_id: str) -> None:
        with self._lock:
            n = self._active.get(tenant_id, 0)
            if n > 1:
                self._active[tenant_id] = n - 1
            else:
                self._active.pop(tenant_id, None)

    def active(self, tenant_id: str) -> int:
        with self._lock:
            return self._active.get(tenant_id, 0)


class TenantPlane:
    """The wired-in enforcement plane: registry + quota book + the
    per-tenant telemetry faces. One per engine; every admission point
    (generate(), predict(), the async lane) calls ``admit``/``release``
    around the request, and the cache manager reads ``cache_shares``
    for its per-tenant T0 budgets."""

    def __init__(self, registry: TenantRegistry, *, metrics=None,
                 logger=None):
        self.registry = registry
        self.quotas = QuotaBook()
        self.metrics = metrics
        self.logger = logger
        self._lock = threading.Lock()
        self._admitted: dict[str, int] = {}
        self._shed: dict[str, int] = {}

    # -- resolution ----------------------------------------------------------
    def resolve(self, tenant_id: str | None) -> TenantSpec:
        return self.registry.resolve(tenant_id)

    def effective_class(self, spec: TenantSpec, slo_class: str) -> str:
        """The tenant's registry default applies when the request
        arrived UNTAGGED (which resolves to latency, the global
        default) — an explicit throughput tag always stands, and a
        throughput-default tenant opts its whole traffic into the batch
        lane without touching clients."""
        if spec.slo_class is not None and slo_class == SLO_LATENCY:
            return spec.slo_class
        return slo_class

    def effective_adapter(self, spec: TenantSpec, adapter: int) -> int:
        """Registry-driven LoRA routing: a request that did not pick an
        adapter (0, the base model) gets the tenant's fine-tune."""
        return spec.adapter if not adapter else adapter

    def cache_shares(self) -> dict[str, float]:
        return {s.tenant_id: s.cache_share
                for s in self.registry.tenants() if s.cache_share > 0}

    def weight(self, tenant_id: str) -> int:
        return self.registry.resolve(tenant_id).weight

    # -- admission -----------------------------------------------------------
    def admit(self, spec: TenantSpec, program: str = "",
              slo_class: str = SLO_LATENCY, gate=None) -> None:
        """Per-tenant quota admission: over-quota raises
        ``TooManyRequests`` with ``reason=tenant_quota`` — a 429 scoped
        to THIS tenant, never a global shed. With a gate, the shed
        routes through its one bookkeeping path (counters + tpu.shed
        marker span); without one, quota enforcement still runs."""
        tid = spec.tenant_id
        try:
            if gate is not None:
                gate.admit_tenant(spec, self.quotas, program=program,
                                  slo_class=slo_class)
            else:
                why, retry_after = self.quotas.check(spec)
                if why is not None:
                    raise TooManyRequests(
                        f"tenant {tid!r} over {why} quota — shed "
                        f"({slo_class})",
                        retry_after=max(0.05, retry_after),
                        reason="tenant_quota")
        except TooManyRequests:
            with self._lock:
                self._shed[tid] = self._shed.get(tid, 0) + 1
            self._gauge("app_tpu_tenant_shed", self._shed.get(tid, 0), tid)
            raise
        with self._lock:
            self._admitted[tid] = self._admitted.get(tid, 0) + 1
        self._gauge("app_tpu_tenant_admitted",
                    self._admitted.get(tid, 0), tid)

    def release(self, tenant_id: str | None) -> None:
        self.quotas.release(tenant_id or DEFAULT_TENANT)

    # -- telemetry -----------------------------------------------------------
    def _gauge(self, name: str, value: float, tenant: str) -> None:
        if self.metrics is None:
            return
        try:
            self.metrics.set_gauge(name, float(value), tenant=tenant)
        except Exception:
            pass  # telemetry must never take the serving loop down

    def note_cache_bytes(self, tenant: str, nbytes: int) -> None:
        self._gauge("app_tpu_tenant_cache_bytes", float(nbytes), tenant)

    def stats(self) -> dict:
        with self._lock:
            admitted = dict(self._admitted)
            shed = dict(self._shed)
        tenants = {}
        for s in self.registry.tenants():
            tid = s.tenant_id
            tenants[tid] = {
                "weight": s.weight,
                "adapter": s.adapter,
                "slo_class": s.slo_class,
                "rps": s.rps,
                "max_concurrency": s.max_concurrency,
                "cache_share": s.cache_share,
                "admitted": admitted.get(tid, 0),
                "shed": shed.get(tid, 0),
                "active": self.quotas.active(tid),
            }
        return {"registry": self.registry.stats(), "tenants": tenants}


def plane_from_config(cfg, metrics=None, logger=None) -> TenantPlane | None:
    """Build the serving plane from ``TPU_TENANTS`` (path to a
    hot-reloadable JSON registry file) or ``TPU_TENANTS_INLINE`` (the
    same document inline, static). Returns None when neither is set —
    tenancy is opt-in and costs nothing when off."""
    path = cfg.get("TPU_TENANTS") or ""
    inline = cfg.get("TPU_TENANTS_INLINE") or ""
    if not path and not inline:
        return None
    try:
        if path:
            registry = TenantRegistry(
                path=path, logger=logger,
                reload_s=cfg.get_float("TPU_TENANTS_RELOAD_S", 0.5))
        else:
            registry = TenantRegistry.from_json(inline, logger=logger)
    except (OSError, ValueError, KeyError, TypeError) as e:
        if logger is not None:
            logger.error({"event": "tenant registry config invalid",
                          "error": repr(e)})
        return None
    return TenantPlane(registry, metrics=metrics, logger=logger)
