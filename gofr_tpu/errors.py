"""Framework error hierarchy.

The reference maps handler errors to HTTP statuses in
pkg/gofr/http/responder.go:47-57 (nil -> 200, ErrorEntityNotFound -> 404,
else -> 500). Here the mapping is carried by the exception itself: any
handler may raise ``HTTPError`` (or subclass) with an explicit status;
unexpected exceptions become 500s in the recovery middleware
(reference pkg/gofr/http/middleware/logger.go:94-117).
"""

from __future__ import annotations

import math


def format_retry_after(seconds: float) -> str:
    """The one Retry-After wire formatter (HTTP header, gRPC trailer,
    drain responses): delta-seconds per RFC 9110 §10.2.3, ceiling so a
    90.4 s estimate never under-advises as 90, floored at 1 because 0
    invites an instant retry storm."""
    return str(max(1, math.ceil(seconds)))


def parse_retry_after(value: str | None) -> float | None:
    """The one Retry-After reader (retry decorator, gateway relay and
    replica table): delta-seconds to a non-negative float; ``None``
    for absent, garbage, or the HTTP-date form (rare — callers fall
    back to their own backoff/jitter)."""
    try:
        return max(0.0, float(value)) if value else None
    except (TypeError, ValueError):
        return None


class GofrError(Exception):
    """Base class for all framework errors."""


class HTTPError(GofrError):
    """An error with an explicit HTTP status code."""

    status_code: int = 500

    def __init__(self, message: str = "", status_code: int | None = None):
        super().__init__(message or self.__class__.__name__)
        if status_code is not None:
            self.status_code = status_code
        self.message = message or self.__class__.__name__

    def to_dict(self) -> dict:
        return {"message": self.message}


class BadRequest(HTTPError):
    status_code = 400


class Unauthorized(HTTPError):
    status_code = 401


class Forbidden(HTTPError):
    status_code = 403


class NotFound(HTTPError):
    status_code = 404


class EntityNotFound(NotFound):
    """Reference: pkg/gofr/http/errors.go ErrorEntityNotFound -> 404."""

    def __init__(self, name: str = "entity", value: str = ""):
        super().__init__(f"No {name} found for value {value!r}")
        self.name = name
        self.value = value


class ProgramNotFound(NotFound, KeyError):
    """An inference request named a TPU program the engine never
    registered -> 404 with the known-program list, instead of the raw
    500 a bare KeyError becomes. Subclasses KeyError so callers doing
    dict-style lookup-miss handling keep working."""

    def __init__(self, program: str, registered: list[str] | None = None):
        known = f"; registered: {sorted(registered)}" if registered else ""
        super().__init__(f"no TPU program {program!r}{known}")
        self.program = program

    # KeyError.__str__ repr()s the message (dict-miss convention);
    # wire errors must render the plain text
    __str__ = Exception.__str__


class InvalidParameter(BadRequest):
    def __init__(self, *params: str):
        super().__init__(f"Invalid parameter(s): {', '.join(params)}")
        self.params = params


class MissingParameter(BadRequest):
    def __init__(self, *params: str):
        super().__init__(f"Missing parameter(s): {', '.join(params)}")
        self.params = params


class ShardingConfigError(GofrError, ValueError):
    """A mesh/sharding configuration the engine refuses to serve with —
    raised at engine construction, before any request is accepted.
    Names the offending ``TPU_SHARDING`` row so the operator can fix
    the config line rather than chase wrong logits: the known case is a
    tp that splits a KV head (n_kv_heads % tp != 0) combined with
    dp/fsdp > 1, a VERIFIED wrong-logits hazard (see
    docs/advanced-guide/multichip-serving.md "known limits").
    Subclasses ValueError so config-validation callers that catch
    ValueError keep working."""

    def __init__(self, message: str, sharding_row: str = ""):
        super().__init__(message)
        self.sharding_row = sharding_row


class InternalServerError(HTTPError):
    status_code = 500


class ServiceUnavailable(HTTPError):
    status_code = 503


class TooManyRequests(HTTPError):
    """Shed by an admission gate (resilience.AdmissionGate): the queue is
    over its configured bound, so the request fails FAST instead of
    joining a line that would blow its own latency budget. Carries the
    gate's wait estimate as ``Retry-After`` (the responder emits
    ``headers``; the gRPC transport maps 429 -> RESOURCE_EXHAUSTED).

    ``reason`` types the PRESSURE KIND on the wire as an
    ``X-Shed-Reason`` header (``hbm`` for arbiter memory sheds; absent
    means queue pressure) — a cross-process peer (the prefix-affinity
    gateway) balances a memory-shedding replica differently from a
    queue-deep one, and the header is the contract that distinction
    survives the hop on (parsing error-message prose would not)."""

    status_code = 429

    def __init__(self, message: str = "", retry_after: float | None = None,
                 reason: str | None = None):
        super().__init__(message or "too many requests")
        self.retry_after = retry_after
        self.reason = reason
        self.headers: dict[str, str] = {}
        if retry_after is not None:
            self.headers["Retry-After"] = format_retry_after(retry_after)
        if reason:
            self.headers["X-Shed-Reason"] = reason


class DeadlineExceeded(HTTPError):
    """The caller's deadline (gRPC ``grpc-timeout`` / HTTP
    ``X-Request-Timeout``) expired before the work completed — including
    while still queued, in which case the dispatcher dropped the item
    without ever executing it (resilience.md). 504 on HTTP; the gRPC
    transport maps it to DEADLINE_EXCEEDED."""

    status_code = 504

    def __init__(self, message: str = "deadline exceeded"):
        super().__init__(message)


class ConnectionLost(HTTPError, EOFError):
    """A transport peer vanished mid-exchange — socket closed, GOAWAY,
    half-read frame. 502 on HTTP (the upstream died, not us).
    Subclasses EOFError because EOFError is this repo's long-standing
    transport-loss sentinel: every ``except (EOFError, OSError)`` arm
    in wire/grpcx/pd keeps catching it unchanged."""

    status_code = 502

    def __init__(self, message: str = "connection lost"):
        super().__init__(message)


class CircuitOpenError(ServiceUnavailable):
    """Raised by the client-side circuit breaker while open
    (reference: pkg/gofr/service/circuit_breaker.go ErrCircuitOpen)."""

    def __init__(self, address: str = "") -> None:
        suffix = f" for {address}" if address else ""
        super().__init__(f"circuit breaker is open{suffix}")
        self.address = address


def status_from_error(err: BaseException | None) -> int:
    """Map an exception to an HTTP status (reference responder.go:47-57)."""
    if err is None:
        return 200
    if isinstance(err, HTTPError):
        return err.status_code
    return 500
