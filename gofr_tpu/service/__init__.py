"""Inter-service HTTP client with decorator options.

Reference: pkg/gofr/service/ —
  - ``HTTP`` interface with Get/Post/Put/Patch/Delete ± headers
    (service/new.go:26-64)
  - ``NewHTTPService(addr, logger, metrics, options...)`` building a
    decorator chain inside-out (service/new.go:68-87, options applied at
    new.go:82-84 via Options.addOption, service/options.go:3)
  - circuit breaker (service/circuit_breaker.go), auth decorators
    (basic_auth.go / apikey_auth.go / oauth.go), health override
    (health_config.go)

Decorators here are small wrappers satisfying the same client surface, so
any combination composes: ``new_http_service(addr, log, metrics,
CircuitBreakerOption(...), BasicAuthOption(...), HealthOption(...))``.
"""

from .client import HTTPService, Response, new_http_service, stream_generate
from .circuit_breaker import CircuitBreaker, CircuitBreakerOption, CircuitOpenError
from .reconnect import ReconnectBackoff
from .retry import Retry, RetryOption
from .auth import APIKeyAuthOption, BasicAuthOption, OAuthOption
from .health import DEFAULT_HEALTH_ENDPOINT, HealthOption

__all__ = [
    "HTTPService",
    "Response",
    "new_http_service",
    "stream_generate",
    "CircuitBreaker",
    "CircuitBreakerOption",
    "CircuitOpenError",
    "ReconnectBackoff",
    "Retry",
    "RetryOption",
    "BasicAuthOption",
    "APIKeyAuthOption",
    "OAuthOption",
    "HealthOption",
    "DEFAULT_HEALTH_ENDPOINT",
]
