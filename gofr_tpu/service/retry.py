"""Retry-with-backoff decorator for the outbound HTTP service client.

No reference equivalent (the reference's resilience decorator is the
circuit breaker only). Policy follows the AWS-style "full jitter"
discipline: attempt ``i`` sleeps ``U[0, min(max_delay, base * 2**i))``,
which decorrelates a thundering herd better than equal-jitter or
fixed-exponential; a server-supplied ``Retry-After`` (shed/drain
backpressure from resilience.AdmissionGate or a draining peer) OVERRIDES
the computed backoff — the server knows its queue better than we do —
bounded only by ``retry_after_cap`` (default 30 s, so a buggy header
can't park the caller) and the caller's ambient deadline.

What retries:
  - connection errors and timeouts, for IDEMPOTENT methods only by
    default (GET/HEAD/PUT/DELETE/OPTIONS — RFC 9110 §9.2.2; a POST that
    died mid-flight may have committed);
  - retryable statuses (default 429/502/503/504) for idempotent methods
    (``retry_non_idempotent=True`` opts POSTs in when the caller knows
    the endpoint is safe to replay).

Composition with the circuit breaker: order the options so the breaker
wraps the retrier —

    new_http_service(addr, log, metrics,
                     RetryOption(max_attempts=3),
                     CircuitBreakerOption(threshold=5))

options apply inside-out, so the LAST option is the OUTERMOST wrapper.
With the breaker outside, one logical call counts as ONE breaker
failure no matter how many attempts the retrier burned (N quick
failures must not slam the breaker open N times as fast), and while the
circuit is open ``CircuitOpenError`` fires before any attempt is made.
If the retrier ends up outside a breaker anyway, it refuses to retry
``CircuitOpenError`` — hammering an open circuit defeats both.

The ambient request deadline (resilience.current_deadline) is honored:
no retry starts if its backoff sleep would outlive the caller's budget.
"""

from __future__ import annotations

import random
import time

from ..errors import CircuitOpenError, parse_retry_after
from ..resilience import current_deadline
from .wrap import ServiceWrapper

IDEMPOTENT_METHODS = frozenset({"GET", "HEAD", "PUT", "DELETE", "OPTIONS"})
DEFAULT_RETRY_STATUSES = (429, 502, 503, 504)


class Retry(ServiceWrapper):
    #: ceiling on a server-supplied Retry-After (seconds): the hint is
    #: honored past max_delay — the server knows its queue — but bounded
    #: so a buggy/hostile header can't park the caller indefinitely
    RETRY_AFTER_CAP = 30.0

    def __init__(self, inner, max_attempts: int = 3, base_delay: float = 0.1,
                 max_delay: float = 2.0,
                 retry_statuses=DEFAULT_RETRY_STATUSES,
                 retry_non_idempotent: bool = False,
                 rng: random.Random | None = None, sleep=time.sleep,
                 retry_after_cap: float | None = None):
        super().__init__(inner)
        self.max_attempts = max(1, int(max_attempts))
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.retry_after_cap = (self.RETRY_AFTER_CAP if retry_after_cap is None
                                else float(retry_after_cap))
        self.retry_statuses = frozenset(int(s) for s in retry_statuses)
        self.retry_non_idempotent = retry_non_idempotent
        # injectable rng/sleep: deterministic jitter under test/chaos
        self._rng = rng or random.Random()
        self._sleep = sleep
        self.retries = 0  # attempts beyond the first, across all calls

    def _may_retry(self, method: str) -> bool:
        return (method.upper() in IDEMPOTENT_METHODS
                or self.retry_non_idempotent)

    def _backoff(self, attempt: int, retry_after: float | None) -> float:
        if retry_after is not None:
            # the server's hint beats the computed backoff, even past
            # max_delay (a draining peer saying "30" means 30) — bounded
            # only by retry_after_cap and the caller's ambient deadline
            return min(retry_after, self.retry_after_cap)
        cap = min(self.max_delay, self.base_delay * (2 ** attempt))
        return self._rng.uniform(0.0, cap)  # full jitter

    def _pause(self, delay: float) -> bool:
        """Sleep before the next attempt — unless it would outlive the
        caller's ambient deadline (then stop retrying: the caller will
        time out before the retry could answer). The deadline caps the
        CUMULATIVE retry loop, not just this sleep: the ambient
        ``Deadline`` is absolute, so each pass re-reads the shrinking
        budget (attempt time included — the transport tightens its own
        socket timeout to the same budget, client.py ``_do``), and the
        loop can never outlive the caller by more than one bounded
        attempt."""
        dl = current_deadline()
        if dl is not None and dl.remaining() <= delay:
            return False
        if delay > 0:
            self._sleep(delay)
        return True

    @staticmethod
    def _retry_after(resp) -> float | None:
        val = resp.header("Retry-After") if hasattr(resp, "header") else ""
        return parse_retry_after(val)

    def _do(self, method, path, params, body, headers):
        last_exc: BaseException | None = None
        resp = None
        for attempt in range(self.max_attempts):
            if attempt > 0:
                self.retries += 1
            try:
                resp = super()._do(method, path, params, body, headers)
            except CircuitOpenError:
                raise  # never hammer an open circuit (see module doc)
            except Exception as e:  # noqa: BLE001 — transport failures
                last_exc = e
                if (attempt + 1 >= self.max_attempts
                        or not self._may_retry(method)
                        or not self._pause(self._backoff(attempt, None))):
                    raise
                continue
            status = getattr(resp, "status_code", 0)
            if (status in self.retry_statuses
                    and self._may_retry(method)
                    and attempt + 1 < self.max_attempts
                    and self._pause(
                        self._backoff(attempt, self._retry_after(resp)))):
                continue
            return resp
        if last_exc is not None:  # pragma: no cover - loop always returns/raises
            raise last_exc
        return resp


class RetryOption:
    """Applied via new_http_service(...) like every other option. Place
    it BEFORE CircuitBreakerOption in the argument list so the breaker
    ends up outermost (options wrap inside-out)."""

    def __init__(self, max_attempts: int = 3, base_delay: float = 0.1,
                 max_delay: float = 2.0,
                 retry_statuses=DEFAULT_RETRY_STATUSES,
                 retry_non_idempotent: bool = False,
                 rng: random.Random | None = None,
                 retry_after_cap: float | None = None):
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.retry_statuses = retry_statuses
        self.retry_non_idempotent = retry_non_idempotent
        self.rng = rng
        self.retry_after_cap = retry_after_cap

    def add_option(self, svc):
        return Retry(svc, self.max_attempts, self.base_delay, self.max_delay,
                     retry_statuses=self.retry_statuses,
                     retry_non_idempotent=self.retry_non_idempotent,
                     rng=self.rng, retry_after_cap=self.retry_after_cap)
