"""Shared decorator plumbing for service options.

The reference re-implements the 10-verb surface in every decorator
(e.g. service/basic_auth.go:46-125, circuit_breaker.go:171-269). Here a
single base class forwards every verb through one ``_do`` choke point, so
each decorator overrides exactly one method.
"""

from __future__ import annotations

from typing import Any, Mapping


def set_header_default(headers: dict, key: str, value: str) -> None:
    """setdefault with case-insensitive key matching — a caller-supplied
    'authorization' must win over a decorator's 'Authorization'."""
    lower = key.lower()
    if any(k.lower() == lower for k in headers):
        return
    headers[key] = value


class VerbSurface:
    """The 10-verb client surface, all flowing through one ``_do`` choke
    point. Shared by the innermost HTTPService and every decorator so the
    verb list exists exactly once."""

    def _do(self, method: str, path: str, params, body, headers) -> Any:
        raise NotImplementedError

    def get(self, path: str, params: Mapping[str, Any] | None = None):
        return self._do("GET", path, params, None, None)

    def get_with_headers(self, path, params=None, headers=None):
        return self._do("GET", path, params, None, headers)

    def post(self, path: str, params=None, body=b""):
        return self._do("POST", path, params, body, None)

    def post_with_headers(self, path, params=None, body=b"", headers=None):
        return self._do("POST", path, params, body, headers)

    def put(self, path: str, params=None, body=b""):
        return self._do("PUT", path, params, body, None)

    def put_with_headers(self, path, params=None, body=b"", headers=None):
        return self._do("PUT", path, params, body, headers)

    def patch(self, path: str, params=None, body=b""):
        return self._do("PATCH", path, params, body, None)

    def patch_with_headers(self, path, params=None, body=b"", headers=None):
        return self._do("PATCH", path, params, body, headers)

    def delete(self, path: str, body=b""):
        return self._do("DELETE", path, None, body, None)

    def delete_with_headers(self, path, body=b"", headers=None):
        return self._do("DELETE", path, None, body, headers)


class ServiceWrapper(VerbSurface):
    def __init__(self, inner):
        self.inner = inner

    def _do(self, method: str, path: str, params, body, headers) -> Any:
        return _dispatch(self.inner, method, path, params, body, headers)

    def health_check(self):
        return self.inner.health_check()

    def close(self) -> None:
        self.inner.close()

    def __getattr__(self, name: str):
        # delegate state inspection (is_open, address, timeout, ...) through
        # the decorator chain so wrapping order never hides it
        return getattr(self.inner, name)


def _dispatch(svc, method: str, path: str, params, body, headers):
    """Call the matching ``*_with_headers`` verb on any client layer."""
    m = method.upper()
    if m == "GET":
        return svc.get_with_headers(path, params, headers)
    if m == "POST":
        return svc.post_with_headers(path, params, body, headers)
    if m == "PUT":
        return svc.put_with_headers(path, params, body, headers)
    if m == "PATCH":
        return svc.patch_with_headers(path, params, body, headers)
    if m == "DELETE":
        return svc.delete_with_headers(path, body, headers)
    raise ValueError(f"unsupported method {method!r}")
