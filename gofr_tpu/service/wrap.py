"""Shared decorator plumbing for service options.

The reference re-implements the 10-verb surface in every decorator
(e.g. service/basic_auth.go:46-125, circuit_breaker.go:171-269). Here a
single base class forwards every verb through one ``_do`` choke point, so
each decorator overrides exactly one method.
"""

from __future__ import annotations

from typing import Any, Mapping


def set_header_default(headers: dict, key: str, value: str) -> None:
    """setdefault with case-insensitive key matching — a caller-supplied
    'authorization' must win over a decorator's 'Authorization'."""
    lower = key.lower()
    if any(k.lower() == lower for k in headers):
        return
    headers[key] = value


def hop_context(headers: dict, timeout: float | None = None) -> float | None:
    """Apply the ambient request context to an outbound hop, in ONE
    place for every cross-process client (HTTPService and the gateway
    relay): the SLO class rides ``X-SLO-Class`` so per-class accounting
    survives the hop (only a non-default class is worth a header byte —
    absent means latency on both sides), and the remaining deadline
    rides ``X-Request-Timeout`` so the peer's budget is the CALLER's
    remaining budget, not a fresh one. Returns ``timeout`` tightened to
    that same remaining budget — with the retry decorator's pause
    check this is what keeps a retry loop from outliving the caller."""
    from ..resilience import SLO_LATENCY, current_deadline, current_slo_class

    slo = current_slo_class()
    if slo != SLO_LATENCY:
        set_header_default(headers, "X-SLO-Class", slo)
    dl = current_deadline()
    if dl is not None:
        set_header_default(headers, "X-Request-Timeout",
                           f"{max(dl.remaining(), 0.001):.6f}s")
        if timeout is not None:
            timeout = max(0.05, dl.budget(timeout))
    return timeout


class VerbSurface:
    """The 10-verb client surface, all flowing through one ``_do`` choke
    point. Shared by the innermost HTTPService and every decorator so the
    verb list exists exactly once."""

    def _do(self, method: str, path: str, params, body, headers) -> Any:
        raise NotImplementedError

    def get(self, path: str, params: Mapping[str, Any] | None = None):
        return self._do("GET", path, params, None, None)

    def get_with_headers(self, path, params=None, headers=None):
        return self._do("GET", path, params, None, headers)

    def post(self, path: str, params=None, body=b""):
        return self._do("POST", path, params, body, None)

    def post_with_headers(self, path, params=None, body=b"", headers=None):
        return self._do("POST", path, params, body, headers)

    def put(self, path: str, params=None, body=b""):
        return self._do("PUT", path, params, body, None)

    def put_with_headers(self, path, params=None, body=b"", headers=None):
        return self._do("PUT", path, params, body, headers)

    def patch(self, path: str, params=None, body=b""):
        return self._do("PATCH", path, params, body, None)

    def patch_with_headers(self, path, params=None, body=b"", headers=None):
        return self._do("PATCH", path, params, body, headers)

    def delete(self, path: str, body=b""):
        return self._do("DELETE", path, None, body, None)

    def delete_with_headers(self, path, body=b"", headers=None):
        return self._do("DELETE", path, None, body, headers)


class ServiceWrapper(VerbSurface):
    def __init__(self, inner):
        self.inner = inner

    def _do(self, method: str, path: str, params, body, headers) -> Any:
        return _dispatch(self.inner, method, path, params, body, headers)

    def health_check(self):
        return self.inner.health_check()

    def close(self) -> None:
        self.inner.close()

    def __getattr__(self, name: str):
        # delegate state inspection (is_open, address, timeout, ...) through
        # the decorator chain so wrapping order never hides it
        return getattr(self.inner, name)


def _dispatch(svc, method: str, path: str, params, body, headers):
    """Call the matching ``*_with_headers`` verb on any client layer."""
    m = method.upper()
    if m == "GET":
        return svc.get_with_headers(path, params, headers)
    if m == "POST":
        return svc.post_with_headers(path, params, body, headers)
    if m == "PUT":
        return svc.put_with_headers(path, params, body, headers)
    if m == "PATCH":
        return svc.patch_with_headers(path, params, body, headers)
    if m == "DELETE":
        return svc.delete_with_headers(path, body, headers)
    from ..errors import BadRequest
    raise BadRequest(f"unsupported method {method!r}")
