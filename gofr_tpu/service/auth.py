"""Client auth decorators: basic, API key, OAuth2 client-credentials.

Reference: pkg/gofr/service/basic_auth.go:9-40 (pre-encoded password),
apikey_auth.go:8-85 (X-API-KEY header), oauth.go:15-67 (client-credentials
token source injecting Bearer tokens). Each wraps the verb surface adding
one header — here via ServiceWrapper._do.
"""

from __future__ import annotations

import base64
import json
import threading
import time
import urllib.parse
import urllib.request

from .wrap import ServiceWrapper, set_header_default


class BasicAuth(ServiceWrapper):
    def __init__(self, inner, username: str, password: str):
        super().__init__(inner)
        token = base64.b64encode(f"{username}:{password}".encode()).decode()
        self._header = f"Basic {token}"

    def _do(self, method, path, params, body, headers):
        headers = dict(headers or {})
        set_header_default(headers, "Authorization", self._header)
        return super()._do(method, path, params, body, headers)


class BasicAuthOption:
    def __init__(self, username: str, password: str):
        self.username, self.password = username, password

    def add_option(self, svc):
        return BasicAuth(svc, self.username, self.password)


class APIKeyAuth(ServiceWrapper):
    def __init__(self, inner, api_key: str, header_name: str = "X-API-KEY"):
        super().__init__(inner)
        self.api_key = api_key
        self.header_name = header_name

    def _do(self, method, path, params, body, headers):
        headers = dict(headers or {})
        set_header_default(headers, self.header_name, self.api_key)
        return super()._do(method, path, params, body, headers)


class APIKeyAuthOption:
    def __init__(self, api_key: str, header_name: str = "X-API-KEY"):
        self.api_key, self.header_name = api_key, header_name

    def add_option(self, svc):
        return APIKeyAuth(svc, self.api_key, self.header_name)


class _TokenSource:
    """Client-credentials token fetcher with expiry-aware caching
    (reference oauth.go:15-44 wraps clientcredentials.Config)."""

    def __init__(self, token_url: str, client_id: str, client_secret: str,
                 scopes: tuple[str, ...] = (), fetch=None):
        self.token_url = token_url
        self.client_id = client_id
        self.client_secret = client_secret
        self.scopes = scopes
        self._fetch = fetch or self._fetch_http
        self._token: str | None = None
        self._expires_at = 0.0
        self._lock = threading.Lock()

    def _fetch_http(self) -> dict:
        form = {"grant_type": "client_credentials",
                "client_id": self.client_id,
                "client_secret": self.client_secret}
        if self.scopes:
            form["scope"] = " ".join(self.scopes)
        req = urllib.request.Request(
            self.token_url, data=urllib.parse.urlencode(form).encode(),
            headers={"Content-Type": "application/x-www-form-urlencoded"})
        with urllib.request.urlopen(req, timeout=10.0) as resp:
            return json.loads(resp.read())

    def token(self) -> str:
        with self._lock:
            now = time.monotonic()
            if self._token is None or now >= self._expires_at:
                payload = self._fetch()
                self._token = payload["access_token"]
                # refresh 30s before expiry; default 1h if server omits it
                ttl = float(payload.get("expires_in", 3600))
                self._expires_at = now + max(ttl - 30.0, 1.0)
            return self._token


class OAuth(ServiceWrapper):
    def __init__(self, inner, source: _TokenSource):
        super().__init__(inner)
        self.source = source

    def _do(self, method, path, params, body, headers):
        headers = dict(headers or {})
        set_header_default(headers, "Authorization", f"Bearer {self.source.token()}")
        return super()._do(method, path, params, body, headers)


class OAuthOption:
    def __init__(self, token_url: str, client_id: str, client_secret: str,
                 scopes: tuple[str, ...] = (), fetch=None):
        self.source = _TokenSource(token_url, client_id, client_secret, scopes, fetch)

    def add_option(self, svc):
        return OAuth(svc, self.source)
