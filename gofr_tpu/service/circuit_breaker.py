"""Client-side circuit breaker.

Reference: pkg/gofr/service/circuit_breaker.go —
  - two states Closed/Open (circuit_breaker.go:12-15)
  - consecutive-failure count reaching ``threshold`` opens the circuit
    (executeWithCircuitBreaker, :57-88)
  - while open: background ticker health-checks the target (:106-118) and
    an inline recovery probe is allowed once ``interval`` has elapsed
    (:149-156); a successful probe closes the circuit
  - wraps every verb (:171-269) — here via ServiceWrapper._do
"""

from __future__ import annotations

import threading
import time

from ..errors import CircuitOpenError
from .wrap import ServiceWrapper

CLOSED, OPEN = 0, 1

__all__ = ["CircuitBreaker", "CircuitBreakerOption", "CircuitOpenError"]


def _orderly_drain(resp) -> bool:
    """A 503 carrying Retry-After is the drain contract's readiness
    answer — a live peer asking for patience, not a dead one."""
    return (getattr(resp, "status_code", 0) == 503
            and hasattr(resp, "header")
            and bool(resp.header("Retry-After")))


class CircuitBreaker(ServiceWrapper):
    def __init__(self, inner, threshold: int = 5, interval: float = 10.0,
                 start_background_probe: bool = True):
        super().__init__(inner)
        self.threshold = max(1, threshold)
        self.interval = interval
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._last_probe = 0.0
        self._lock = threading.Lock()
        self._probe_thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._start_background_probe = start_background_probe
        # the recovery probe's health source; a HealthOption applied later in
        # the chain re-points this at the custom endpoint (health.py)
        self.health_probe = lambda: self.inner.health_check()

    # -- state inspection ---------------------------------------------------
    @property
    def is_open(self) -> bool:
        with self._lock:
            return self._state == OPEN

    def _open(self) -> None:
        self._state = OPEN
        self._opened_at = time.monotonic()
        self._last_probe = 0.0
        if self._start_background_probe and (
                self._probe_thread is None or not self._probe_thread.is_alive()):
            self._stop.clear()
            self._probe_thread = threading.Thread(
                target=self._probe_loop, daemon=True,
                name=f"cb-probe-{getattr(self.inner, 'address', '')}")
            self._probe_thread.start()

    def _close_circuit(self) -> None:
        self._state = CLOSED
        self._failures = 0
        self._stop.set()

    # -- background recovery (reference :106-118) ----------------------------
    def _probe_loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                h = self.health_probe()
                healthy = getattr(h, "status", "DOWN") == "UP"
            except Exception:
                healthy = False
            if healthy:
                with self._lock:
                    self._close_circuit()
                return

    # -- the guarded call (reference :57-88, :149-156) -----------------------
    def _do(self, method, path, params, body, headers):
        with self._lock:
            if self._state == OPEN:
                now = time.monotonic()
                # inline recovery probe: let one real request through once
                # `interval` has elapsed since opening / the last probe
                ref = max(self._opened_at, self._last_probe)
                if now - ref < self.interval:
                    raise CircuitOpenError(getattr(self.inner, "address", ""))
                self._last_probe = now
        try:
            resp = super()._do(method, path, params, body, headers)
        except Exception:
            self._record_failure()
            raise
        status = getattr(resp, "status_code", 0)
        if status >= 500 and not _orderly_drain(resp):
            self._record_failure()
        else:
            # 2xx-4xx — or a 503 WITH Retry-After: the framework's
            # drain answer (App.stop readiness flip, resilience.md).
            # The peer is alive and told us when to come back; the
            # breaker's job is failing fast against a DEAD peer, so an
            # orderly drain longer than threshold x poll-interval must
            # not reclassify it as down (the gateway's replica table
            # polls through this breaker every second of a rolling
            # restart)
            with self._lock:
                if self._state == OPEN:
                    self._close_circuit()
                self._failures = 0
        return resp

    def _record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._failures >= self.threshold and self._state == CLOSED:
                self._open()

    def close(self) -> None:
        self._stop.set()
        super().close()


class CircuitBreakerOption:
    """reference CircuitBreakerConfig (circuit_breaker.go:24-27) applied via
    Options.addOption (options.go:3)."""

    def __init__(self, threshold: int = 5, interval: float = 10.0,
                 start_background_probe: bool = True):
        self.threshold = threshold
        self.interval = interval
        self.start_background_probe = start_background_probe

    def add_option(self, svc):
        return CircuitBreaker(svc, self.threshold, self.interval,
                              self.start_background_probe)
