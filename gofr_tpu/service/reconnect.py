"""Lazy-connect reconnect backoff: the one client-side convention.

Every client that holds a long-lived connection to a peer it must
reconnect to on loss — the P/D prefill coordinator (pd/prefill.py),
the gateway's replica relays (gateway/) — needs the same three rules:

  1. a failed connect arms a backoff window; attempts inside the
     window fail fast WITHOUT touching the socket (a down peer must
     cost one connect per window, not one per request);
  2. consecutive failures double the window up to a cap (full
     recovery pressure decays exponentially);
  3. a configuration-class failure (refused handshake, wrong service)
     holds at the cap immediately — retrying faster cannot fix a
     wrong deploy.

This class is that convention, extracted from the two copies that
had grown in ``pd/prefill.py`` (connect path + loss path) so the
gateway doesn't add a third. It tracks state only — callers own the
socket and the typed error they raise; ``blocked()``'s return value
is the honest ``Retry-After`` for that error.

Thread model: every method takes the internal lock, so one instance
may be shared by a connect path and a reader-thread loss path (the
PDPrefill shape) without external locking.
"""

from __future__ import annotations

import threading
import time

__all__ = ["ReconnectBackoff"]


class ReconnectBackoff:
    #: first failure's window (seconds) unless overridden
    BASE_S = 0.5
    #: ceiling the doubling stops at; also the config-error hold
    CAP_S = 15.0

    def __init__(self, base_s: float | None = None,
                 cap_s: float | None = None, clock=time.monotonic):
        self.base_s = float(self.BASE_S if base_s is None else base_s)
        self.cap_s = float(self.CAP_S if cap_s is None else cap_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._delay = self.base_s
        self._until = 0.0

    # -- state reads ----------------------------------------------------------
    @property
    def delay(self) -> float:
        """The window the NEXT failure will arm — the honest
        ``Retry-After`` for a typed error raised while this path is
        failing (the peer won't be re-probed sooner)."""
        with self._lock:
            return self._delay

    def blocked(self) -> float:
        """Seconds left in the current backoff window; 0.0 means an
        attempt may proceed (callers raise their typed unavailable
        error with the returned value as ``retry_after``)."""
        with self._lock:
            return max(0.0, self._until - self._clock())

    def retry_after(self) -> float:
        """The honest ``Retry-After`` for an error raised NOW: the
        remaining ARMED window if one is armed (the peer won't be
        re-probed sooner), else the base window. NOT ``delay`` — that
        is the already-doubled next window, and advertising it would
        systematically tell clients to wait twice as long as the
        actual re-probe point."""
        with self._lock:
            return max(0.0, self._until - self._clock()) or self.base_s

    # -- state transitions ----------------------------------------------------
    def failure(self) -> float:
        """A connect/hold attempt failed: arm the current window,
        double the next one (up to the cap), and return the armed
        window — the ``retry_after`` this failure should advertise."""
        with self._lock:
            armed = self._delay
            self._until = self._clock() + armed
            self._delay = min(self._delay * 2, self.cap_s)
            return armed

    def hold(self, seconds: float | None = None) -> float:
        """Arm a FIXED window (default: the cap) without consuming the
        doubling ladder — the configuration-error class (refused
        handshake, wrong weights behind the address): backing off
        faster cannot fix it, so park at the long window at once."""
        armed = self.cap_s if seconds is None else float(seconds)
        with self._lock:
            self._until = self._clock() + armed
            return armed

    def success(self) -> None:
        """Connected (or the peer answered): clear the window, reset
        the ladder to the base."""
        with self._lock:
            self._delay = self.base_s
            self._until = 0.0

    # alias so call sites read as intent ("reset after manual repoint")
    reset = success

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return (f"ReconnectBackoff(delay={self.delay:.3f}s, "
                f"blocked={self.blocked():.3f}s)")
