"""Service health probing + custom-endpoint override.

Reference: pkg/gofr/service/health.go:18-48 (default GET .well-known/alive ->
Health{UP/DOWN}) and health_config.go:5-23 (HealthConfig decorator
overriding the endpoint).
"""

from __future__ import annotations

from ..datasource import Health, STATUS_DOWN, STATUS_UP
from .wrap import ServiceWrapper

DEFAULT_HEALTH_ENDPOINT = ".well-known/alive"


class CustomHealth(ServiceWrapper):
    def __init__(self, inner, endpoint: str):
        super().__init__(inner)
        self.endpoint = endpoint.lstrip("/")
        self._repoint_breaker_probes()

    def _repoint_breaker_probes(self) -> None:
        """Any CircuitBreaker beneath us must probe the CUSTOM endpoint while
        open (reference health_config.go overrides the endpoint for the whole
        chain). The probe dispatches against the breaker's inner layer so an
        open circuit cannot veto its own recovery check."""
        from .circuit_breaker import CircuitBreaker
        from .wrap import _dispatch

        layer = self.inner
        while layer is not None:
            if isinstance(layer, CircuitBreaker):
                target = layer.inner

                def probe(target=target):
                    from ..datasource import Health, STATUS_DOWN, STATUS_UP

                    try:
                        resp = _dispatch(target, "GET", self.endpoint, None, None, None)
                        status = STATUS_UP if resp.ok else STATUS_DOWN
                        return Health(status=status)
                    except Exception as e:
                        return Health(status=STATUS_DOWN, details={"error": repr(e)})

                layer.health_probe = probe
            layer = getattr(layer, "inner", None)

    def health_check(self) -> Health:
        try:
            resp = self._do("GET", self.endpoint, None, None, None)
            if resp.ok:
                return Health(status=STATUS_UP, details={"host": self.address})
            return Health(status=STATUS_DOWN,
                          details={"host": self.address, "status": resp.status_code})
        except Exception as e:
            return Health(status=STATUS_DOWN,
                          details={"host": self.address, "error": repr(e)})


class HealthOption:
    def __init__(self, endpoint: str):
        self.endpoint = endpoint

    def add_option(self, svc):
        return CustomHealth(svc, self.endpoint)
