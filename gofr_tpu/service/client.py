"""Core HTTP service client: verbs, tracing, logging, metrics.

Reference: pkg/gofr/service/new.go —
  - verb set (new.go:35-64): get/post/put/patch/delete, each with a
    ``*_with_headers`` variant
  - createAndSendRequest (new.go:135-192): span per call, traceparent
    injection, structured Log/ErrorLog, ``app_http_service_response``
    histogram labeled path/method/status
  - encodeQueryParameters (new.go:196)
"""

from __future__ import annotations

import http.client
import json
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
from typing import Any, Mapping

from .. import chaos
from ..datasource import Health, STATUS_DOWN, STATUS_UP
from ..errors import HTTPError, ServiceUnavailable
from ..resilience import current_deadline
from .wrap import VerbSurface, hop_context


class Response:
    """Thin response carrier (reference service/response.go)."""

    def __init__(self, status_code: int, body: bytes, headers: Mapping[str, str]):
        self.status_code = status_code
        self.body = body
        self.headers = {k.lower(): v for k, v in dict(headers).items()}

    def json(self) -> Any:
        return json.loads(self.body) if self.body else None

    def header(self, key: str, default: str = "") -> str:
        return self.headers.get(key.lower(), default)

    @property
    def ok(self) -> bool:
        return 200 <= self.status_code < 300


def _encode_query(params: Mapping[str, Any] | None) -> str:
    """reference new.go:196 encodeQueryParameters — list values repeat the key."""
    if not params:
        return ""
    pairs: list[tuple[str, str]] = []
    for k, v in params.items():
        if isinstance(v, (list, tuple)):
            pairs.extend((k, str(x)) for x in v)
        else:
            pairs.append((k, str(v)))
    return urllib.parse.urlencode(pairs)


class HTTPService(VerbSurface):
    """The innermost client every decorator wraps (reference new.go:89).
    The verb surface (reference new.go:35-64) comes from VerbSurface; here
    ``_do`` IS the network hop."""

    def __init__(self, address: str, logger=None, metrics=None, tracer=None,
                 timeout: float = 30.0):
        self.address = address.rstrip("/")
        self.logger = logger
        self.metrics = metrics
        self.tracer = tracer
        self.timeout = timeout

    # -- the one network hop (reference new.go:135-192) ----------------------
    def _do(self, method: str, path: str, params, body, headers) -> Response:
        url = f"{self.address}/{path.lstrip('/')}" if path else self.address
        q = _encode_query(params)
        if q:
            url = f"{url}?{q}"

        hdrs = {k: str(v) for k, v in (headers or {}).items()}
        data: bytes | None = None
        if body not in (None, b"", ""):
            if isinstance(body, bytes):
                data = body
            else:
                data = json.dumps(body, default=str).encode()
                hdrs.setdefault("Content-Type", "application/json")

        span = None
        if self.tracer is not None:
            span = self.tracer.start_span(f"http-service {method} {path}")
            hdrs.setdefault("traceparent", span.traceparent())

        # ambient request context crosses the hop (the gateway-forward
        # contract, docs/advanced-guide/gateway.md): one convention,
        # service/wrap.hop_context
        timeout = hop_context(hdrs, self.timeout)

        start = time.perf_counter()
        status = 0
        try:
            chaos.fire(chaos.SERVICE_REQUEST)
            req = urllib.request.Request(url, data=data, method=method, headers=hdrs)
            try:
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    status = resp.status
                    out = Response(resp.status, resp.read(), dict(resp.headers))
            except urllib.error.HTTPError as e:
                # non-2xx is still a response, not an exception (Go semantics)
                status = e.code
                out = Response(e.code, e.read(), dict(e.headers))
            dur = time.perf_counter() - start
            self._observe(method, path, status, dur, None)
            return out
        except Exception as e:
            dur = time.perf_counter() - start
            self._observe(method, path, status, dur, e)
            raise
        finally:
            if span is not None:
                span.end()

    def _observe(self, method, path, status, dur, err) -> None:
        if self.metrics is not None:
            try:
                self.metrics.record_histogram(
                    "app_http_service_response", dur,
                    path=path or "/", method=method, status=str(status))
            except Exception:
                pass
        if self.logger is None:
            return
        entry = {"event": "http-service call", "address": self.address,
                 "method": method, "path": path, "status": status,
                 "duration_us": int(dur * 1e6)}
        if err is not None:
            entry["error"] = repr(err)
            self.logger.error(entry)
        else:
            self.logger.debug(entry)

    # -- health (reference service/health.go:18-48) --------------------------
    def health_check(self) -> Health:
        from .health import DEFAULT_HEALTH_ENDPOINT

        try:
            resp = self.get(DEFAULT_HEALTH_ENDPOINT)
            if resp.ok:
                return Health(status=STATUS_UP, details={"host": self.address})
            return Health(status=STATUS_DOWN,
                          details={"host": self.address, "status": resp.status_code})
        except Exception as e:
            return Health(status=STATUS_DOWN,
                          details={"host": self.address, "error": repr(e)})

    def close(self) -> None:  # decorators forward this inward
        pass


def new_http_service(address: str, logger=None, metrics=None, *options,
                     tracer=None, timeout: float = 30.0):
    """Build the decorator chain inside-out (reference new.go:68-87)."""
    svc = HTTPService(address, logger, metrics, tracer=tracer, timeout=timeout)
    for opt in options:
        svc = opt.add_option(svc)
    return svc


def stream_generate(service, body: Mapping[str, Any],
                    path: str = "/generate", *, max_resumes: int = 3):
    """Streaming ``/generate`` call honoring the durable-streams
    resume contract (docs/advanced-guide/resilience.md) — gofr-to-gofr
    calls get mid-stream durability without a gateway hop.

    Yields token ids as ndjson lines arrive. On a mid-stream loss —
    the typed error line's resume token, or raw transport truncation
    after >= 1 token — the call re-POSTs the continuation (prompt +
    received tokens, same ``request_id``/``seed``) under the ambient
    Deadline, bounded by ``max_resumes``; replayed duplicates (cursor
    below our position) are swallowed, so the yielded stream is
    token-exact across any number of server deaths.

    ``service`` is an HTTPService (or any object with ``address``) or
    a bare ``host:port`` string. Pre-first-token failures raise typed
    (the caller's own retry policy owns those — nothing was
    delivered)."""
    address = str(getattr(service, "address", service)).rstrip("/")
    if not address.startswith("http"):
        address = f"http://{address}"
    url = f"{address}/{path.lstrip('/')}"
    base_timeout = float(getattr(service, "timeout", 120.0))
    payload = dict(body)
    emitted = [int(t) for t in (payload.get("emitted") or [])]
    if not payload.get("request_id"):
        # the dedup identity a resumed replay carries — chosen before
        # the first POST so a dead server never holds the only copy
        payload["request_id"] = f"cl-{uuid.uuid4().hex[:16]}"
    resumes = 0
    while True:
        hdrs = {"Content-Type": "application/json"}
        timeout = hop_context(hdrs, base_timeout)
        resume: dict | None = None
        try:
            chaos.fire(chaos.SERVICE_REQUEST)
            req = urllib.request.Request(
                url, data=json.dumps(payload).encode(), method="POST",
                headers=hdrs)
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                for raw in resp:
                    line = raw.strip()
                    if not line:
                        continue
                    obj = json.loads(line)
                    if "token" in obj:
                        cursor = int(obj.get("cursor", len(emitted)))
                        if cursor < len(emitted):
                            continue  # replayed duplicate: we have it
                        emitted.append(int(obj["token"]))
                        yield int(obj["token"])
                        continue
                    err = (obj.get("error") or {}) \
                        if isinstance(obj, dict) else {}
                    resume = err.get("resume")
                    if resume is None:
                        raise HTTPError(
                            str(err.get("message", "stream failed")),
                            status_code=int(err.get("status", 503)))
                    break
            if resume is None:
                return  # clean end: the stream completed
            if isinstance(resume, dict) and resume.get("seed") \
                    is not None and payload.get("seed") is None:
                payload["seed"] = int(resume["seed"])
        except urllib.error.HTTPError as e:
            # a buffered non-2xx (shed, drain, bad request): typed,
            # never resumed blind — nothing streamed on this attempt
            data = e.read()
            try:
                msg = json.loads(data)["error"]["message"]
            except Exception:  # noqa: BLE001 — non-envelope body
                msg = data.decode("utf-8", "replace")[:200]
            raise HTTPError(msg, status_code=e.code) from e
        except (OSError, http.client.HTTPException,
                urllib.error.URLError):
            if not emitted:
                raise  # pre-first-token: the caller's retry owns it
            resume = {}  # transport truncation mid-stream: resume blind
        resumes += 1
        dl = current_deadline()
        if resumes > max_resumes or (dl is not None
                                     and dl.remaining() <= 0):
            raise ServiceUnavailable(
                f"stream lost after {len(emitted)} tokens and client "
                f"resume is exhausted ({resumes - 1} resumes)")
        payload["resume_from"] = len(emitted)
        payload["emitted"] = list(emitted)
