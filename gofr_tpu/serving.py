"""Framework-owned replica serving route: POST /generate with the
durable-streams resume contract.

Until PR 18 every replica hand-rolled its /generate handler (benches,
tests, deployments all re-implemented the same six lines), which meant
no two replicas agreed on a wire contract the gateway could resume
against. This module is the canonical route: ``install_generate(app)``
registers a POST handler over ``ctx.tpu.generate`` that speaks the
stream resume contract (docs/advanced-guide/resilience.md):

  - every ndjson token line carries a monotone **cursor** — the
    absolute generated-token index of the ORIGINAL request (a resumed
    continuation keeps counting where the dead replica stopped);
  - a mid-stream engine failure after >= 1 delivered token ends the
    (already-200) stream with ONE typed error line whose ``resume``
    object is a complete resume token: request id, next cursor, the
    block-chain fingerprint of prompt+emitted (the same chain hashing
    the radix index and T2 keys use), and the request's sampling seed;
  - a repeated ``request_id`` is IDEMPOTENT at admission: the route
    cancels the zombie stream it may still hold before admitting the
    retry, so a client/gateway retry never double-generates;
  - a request with ``resume_from``/``emitted`` admits as a
    continuation (``generate(continue_from=...)``): prompt+emitted
    prefill through the normal gate/deadline/SLO/chunk-lattice path
    (warm caches cover the chain and only the tail recomputes), and
    the first line of the continuation reports ``recompute`` — how
    many prompt positions the replica actually had to prefill.

Request body (JSON)::

    {"tokens": [...],                 # prompt token ids (required)
     "max_new": 16, "temperature": 0.0, "top_k": 0,
     "eos": 2 | [2, 7], "adapter": 0,
     "seed": 123,                     # sampling seed (optional)
     "request_id": "r-...",          # dedup + resume identity
     "resume_from": 5,                # cursor to continue from
     "emitted": [...]}                # the 5 tokens already delivered

Failures BEFORE the first token stay buffered typed responses (400 /
429 + Retry-After / 503 / 504) — the gateway's pre-commit failover
path handles those; only post-commit failures use the typed line.
"""

from __future__ import annotations

import json
import threading

from .errors import BadRequest, HTTPError, status_from_error
from .wire import WAKE

__all__ = ["EmbeddingsRoute", "GenerateRoute", "install_embeddings",
           "install_generate", "resume_chain"]


def resume_chain(tokens, emitted, block: int = 16, adapter: int = 0) -> str:
    """The resume token's block-chain fingerprint: the LAST chain hash
    of prompt+emitted under the same salt/chaining the radix index and
    T2 fingerprint keys use — a successor replica whose cache namespace
    can produce this hash can cover the whole chain warm."""
    import numpy as np

    from .tpu.kvcache import chain_hashes, first_block_hash

    toks = np.concatenate([np.asarray(tokens, np.int32).reshape(-1),
                           np.asarray(emitted, np.int32).reshape(-1)]) \
        if len(emitted) else np.asarray(tokens, np.int32).reshape(-1)
    last = None
    for h in chain_hashes(toks, block, adapter):
        last = h
    if last is None:  # sub-block: same fallback the affinity key uses
        last = first_block_hash(toks, block, adapter)
    return last.hex()[:32]


class _ResumableLines:
    """The ndjson source handed to ``ctx.stream``: tokens map to
    cursor-carrying lines on the transport's zero-handoff sink path
    (the map runs on the producing thread), and terminal engine
    errors — which always ride the queue, never the sink — convert in
    ``__iter__``:

      - failure with ZERO tokens delivered re-raises, so the transport
        returns a buffered typed response (the gateway fails over
        pre-commit, nothing was delivered);
      - failure after >= 1 token yields ONE typed error line carrying
        the resume token, then ends the stream.
    """

    def __init__(self, route: "GenerateRoute", rid: str | None, stream,
                 prompt, emitted, adapter: int):
        self._route = route
        self._rid = rid
        self._stream = stream
        self._prompt = list(int(t) for t in prompt)
        self._emitted = list(int(t) for t in emitted)
        self._adapter = int(adapter)
        self._base = len(self._emitted)
        self._sent = 0

    # -- the per-token transform (sink path AND iterator path) ---------------
    def _line(self, item) -> bytes:
        tok = int(item[0] if isinstance(item, tuple) else item)
        cursor = self._base + self._sent
        obj = {"token": tok, "cursor": cursor}
        if self._sent == 0 and self._base:
            # first line of a continuation: how much prefix this
            # replica actually recomputed (a T1/T2-warm resume covers
            # most of prompt+emitted and recomputes only the tail)
            obj["recompute"] = max(
                0, getattr(self._stream, "prompt_len", 0)
                - getattr(self._stream, "cache_tokens", 0))
        self._sent += 1
        self._emitted.append(tok)
        return (json.dumps(obj) + "\n").encode()

    def resume_token(self) -> dict:
        token: dict = {"cursor": self._base + self._sent,
                       "emitted": self._sent,
                       "chain": resume_chain(self._prompt, self._emitted,
                                             self._route.block,
                                             self._adapter)}
        if self._rid is not None:
            token["request_id"] = self._rid
        seed = getattr(self._stream, "seed", None)
        if seed is not None:
            token["seed"] = int(seed)
        return token

    # -- PushStream protocol passthrough -------------------------------------
    def set_sink(self, sink) -> None:
        self._stream.set_sink(lambda item: sink(self._line(item)))

    def clear_sink(self) -> None:
        cs = getattr(self._stream, "clear_sink", None)
        if cs is not None:
            cs()

    def wake(self) -> None:
        w = getattr(self._stream, "wake", None)
        if w is not None:
            w()

    def cancel(self) -> None:
        c = getattr(self._stream, "cancel", None)
        if c is not None:
            c()

    @property
    def trace(self):
        return getattr(self._stream, "trace", None)

    def __iter__(self):
        try:
            for item in self._stream:
                yield item if item is WAKE else self._line(item)
        except Exception as e:  # noqa: BLE001 — typed-line conversion
            if self._sent == 0:
                raise  # pre-commit: buffered typed response instead
            detail: dict = {
                "message": str(e) or repr(e),
                "status": (status_from_error(e)
                           if isinstance(e, HTTPError) else 503)}
            if detail["status"] in (429, 503):
                detail["retry_after"] = self._route.retry_after_s
                detail["resume"] = self.resume_token()
            yield (json.dumps({"error": detail}) + "\n").encode()
        finally:
            self._route._drop(self._rid, self._stream)


class GenerateRoute:
    """The route's server half: admission (with request-id dedup) +
    the per-request line source. One instance per App; the live-stream
    registry is bounded by in-flight requests (entries drop at each
    stream's terminal, whatever it is)."""

    def __init__(self, engine, *, block: int = 16,
                 retry_after_s: float = 1.0, logger=None):
        self.engine = engine
        self.block = max(1, int(block))
        self.retry_after_s = float(retry_after_s)
        self.logger = logger
        self._live: dict[str, object] = {}
        self._lock = threading.Lock()

    def _drop(self, rid: str | None, stream) -> None:
        if rid is None:
            return
        with self._lock:
            if self._live.get(rid) is stream:
                del self._live[rid]

    def _dedup(self, rid: str | None) -> None:
        """Idempotent replay: a repeated request id cancels the zombie
        stream a previous attempt may still be generating into (its
        client is gone — the retry IS the client now), so a gateway
        retry never runs two generations for one request."""
        if rid is None:
            return
        with self._lock:
            prev = self._live.pop(rid, None)
        if prev is not None:
            try:
                prev.cancel()
            except Exception:
                pass
            if self.logger is not None:
                self.logger.info({"event": "generate replay dedup",
                                  "request_id": rid})

    def handle(self, ctx):
        body = ctx.bind()
        if not isinstance(body, dict) or not isinstance(
                body.get("tokens"), list):
            raise BadRequest("generate: body must be JSON with a "
                             "'tokens' array")
        try:
            tokens = [int(t) for t in body["tokens"]]
            max_new = int(body.get("max_new",
                                   body.get("max_new_tokens", 16)))
            temperature = float(body.get("temperature", 0.0) or 0.0)
            top_k = int(body.get("top_k", 0) or 0)
            adapter = int(body.get("adapter", 0) or 0)
            eos = body.get("eos", body.get("eos_id"))
            if isinstance(eos, list):
                eos = frozenset(int(t) for t in eos)
            elif eos is not None:
                eos = int(eos)
            seed = body.get("seed")
            seed = int(seed) if seed is not None else None
            rid = body.get("request_id")
            rid = str(rid) if rid is not None else None
            resume_from = body.get("resume_from")
            emitted = [int(t) for t in (body.get("emitted") or [])]
        except (TypeError, ValueError) as e:
            raise BadRequest(f"generate: malformed field: {e}") from e
        continue_from = None
        if resume_from is not None:
            if int(resume_from) != len(emitted):
                raise BadRequest(
                    f"generate: resume_from={resume_from} but "
                    f"{len(emitted)} emitted tokens were replayed — "
                    "the cursor must equal the replay length")
            continue_from = (tokens, emitted)
        self._dedup(rid)
        stream = self.engine.generate(
            tokens, max_new_tokens=max_new, temperature=temperature,
            top_k=top_k, eos_id=eos, adapter=adapter, seed=seed,
            continue_from=continue_from)
        if rid is not None:
            with self._lock:
                self._live[rid] = stream
        ctx.stream(_ResumableLines(self, rid, stream, tokens, emitted,
                                   adapter))
        return None

    def stats(self) -> dict:
        with self._lock:
            return {"live": len(self._live)}


class EmbeddingsRoute:
    """POST /v1/embeddings over the bert family's ``embed`` program —
    the multi-tenant plane's second serving surface (per-tenant quotas,
    fair batching and metrics apply to predict() traffic exactly as to
    generate()). The wire shape follows the OpenAI embeddings response
    so existing clients can point at a replica unchanged, except input
    is pre-tokenized id lists (this framework serves tokens, not text):

        {"input": [[101, 2023, ...], ...]}   # or one flat id list

    Image embeddings over the vit family are future work: vit's program
    is ``classify`` (softmax over classes, not a pooled vector), so an
    embeddings surface needs a projection-head program first.
    """

    def __init__(self, engine, *, logger=None):
        self.engine = engine
        self.logger = logger

    def handle(self, ctx):
        body = ctx.bind()
        if not isinstance(body, dict):
            raise BadRequest("embeddings: body must be a JSON object "
                             "with an 'input' array")
        raw = body.get("input")
        if not isinstance(raw, list) or not raw:
            raise BadRequest("embeddings: 'input' must be a non-empty "
                             "array of token-id lists")
        if "embed" not in getattr(self.engine, "_programs", {}):
            raise BadRequest(
                "embeddings: this replica serves no 'embed' program — "
                "run a bert-family model (TPU_MODEL=bert)")
        try:
            items = ([[int(t) for t in raw]]
                     if raw and not isinstance(raw[0], list)
                     else [[int(t) for t in row] for row in raw])
        except (TypeError, ValueError) as e:
            raise BadRequest(
                f"embeddings: malformed token id: {e}") from e
        data = []
        for i, tokens in enumerate(items):
            if not tokens:
                raise BadRequest(f"embeddings: input[{i}] is empty")
            vec = self.engine.predict("embed", tokens)
            data.append({"object": "embedding", "index": i,
                         "embedding": [float(x) for x in vec]})
        return {"object": "list", "data": data,
                "model": getattr(self.engine, "model_name", "bert"),
                "meta": {"tenant": ctx.tenant,
                         "slo_class": ctx.slo_class}}


def install_embeddings(app, path: str = "/v1/embeddings") -> EmbeddingsRoute:
    """Register the canonical /v1/embeddings on an App (bert family)."""
    route = EmbeddingsRoute(app.container.tpu, logger=app.logger)
    app.post(path, route.handle)
    return route


def install_generate(app, path: str = "/generate") -> GenerateRoute:
    """Register the canonical streaming /generate on an App. Reads
    ``TPU_KVCACHE_BLOCK`` so the resume token's chain fingerprint uses
    the same block size the replica's radix index hashes by."""
    route = GenerateRoute(
        app.container.tpu,
        block=app.config.get_int("TPU_KVCACHE_BLOCK", 16),
        retry_after_s=app.config.get_float("TPU_RESUME_RETRY_AFTER_S",
                                           1.0),
        logger=app.logger)
    app.post(path, route.handle)
    return route
