"""Leveled structured logger.

Reference: pkg/gofr/logging/logger.go — levels DEBUG<INFO<NOTICE<WARN<ERROR<FATAL
(logging/level.go:10-17), JSON output when piped and colorized pretty-print on a
TTY (logger.go:147-187), stderr split for >=ERROR (logger.go:60-63), Fatal exits
(logger.go:135-145). Named ``glog`` to avoid shadowing the stdlib ``logging``
module inside the package.
"""

from __future__ import annotations

import enum
import io
import json
import os
import sys
import threading
import time
from typing import Any, IO


class LogLevel(enum.IntEnum):
    DEBUG = 1
    INFO = 2
    NOTICE = 3
    WARN = 4
    ERROR = 5
    FATAL = 6

    @classmethod
    def parse(cls, s: str | None, default: "LogLevel" = None) -> "LogLevel":
        default = default if default is not None else cls.INFO
        if not s:
            return default
        try:
            return cls[s.strip().upper()]
        except KeyError:
            return default


_COLORS = {
    LogLevel.DEBUG: 37,  # grey
    LogLevel.INFO: 36,  # cyan
    LogLevel.NOTICE: 36,
    LogLevel.WARN: 33,  # yellow
    LogLevel.ERROR: 31,  # red
    LogLevel.FATAL: 31,
}


def _is_terminal(stream: IO) -> bool:
    """Reference: logging/logger.go:257 checkIfTerminal."""
    try:
        return stream.isatty()
    except Exception:
        return False


class Logger:
    """Structured leveled logger with pluggable streams.

    Matches the reference ``logging.Logger`` interface surface
    (logging/logger.go:23-39): Debug/Log(Info)/Notice/Warn/Error/Fatal plus
    the ``*f`` format variants, and ``change_level`` used by the remote
    level poller (logging/dynamicLevelLogger.go).
    """

    def __init__(
        self,
        level: LogLevel = LogLevel.INFO,
        out: IO | None = None,
        err: IO | None = None,
        pretty: bool | None = None,
    ):
        self.level = level
        self.out = out if out is not None else sys.stdout
        self.err = err if err is not None else sys.stderr
        self.pretty = pretty if pretty is not None else _is_terminal(self.out)
        self._lock = threading.Lock()

    # -- core ---------------------------------------------------------------
    def _logf(self, level: LogLevel, *args: Any, fmt: str | None = None,
              force: bool = False) -> None:
        if level < self.level and not force:
            return
        stream = self.err if level >= LogLevel.ERROR else self.out
        now = time.time()
        if fmt is not None:
            message: Any = (fmt % args) if args else fmt
        elif len(args) == 1:
            message = args[0]
        else:
            message = " ".join(str(a) for a in args)

        if self.pretty:
            line = self._pretty_line(level, now, message)
        else:
            entry: dict[str, Any] = {
                "level": level.name,
                "time": time.strftime(
                    "%Y-%m-%dT%H:%M:%S", time.localtime(now)
                ) + f".{int((now % 1) * 1e6):06d}",
            }
            if isinstance(message, dict):
                entry["message"] = message
            elif hasattr(message, "log_fields"):
                entry["message"] = message.log_fields()
            else:
                entry["message"] = str(message)
            entry.update(_trace_fields())
            line = json.dumps(entry, default=str)
        with self._lock:
            try:
                stream.write(line + "\n")
                stream.flush()
            except ValueError:
                pass  # closed stream during shutdown

    def _pretty_line(self, level: LogLevel, now: float, message: Any) -> str:
        color = _COLORS[level]
        ts = time.strftime("%H:%M:%S", time.localtime(now))
        if hasattr(message, "pretty_print"):
            body = message.pretty_print()
        elif isinstance(message, dict):
            body = " ".join(f"{k}={v}" for k, v in message.items())
        else:
            body = str(message)
        return f"\x1b[{color}m{level.name:<6}\x1b[0m [{ts}] {body}"

    # -- public surface -----------------------------------------------------
    def debug(self, *args: Any) -> None:
        self._logf(LogLevel.DEBUG, *args)

    def debugf(self, fmt: str, *args: Any) -> None:
        self._logf(LogLevel.DEBUG, *args, fmt=fmt)

    def info(self, *args: Any) -> None:
        self._logf(LogLevel.INFO, *args)

    def infof(self, fmt: str, *args: Any) -> None:
        self._logf(LogLevel.INFO, *args, fmt=fmt)

    # reference calls INFO-level logging "Log"
    log = info
    logf = infof

    def notice(self, *args: Any) -> None:
        self._logf(LogLevel.NOTICE, *args)

    def noticef(self, fmt: str, *args: Any) -> None:
        self._logf(LogLevel.NOTICE, *args, fmt=fmt)

    def warn(self, *args: Any) -> None:
        self._logf(LogLevel.WARN, *args)

    def warnf(self, fmt: str, *args: Any) -> None:
        self._logf(LogLevel.WARN, *args, fmt=fmt)

    def wide(self, fields: dict) -> None:
        """Emit one canonical WIDE event: a single structured line
        carrying everything worth knowing about one request (outcome,
        slo_class, queue wait, chunk count, cache tier, tokens,
        trace_id — see docs/advanced-guide/observability.md). The
        contract is grep-ability: ``"event": "request"`` in JSON mode
        (or ``event=request`` pretty) finds every request's one-line
        summary, and the dict's insertion order is preserved so field
        positions stay stable across lines.

        BYPASSES the level gate: wide events are the per-request log
        contract dashboards and scripts join on, and a deployment that
        raises the level to WARN to cut diagnostic noise must not
        silently lose every request record with it. The line still
        labels itself INFO."""
        self._logf(LogLevel.INFO, dict(fields), force=True)

    def error(self, *args: Any) -> None:
        self._logf(LogLevel.ERROR, *args)

    def errorf(self, fmt: str, *args: Any) -> None:
        self._logf(LogLevel.ERROR, *args, fmt=fmt)

    def fatal(self, *args: Any) -> None:
        self._logf(LogLevel.FATAL, *args)
        raise SystemExit(1)

    def fatalf(self, fmt: str, *args: Any) -> None:
        self._logf(LogLevel.FATAL, *args, fmt=fmt)
        raise SystemExit(1)

    def change_level(self, level: LogLevel) -> None:
        if level != self.level:
            self.info({"event": "log level changed", "to": level.name})
            self.level = level


def _trace_fields() -> dict[str, str]:
    """Stitch active trace/span ids into every structured log line
    (reference: middleware/logger.go:47-48 does this for request logs)."""
    from . import tracing  # local import: tracing imports nothing from glog

    span = tracing.current_span()
    if span is None:
        return {}
    return {"trace_id": span.trace_id, "span_id": span.span_id}


def new_logger(level: LogLevel | str = LogLevel.INFO, **kw: Any) -> Logger:
    if isinstance(level, str):
        level = LogLevel.parse(level)
    return Logger(level=level, **kw)


def new_file_logger(path: str, level: LogLevel = LogLevel.INFO) -> Logger:
    """Reference: logging/logger.go:236-255 NewFileLogger for CMD apps."""
    if not path:
        return Logger(level=level, out=io.StringIO(), err=io.StringIO(), pretty=False)
    f = open(path, "a", encoding="utf-8")  # noqa: SIM115 - long-lived handle
    return Logger(level=level, out=f, err=f, pretty=False)


def logger_from_config(config) -> Logger:
    """Build the app logger from LOG_LEVEL (container/container.go:64-67)."""
    return new_logger(LogLevel.parse(config.get("LOG_LEVEL")))


_ = os  # keep os imported for future use without lint noise
