"""Configuration: ``.env`` file + process environment.

Reference: pkg/gofr/config/config.go:3-6 defines ``Config{Get, GetOrDefault}``;
pkg/gofr/config/godotenv.go:18-33 loads ``./configs/.env`` and then falls back
to the process env. We keep the same two-method surface plus typed helpers
(the reference scatters ``strconv`` calls at each use site; a typed getter is
the idiomatic Python equivalent).
"""

from __future__ import annotations

import os
from typing import Mapping, Protocol, runtime_checkable


@runtime_checkable
class Config(Protocol):
    def get(self, key: str) -> str | None: ...

    def get_or_default(self, key: str, default: str) -> str: ...


class _TypedMixin:
    """Typed convenience getters shared by all Config implementations."""

    def get(self, key: str) -> str | None:  # pragma: no cover - overridden
        raise NotImplementedError

    def get_or_default(self, key: str, default: str) -> str:
        v = self.get(key)
        return v if v not in (None, "") else default

    def get_int(self, key: str, default: int) -> int:
        v = self.get(key)
        if v in (None, ""):
            return default
        try:
            return int(v)
        except ValueError:
            return default

    def get_float(self, key: str, default: float) -> float:
        v = self.get(key)
        if v in (None, ""):
            return default
        try:
            return float(v)
        except ValueError:
            return default

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key)
        if v in (None, ""):
            return default
        return v.strip().lower() in ("1", "true", "yes", "on")


def parse_env_file(path: str) -> dict[str, str]:
    """Parse a dotenv-style file: KEY=VALUE lines, '#' comments, optional
    quoting. Mirrors the subset of godotenv the reference relies on."""
    out: dict[str, str] = {}
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                if line.startswith("export "):
                    line = line[len("export "):]
                if "=" not in line:
                    continue
                key, _, val = line.partition("=")
                key = key.strip()
                val = val.strip()
                if len(val) >= 2 and val[0] == val[-1] and val[0] in "\"'":
                    val = val[1:-1]
                else:
                    # strip trailing inline comment
                    if " #" in val:
                        val = val.split(" #", 1)[0].rstrip()
                if key:
                    out[key] = val
    except OSError:
        pass
    return out


class EnvConfig(_TypedMixin):
    """Loads ``<folder>/.env`` (+ ``.<APP_ENV>.env`` override) then process env.

    Reference: pkg/gofr/config/godotenv.go:11-33, selected by App.readConfig
    (pkg/gofr/gofr.go:167-174) which uses ``./configs``.
    """

    def __init__(self, folder: str = "./configs"):
        self.folder = folder
        self._file_vars: dict[str, str] = parse_env_file(os.path.join(folder, ".env"))
        app_env = os.environ.get("APP_ENV", "")
        if app_env:
            self._file_vars.update(
                parse_env_file(os.path.join(folder, f".{app_env}.env"))
            )

    def get(self, key: str) -> str | None:
        # Process env wins over the file, matching godotenv's non-override
        # load into the environment followed by os.Getenv reads.
        if key in os.environ:
            return os.environ[key]
        return self._file_vars.get(key)


class MapConfig(_TypedMixin):
    """In-memory config for tests (reference: pkg/gofr/testutil/mock_config.go:11)."""

    def __init__(self, values: Mapping[str, str] | None = None):
        self.values: dict[str, str] = dict(values or {})

    def get(self, key: str) -> str | None:
        return self.values.get(key)
