"""Framework version constant (reference: pkg/gofr/version/version.go:3)."""

__version__ = "0.1.0-dev"
FRAMEWORK = "gofr_tpu"
