"""Metrics: name-keyed registry of counter/up-down-counter/histogram/gauge
with Prometheus text exposition.

Reference surface: pkg/gofr/metrics/register.go:13-23 (``Manager`` iface with
NewCounter/NewUpDownCounter/NewHistogram/NewGauge + record methods), the typed
store with already-/not-registered errors (metrics/store.go:14-113,
metrics/errors.go:5-19), label validation and the >20 label-cardinality
warning (register.go:233), and the promhttp endpoint with per-scrape runtime
gauges (metrics/handler.go:11-34). The OTel+Prometheus exporter pair is
replaced by a direct text-format renderer — one fewer moving part, same wire
format.
"""

from __future__ import annotations

import gc
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Iterable, Sequence


class MetricError(Exception):
    pass


class MetricAlreadyRegistered(MetricError):
    def __init__(self, name: str):
        super().__init__(f"metric {name!r} is already registered")


class MetricNotRegistered(MetricError):
    def __init__(self, name: str):
        super().__init__(f"metric {name!r} is not registered")


def _label_key(labels: dict[str, str]) -> tuple[tuple[str, str], ...]:
    return tuple(sorted(labels.items()))


@dataclass
class _Metric:
    name: str
    desc: str
    kind: str  # counter | updown | histogram | gauge
    buckets: Sequence[float] = ()
    # label-set key -> value. For histograms the value is
    # (bucket_counts: list[int], total_sum: float, count: int).
    series: dict[tuple, object] = field(default_factory=dict)
    lock: threading.Lock = field(default_factory=threading.Lock)
    # label-set key -> {bucket index -> (trace_id, value, unix_ts)}.
    # The OpenMetrics trace<->metric join: each histogram bucket keeps
    # its most recent exemplar (index len(buckets) = +Inf; -1 = the
    # counter-sample exemplar). Rendered ONLY by render_openmetrics —
    # the Prometheus 0.0.4 text format has no exemplar syntax.
    exemplars: dict[tuple, dict[int, tuple]] = field(default_factory=dict)


DEFAULT_HISTOGRAM_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10,
)


def _new_histogram_series(buckets: Sequence[float]):
    """Native wait-free histogram when the runtime is available, else the
    locked python representation [cumulative_counts, sum, count]."""
    try:
        from .native import NativeHistogram, available

        if available():
            return NativeHistogram(buckets)
    except Exception:
        pass
    return [[0] * len(buckets), 0.0, 0]


class Manager:
    """Thread-safe metrics registry + recorder.

    API matches the reference Manager (metrics/register.go:13-23) with
    snake_case naming; labels are keyword arguments:

        m.new_counter("app_reqs", "total requests")
        m.increment_counter("app_reqs", path="/a", method="GET")
    """

    def __init__(self, logger=None):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()
        self._logger = logger

    # -- registration (metrics/register.go:53-144) --------------------------
    def _register(self, name: str, desc: str, kind: str, buckets: Sequence[float] = ()) -> None:
        if not name:
            raise MetricError("metric name cannot be empty")
        with self._lock:
            if name in self._metrics:
                raise MetricAlreadyRegistered(name)
            self._metrics[name] = _Metric(name=name, desc=desc, kind=kind, buckets=tuple(buckets))

    def new_counter(self, name: str, desc: str = "") -> None:
        self._register(name, desc, "counter")

    def new_updown_counter(self, name: str, desc: str = "") -> None:
        self._register(name, desc, "updown")

    def new_histogram(self, name: str, desc: str = "",
                      buckets: Sequence[float] = DEFAULT_HISTOGRAM_BUCKETS,
                      ) -> None:
        self._register(name, desc, "histogram", sorted(buckets))

    def new_gauge(self, name: str, desc: str = "") -> None:
        self._register(name, desc, "gauge")

    # -- recording (metrics/register.go:147-231) ----------------------------
    def _get(self, name: str, kind: str) -> _Metric:
        m = self._metrics.get(name)
        if m is None or m.kind != kind:
            raise MetricNotRegistered(name)
        return m

    def _check_cardinality(self, m: _Metric, labels: dict[str, str]) -> None:
        # reference register.go:233 getAttributes warns past 20 label values
        if len(labels) > 20 and self._logger is not None:
            self._logger.warn(
                {"event": "high metric label cardinality", "metric": m.name, "labels": len(labels)}
            )

    def increment_counter(self, name: str, exemplar: str | None = None,
                          **labels: str) -> None:
        """``exemplar``: optional trace id attached to this series'
        OpenMetrics ``_total`` sample (shed/error counters pass the
        ambient span so a dashboard count links to an exact trace)."""
        m = self._get(name, "counter")
        self._check_cardinality(m, labels)
        key = _label_key(labels)
        with m.lock:
            m.series[key] = float(m.series.get(key, 0.0)) + 1.0
            if exemplar:
                m.exemplars.setdefault(key, {})[-1] = (
                    str(exemplar), 1.0, time.time())

    def delta_updown_counter(self, name: str, delta: float, **labels: str) -> None:
        m = self._get(name, "updown")
        key = _label_key(labels)
        with m.lock:
            m.series[key] = float(m.series.get(key, 0.0)) + delta

    def record_histogram(self, name: str, value: float,
                         exemplar: str | None = None, **labels: str) -> None:
        """``exemplar``: optional trace id for the bucket this value
        lands in — the OpenMetrics bucket->trace link (a p99 TTFT
        bucket resolves to the exact trace that put it there). Costs
        one locked dict write, paid only when passed."""
        m = self._get(name, "histogram")
        key = _label_key(labels)
        entry = m.series.get(key)
        if entry is None:
            with m.lock:
                entry = m.series.get(key)
                if entry is None:
                    entry = _new_histogram_series(m.buckets)
                    m.series[key] = entry
        if exemplar:
            idx = len(m.buckets)
            for i, b in enumerate(m.buckets):
                if value <= b:
                    idx = i
                    break
            with m.lock:
                m.exemplars.setdefault(key, {})[idx] = (
                    str(exemplar), float(value), time.time())
        if type(entry) is not list:  # native: wait-free, no Python lock
            entry.record(value)
            return
        with m.lock:
            counts, _, _ = entry
            for i, b in enumerate(m.buckets):
                if value <= b:
                    counts[i] += 1
            entry[1] += value
            entry[2] += 1

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        m = self._get(name, "gauge")
        key = _label_key(labels)
        with m.lock:
            m.series[key] = float(value)

    # -- exposition ---------------------------------------------------------
    def render_prometheus(self) -> str:
        """Render all metrics in Prometheus text exposition format 0.0.4."""
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in sorted(metrics, key=lambda x: x.name):
            ptype = {"counter": "counter", "updown": "gauge",
                     "gauge": "gauge", "histogram": "histogram"}[m.kind]
            if m.desc:
                lines.append(f"# HELP {m.name} {m.desc}")
            lines.append(f"# TYPE {m.name} {ptype}")
            with m.lock:
                series = dict(m.series)
            for key, val in sorted(series.items()):
                label_str = _fmt_labels(key)
                if m.kind == "histogram":
                    counts, total, count = _hist_snapshot(val)
                    cum = 0
                    for b, c in zip(m.buckets, counts):
                        cum = c
                        lines.append(
                            f'{m.name}_bucket{_fmt_labels(key, extra=("le", _fmt_float(b)))} {cum}'
                        )
                    lines.append(f'{m.name}_bucket{_fmt_labels(key, extra=("le", "+Inf"))} {count}')
                    lines.append(f"{m.name}_sum{label_str} {total}")
                    lines.append(f"{m.name}_count{label_str} {count}")
                else:
                    lines.append(f"{m.name}{label_str} {val}")
        return "\n".join(lines) + "\n"

    def render_openmetrics(self) -> str:
        """Render all metrics in the OpenMetrics 1.0 text exposition,
        with exemplars. Sample lines are identical to the Prometheus
        renderer's except for the exemplar suffix on histogram bucket
        (and counter ``_total``) lines; the additions are the metric-
        family naming (a counter family drops its ``_total`` suffix on
        the TYPE/HELP lines) and the mandatory ``# EOF`` terminator.
        Served content-negotiated from ``/metrics`` — scrapers that do
        not send ``Accept: application/openmetrics-text`` keep getting
        the 0.0.4 text format byte-identically."""
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in sorted(metrics, key=lambda x: x.name):
            if m.kind == "counter":
                # OpenMetrics counters expose family X with sample
                # X_total; a counter registered WITHOUT the suffix has
                # no conformant counter rendering — expose it as
                # `unknown` (bare samples allowed) instead of minting a
                # renamed series dashboards have never seen
                if m.name.endswith("_total"):
                    family, ptype = m.name[: -len("_total")], "counter"
                else:
                    family, ptype = m.name, "unknown"
            elif m.kind == "histogram":
                family, ptype = m.name, "histogram"
            else:
                family, ptype = m.name, "gauge"
            if m.desc:
                lines.append(f"# HELP {family} {m.desc}")
            lines.append(f"# TYPE {family} {ptype}")
            with m.lock:
                series = dict(m.series)
                exemplars = {k: dict(v) for k, v in m.exemplars.items()}
            for key, val in sorted(series.items()):
                label_str = _fmt_labels(key)
                ex = exemplars.get(key, {})
                if m.kind == "histogram":
                    counts, total, count = _hist_snapshot(val)
                    cum = 0
                    for i, (b, c) in enumerate(zip(m.buckets, counts)):
                        cum = c
                        lines.append(
                            f'{m.name}_bucket{_fmt_labels(key, extra=("le", _fmt_float(b)))} {cum}'
                            + _fmt_exemplar(ex.get(i)))
                    lines.append(
                        f'{m.name}_bucket{_fmt_labels(key, extra=("le", "+Inf"))} {count}'
                        + _fmt_exemplar(ex.get(len(m.buckets))))
                    # exemplars attach to bucket lines ONLY: _sum/_count
                    # (and every non-counter sample) stay bare per spec
                    lines.append(f"{m.name}_sum{label_str} {total}")
                    lines.append(f"{m.name}_count{label_str} {count}")
                elif m.kind == "counter" and m.name.endswith("_total"):
                    lines.append(f"{m.name}{label_str} {val}"
                                 + _fmt_exemplar(ex.get(-1)))
                else:
                    lines.append(f"{m.name}{label_str} {val}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"


def _hist_snapshot(val) -> tuple[list, float, int]:
    """One histogram series -> (cumulative bucket counts, sum, count),
    shared by both exposition renderers so they can never disagree."""
    if type(val) is not list:  # native snapshot -> cumulative
        raw, total, count = val.snapshot()
        counts, cum = [], 0
        for c in raw[:-1]:
            cum += c
            counts.append(cum)
        # +Inf/_count from the SAME snapshot's buckets (incl.
        # overflow), not the independent count atomic: a
        # scrape racing record() must never show a le-bucket
        # above +Inf (Prometheus monotonicity).
        count = cum + raw[-1]
    else:
        counts, total, count = val
    return counts, total, count


def _fmt_exemplar(ex: tuple | None) -> str:
    """OpenMetrics exemplar suffix: `` # {trace_id="…"} value ts``.
    Empty when the bucket has never seen an exemplar."""
    if not ex:
        return ""
    trace_id, value, ts = ex
    tid = str(trace_id).replace(chr(92), chr(92) * 2).replace(
        chr(34), chr(92) + chr(34))
    return f' # {{trace_id="{tid}"}} {_fmt_float(value)} {ts:.3f}'


def _fmt_float(v: float) -> str:
    return repr(float(v)) if v != int(v) else str(int(v))


def _fmt_labels(key: tuple, extra: tuple[str, str] | None = None) -> str:
    items = list(key)
    if extra is not None:
        items.append(extra)
    if not items:
        return ""
    def esc(v):
        return str(v).replace(chr(92), chr(92) * 2).replace(
            chr(34), chr(92) + chr(34))

    inner = ",".join(f'{k}="{esc(v)}"' for k, v in items)
    return "{" + inner + "}"


# -- framework metrics ------------------------------------------------------

# Bucket priors from the reference (container/container.go:147-157):
HTTP_BUCKETS = (0.001, 0.003, 0.005, 0.01, 0.02, 0.03, 0.05, 0.1, 0.2, 0.3,
                0.5, 0.75, 1, 2, 3, 5, 10, 30)
SQL_BUCKETS_US = (50, 75, 100, 125, 150, 200, 300, 500, 750, 1000, 2000, 3000,
                  4000, 5000, 7500, 10000)
REDIS_BUCKETS_US = (50, 75, 100, 125, 150, 200, 300, 500, 750, 1000, 2000, 3000)
# TPU device-op latency priors (new; microsecond-scale host ops up to
# second-scale sharded executions):
TPU_BUCKETS = (0.0005, 0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1, 0.15, 0.2,
               0.3, 0.5, 0.75, 1, 2, 5, 10, 30)
# TTFT spans admission wait + one prefill dispatch: ms-scale when a slot
# is free and the shape is warm, seconds under queueing or a first-shape
# compile — so the range is wide with extra resolution in 10ms-1s where
# the serving SLO lives:
TTFT_BUCKETS = (0.002, 0.005, 0.01, 0.02, 0.035, 0.05, 0.075, 0.1, 0.15,
                0.25, 0.4, 0.6, 1, 1.5, 2.5, 5, 10, 30, 60)
# Inter-token gaps cluster at decode-step cadence (sub-ms to tens of ms
# on hardware; hundreds of ms on the CPU backend) and spike when a chunk
# lattice or compile interleaves — fine buckets below 100ms, coarse above:
ITL_BUCKETS = (0.0005, 0.001, 0.002, 0.004, 0.008, 0.015, 0.03, 0.05, 0.1,
               0.2, 0.4, 0.8, 1.5, 3, 10)
# Inter-block dispatch gaps: 0 when pipelined (a successor block was
# already queued at reap), else the reap+delivery+admission+dispatch
# host window — sub-ms through a few hundred ms (CPU backend / compile
# interleaves). The first bucket splits "pipelined" from "not":
GAP_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.002, 0.004, 0.008, 0.015,
               0.03, 0.06, 0.12, 0.25, 0.5, 1, 3)
# Resume recompute cost is measured in TOKENS re-prefilled, not
# seconds: powers-of-two up through a long context's worth
RECOMPUTE_BUCKETS = (0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024,
                     2048, 4096, 8192)


def register_framework_metrics(m: Manager) -> None:
    """Built-in metrics (reference container/container.go:138-166 registers 16;
    we add the ``app_tpu_*`` family for the TPU datasource)."""
    # system gauges — refreshed per scrape by system_metrics()
    m.new_gauge("app_go_routines", "number of live threads")
    m.new_gauge("app_sys_memory_alloc", "resident set size in bytes")
    m.new_gauge("app_sys_total_alloc", "peak resident set size in bytes")
    m.new_gauge("app_go_numGC", "number of completed GC collections")
    m.new_gauge("app_go_sys", "virtual memory size in bytes")

    m.new_histogram("app_http_response", "response time of http requests in seconds", HTTP_BUCKETS)
    m.new_histogram("app_http_service_response",
                    "response time of http service requests in seconds",
                    HTTP_BUCKETS)
    m.new_histogram("app_sql_stats", "response time of sql queries in microseconds", SQL_BUCKETS_US)
    m.new_gauge("app_sql_open_connections", "open sql connections")
    m.new_gauge("app_sql_inUse_connections", "in-use sql connections")
    m.new_histogram("app_redis_stats",
                    "response time of redis commands in microseconds",
                    REDIS_BUCKETS_US)

    m.new_counter("app_pubsub_publish_total_count", "total publish attempts")
    m.new_counter("app_pubsub_publish_success_count", "successful publishes")
    m.new_counter("app_pubsub_subscribe_total_count", "total subscribe receives")
    m.new_counter("app_pubsub_subscribe_success_count", "successful subscribe receives")

    # TPU datasource family (no reference equivalent; BASELINE.json north star)
    m.new_histogram("app_tpu_predict_duration",
                    "end-to-end predict latency in seconds", TPU_BUCKETS)
    m.new_histogram("app_tpu_device_execute_duration",
                    "on-device execution time in seconds", TPU_BUCKETS)
    m.new_histogram("app_tpu_batch_wait_duration",
                    "time a request waits for a batch in seconds",
                    TPU_BUCKETS)
    m.new_gauge("app_tpu_batch_fill", "fraction of batch slots occupied at dispatch")
    m.new_counter("app_tpu_requests_total", "total TPU predict requests")
    m.new_counter("app_tpu_tokens_generated_total", "total generated tokens")
    m.new_counter("app_tpu_prefix_cache_hits_total",
                  "generation admissions that restored a cached prompt-prefix KV row")
    # hierarchical kv cache (tpu/kvcache: t0=HBM pool, t1=host DRAM,
    # t2=Redis-shared — docs/advanced-guide/kv-cache.md). Counters are
    # labeled by tier; a lookup that falls through t0 to hit t1 counts
    # a t0 miss AND a t1 hit, so per-tier hit ratios read directly.
    m.new_counter("app_tpu_kvcache_hits_total",
                  "prefix-cache lookups served, by tier")
    m.new_counter("app_tpu_kvcache_misses_total",
                  "prefix-cache lookups a consulted tier failed to serve")
    m.new_counter("app_tpu_kvcache_evictions_total",
                  "prefix entries evicted, by tier (t0 evictions spill "
                  "to t1 when the host tier is enabled)")
    m.new_gauge("app_tpu_kvcache_entries", "live prefix entries, by tier")
    m.new_gauge("app_tpu_kvcache_bytes",
                "bytes held by the host offload tier")
    m.new_histogram("app_tpu_kvcache_restore_duration",
                    "host-side prefix-restore path time in seconds, by "
                    "tier (row copy dispatch; +device_put for t1; "
                    "+Redis fetch for t2)", TPU_BUCKETS)
    m.new_gauge("app_tpu_devices", "number of visible TPU devices")
    m.new_counter("app_tpu_paged_evictions_total",
                  "streams truncated early by paged KV pool exhaustion")
    # device-memory accounting (gofr_tpu/tpu/hbm.py): bytes each
    # serving subsystem DECLARES it holds on device — the arbiter's
    # visibility substrate; pushed by the registry on every change
    m.new_gauge("app_tpu_device_bytes",
                "declared live device bytes, by serving subsystem "
                "(engine, kvcache-t0, lora, spec-decode, batcher)")
    # the HBM arbiter (docs/advanced-guide/memory.md): one budget the
    # subsystems lease from, with demand-driven reclaim and an
    # OOM-shed path instead of process death
    m.new_gauge("app_tpu_hbm_budget_bytes",
                "the arbiter's device-memory budget (0 = arbitration "
                "off; TPU_HBM_BUDGET_MB or device limit minus "
                "headroom)")
    m.new_counter("app_tpu_hbm_reclaims_total",
                  "arbiter reclaim callbacks that freed bytes, by the "
                  "RECLAIMED subsystem (T0 pool shrink-to-host-tier, "
                  "cold paged block release, scratch drops)")
    m.new_counter("app_tpu_hbm_shed_total",
                  "requests degraded to 429/RESOURCE_EXHAUSTED because "
                  "an HBM lease could not be covered after reclaim, by "
                  "requesting subsystem")
    # per-shard arbitration (multi-chip tensor-parallel serving,
    # docs/advanced-guide/multichip-serving.md): mesh engines settle
    # one lease entry per device, so in-use/headroom break out per chip
    m.new_gauge("app_tpu_hbm_device_in_use_bytes",
                "leased device bytes per mesh device (device label; "
                "series exist only when sharded leases are live)")
    m.new_gauge("app_tpu_hbm_device_budget_bytes",
                "the arbiter's PER-DEVICE budget (0 = per-device "
                "arbitration off; TPU_HBM_DEVICE_BUDGET_MB or device "
                "limit minus headroom)")

    # overload-safety family (gofr_tpu/resilience: deadlines, admission
    # control, brownout — see docs/advanced-guide/resilience.md)
    m.new_counter("app_tpu_expired_dropped_total",
                  "queued requests dropped at dispatch because the caller's "
                  "deadline expired (never executed)")
    m.new_counter("app_tpu_shed_total",
                  "requests rejected early by the admission gate "
                  "(429/RESOURCE_EXHAUSTED with Retry-After), by "
                  "slo_class — throughput-class sheds first under "
                  "class degradation")
    m.new_counter("app_tpu_prefill_chunks_total",
                  "mid-chunk dispatches of chunked prefills (each one "
                  "is a bounded slice of a long prompt interleaved "
                  "with decode/admission; serving-scheduler.md)")
    m.new_counter("app_tpu_brownout_capped_total",
                  "generation requests whose max_new_tokens was capped by "
                  "the brownout band")
    m.new_gauge("app_tpu_brownout_active",
                "1 while the admission gate's brownout band is engaged")

    # disaggregated prefill/decode serving (gofr_tpu/pd/ — see
    # docs/advanced-guide/disaggregated-serving.md): the KV-ship path
    # between dedicated prefill and decode pools
    m.new_counter("app_tpu_pd_requests_total",
                  "P/D-split requests, by role (prefill = relayed to "
                  "the decode pool, decode = ingested from a prefill "
                  "worker)")
    m.new_counter("app_tpu_pd_ingests_total",
                  "shipped-KV row installs admitted into decode slots "
                  "(zero prefill FLOPs on the decode pool)")
    m.new_counter("app_tpu_pd_kv_frames_total",
                  "checksummed KV block frames crossing the pool "
                  "boundary, by direction (byte totals live on the "
                  "role's health/stats surface)")
    m.new_counter("app_tpu_pd_frame_rejects_total",
                  "KV frames rejected at the transfer boundary "
                  "(checksum/truncation/layout) — each one failed a "
                  "single request typed, never a pool row")
    m.new_counter("app_tpu_pd_peer_losses_total",
                  "decode-peer connection losses that shed in-flight "
                  "relayed streams (503 + Retry-After)")
    m.new_histogram("app_tpu_pd_ship_duration",
                    "KV-ship wall time per relayed request in seconds: "
                    "first block encode to the shipper's final windowed "
                    "send returning (the wire segment of the critical "
                    "path)", TPU_BUCKETS)
    m.new_gauge("app_tpu_wire_backlog_bytes",
                "bytes parked in a wire outbox behind a slow socket, by "
                "role — the flow-control signal SocketWriter already "
                "tracks, exported")

    # prefix-affinity gateway (gofr_tpu/gateway,
    # docs/advanced-guide/gateway.md): the front door over N serving
    # replicas — routing decisions, failover spend, and the replica
    # table's aggregate view
    m.new_counter("app_tpu_gateway_requests_total",
                  "requests through the gateway, by terminal outcome "
                  "(ok / shed / failed)")
    m.new_counter("app_tpu_gateway_affinity_total",
                  "routing decisions, by result (hit = routed to the "
                  "prefix-affinity owner, spill = owner unroutable or "
                  "pressure-biased away, short = prompt below one "
                  "affinity block, balanced by pressure)")
    m.new_counter("app_tpu_gateway_failovers_total",
                  "pre-first-token retries on another replica, by "
                  "reason (transport / drain / shed)")
    m.new_counter("app_tpu_gateway_midstream_total",
                  "committed (already-200) relays terminated by a "
                  "mid-stream replica loss with the typed error line "
                  "— these requests also counted ok at commit, so "
                  "this is a loss-rate numerator, not an outcome")
    m.new_counter("app_tpu_gateway_retry_exhausted_total",
                  "requests answered a typed 503 because the failover "
                  "retry budget was empty (storm brake) or every "
                  "replica was tried")
    m.new_gauge("app_tpu_gateway_replicas",
                "replica table population, by state (ready / draining "
                "/ down)")
    m.new_gauge("app_tpu_gateway_pressure",
                "per-replica memory-pressure score (decaying; fed by "
                "429 X-Shed-Reason: hbm responses)")
    # durable streams (docs/advanced-guide/durable-streams.md): how
    # often replica death forced a token-exact continuation, and what
    # each one cost in re-prefilled tokens
    m.new_counter("app_tpu_gateway_resumes_total",
                  "committed relays continued on another replica after "
                  "mid-stream loss (the durable-streams save; pairs "
                  "with app_tpu_gateway_midstream_total as the "
                  "could-not-resume remainder)")
    m.new_histogram("app_tpu_resume_recompute_tokens",
                    "tokens re-prefilled to rebuild generation state "
                    "for one resumed stream", RECOMPUTE_BUCKETS)
    m.new_counter("app_tpu_pd_resumes_total",
                  "decode streams resumed by the P/D coordinator after "
                  "a decode-replica loss (KV re-shipped, stream "
                  "continued token-exact)")

    # tracing export health (tracing.ZipkinExporter): spans dropped
    # because the pending buffer hit its bound while the collector was
    # down/stalled — fail-open export must cost bounded memory, and
    # this counter is how a silent collector outage stays visible
    m.new_counter("app_tpu_spans_dropped_total",
                  "finished spans dropped by the bounded trace-export "
                  "buffer (collector down or stalled)")
    # tail-sampler visibility (tracing.TailSampler): the keep/drop
    # verdicts and linger sweeps that decide which traces survive
    m.new_counter("app_tpu_trace_kept_total",
                  "traces the tail sampler forwarded downstream, by "
                  "keep reason (interesting / slow / sampled)")
    m.new_counter("app_tpu_trace_dropped_total",
                  "traces the tail sampler discarded after buffering")
    m.new_counter("app_tpu_trace_sweeps_total",
                  "linger sweeps that judged rootless buffered traces")

    # serving-path telemetry (gofr_tpu/observe: the inference flight
    # recorder's metric face)
    m.new_histogram("app_tpu_ttft_duration",
                    "time from generate() submit to first token in seconds "
                    "(labeled by slo_class: the latency-class series is "
                    "the TTFT SLO)",
                    TTFT_BUCKETS)
    m.new_histogram("app_tpu_inter_token_duration",
                    "gap between consecutive delivered tokens in seconds",
                    ITL_BUCKETS)
    m.new_gauge("app_tpu_tokens_per_second",
                "decode throughput of the most recently finished stream")
    m.new_gauge("app_tpu_queue_depth",
                "requests waiting for a generation slot or a coalesced "
                "batch (generate also exports per-slo_class series for "
                "the split wait lines)")
    m.new_gauge("app_tpu_active_sequences",
                "generation slots currently holding a live stream")
    m.new_histogram("app_tpu_dispatch_gap_duration",
                    "inter-block host-dispatch gap in seconds: how long "
                    "the device stream sat idle between one fused decode "
                    "block's outputs coming ready and the next dispatch "
                    "(pipelined reaps with a successor already queued "
                    "record 0; exemplar-capable like every histogram)",
                    GAP_BUCKETS)
    m.new_gauge("app_tpu_pipeline_depth",
                "fused decode blocks in flight on the device stream "
                "after the last pipeline top-up")
    m.new_histogram("app_tpu_request_segment_duration",
                    "per-request critical-path segment time in seconds, "
                    "by segment (queue_wait / prefill / handoff / "
                    "decode on engines; pick / connect / ttfb on the "
                    "gateway; kv_transfer on decode ingest) — the "
                    "histogram face of the wide event's breakdown, "
                    "exemplar-linked to the trace",
                    TTFT_BUCKETS)
    # multi-tenant serving plane (gofr_tpu/tenancy,
    # docs/advanced-guide/multi-tenancy.md): per-tenant admission and
    # cache-footprint faces; shed/TTFT/queue-depth/cache-hit series
    # additionally grow a tenant label when a plane is installed
    m.new_gauge("app_tpu_tenant_admitted",
                "requests admitted through the tenant quota book, "
                "by tenant (cumulative)")
    m.new_gauge("app_tpu_tenant_shed",
                "requests shed with reason=tenant_quota, by tenant "
                "(cumulative)")
    m.new_gauge("app_tpu_tenant_cache_bytes",
                "prefix-cache T0 bytes resident per tenant (the "
                "cache-share arbiter lease evicts the over-budget "
                "tenant's rows first)")
    m.new_counter("app_tpu_async_jobs_total",
                  "async inference lane jobs by outcome (done / dedup "
                  "/ interrupted / backpressured)")


def update_system_metrics(m: Manager) -> None:
    """Per-scrape runtime stats (reference metrics/handler.go:20-34 refreshes
    goroutines/heap/GC per scrape; Python equivalents via /proc + gc)."""
    try:
        m.set_gauge("app_go_routines", float(threading.active_count()))
        counts = gc.get_stats()
        m.set_gauge("app_go_numGC", float(sum(s.get("collections", 0) for s in counts)))
        rss, peak, vsize = _read_proc_mem()
        m.set_gauge("app_sys_memory_alloc", rss)
        m.set_gauge("app_sys_total_alloc", peak)
        m.set_gauge("app_go_sys", vsize)
    except MetricNotRegistered:
        pass


def _read_proc_mem() -> tuple[float, float, float]:
    rss = peak = vsize = 0.0
    try:
        with open(f"/proc/{os.getpid()}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    rss = float(line.split()[1]) * 1024
                elif line.startswith("VmHWM:"):
                    peak = float(line.split()[1]) * 1024
                elif line.startswith("VmSize:"):
                    vsize = float(line.split()[1]) * 1024
    except OSError:
        pass
    return rss, peak, vsize


Iterable  # re-export quiet
time  # keep import
