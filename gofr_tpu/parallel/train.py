"""Sharded training: one jitted step — forward, loss, grad, optax update.

The reference framework has no training loop (it is a Go microservice
framework); this subsystem exists because a TPU-native serving framework
needs a first-class fine-tuning/continued-pretraining path for the models
it serves. Design:

  - ONE `jax.jit` over the whole step with explicit in/out shardings and
    donated (params, opt_state): XLA fuses forward+backward+update and
    overlaps the fsdp all-gathers/reduce-scatters with compute.
  - Gradients reduce over the data axes automatically: params are sharded
    (or replicated) over (dp, fsdp) while the batch is split over them, so
    GSPMD inserts the psum/reduce-scatter — we never call a collective.
  - `jax.checkpoint` on the scanned layer body trades recompute for HBM,
    which is what makes long-sequence training fit.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

from ..models import llama
from ..models.common import ModelConfig
from .mesh import AXIS_PP, AXIS_SP, DATA_AXES, Mesh
from .sharding import (activation_constraint, batch_spec, fit_spec,
                       param_specs, shardings_for)
from jax.sharding import NamedSharding, PartitionSpec as P


class TrainState(NamedTuple):
    step: jnp.ndarray
    params: Any
    opt_state: Any


def default_optimizer(lr: float = 3e-4, *, warmup: int = 100,
                      total_steps: int = 10_000,
                      weight_decay: float = 0.1,
                      grad_clip: float = 1.0) -> optax.GradientTransformation:
    sched = optax.warmup_cosine_decay_schedule(0.0, lr, warmup,
                                               max(total_steps, warmup + 1))
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(sched, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )


def loss_parts_local(logits: jnp.ndarray, tokens_full: jnp.ndarray,
                     lengths: jnp.ndarray, g0, S: int
                     ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(sum of masked next-token NLL, number of masked positions) for a
    SEQUENCE SHARD: ``logits`` [B, Sn, V] sits at global positions
    [g0, g0+Sn) of a length-S sequence whose full token ids are
    ``tokens_full`` [B, S] — the next-token shift reads cross-boundary
    targets from the full ids. The ONE definition of the
    shift/mask/log-softmax math: loss_parts is the g0=0, Sn=S case,
    next_token_loss its ratio, and the pipeline conveyor psums these
    parts over microbatches and sp shards into exactly the full mean."""
    B, sn, _ = logits.shape
    tgt_i = g0 + jnp.arange(sn, dtype=jnp.int32) + 1          # [Sn] global
    safe = jnp.minimum(tgt_i, S - 1)
    tgt = jnp.take_along_axis(tokens_full,
                              jnp.broadcast_to(safe, (B, sn)), axis=1)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, tgt[..., None].astype(jnp.int32),
                               axis=-1)[..., 0]               # [B, Sn]
    mask = ((tgt_i[None, :] < lengths[:, None])
            & (tgt_i[None, :] <= S - 1)).astype(jnp.float32)
    return jnp.sum(nll * mask), jnp.sum(mask)


def loss_parts(logits: jnp.ndarray, tokens: jnp.ndarray,
               lengths: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Additive causal-LM loss over the full sequence — the unsharded
    case of loss_parts_local."""
    return loss_parts_local(logits, tokens, lengths, jnp.int32(0),
                            logits.shape[1])


def next_token_loss(logits: jnp.ndarray, tokens: jnp.ndarray,
                    lengths: jnp.ndarray) -> jnp.ndarray:
    """Mean causal-LM cross-entropy: logits [B,S,V] f32 predict tokens
    shifted left; positions ≥ length are masked out."""
    nll_sum, mask_sum = loss_parts(logits, tokens, lengths)
    return nll_sum / jnp.maximum(mask_sum, 1.0)


def _build_state(cfg: ModelConfig,
                 optimizer: optax.GradientTransformation) -> Callable:
    """The ONE definition of a fresh TrainState's structure — init and
    the checkpoint-restore skeleton must never drift apart."""

    def build(key):
        params = llama.init(cfg, key)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=optimizer.init(params))

    return build


def load_balance_loss(router_probs: jnp.ndarray,
                      lengths: jnp.ndarray) -> jnp.ndarray:
    """Switch-Transformer-style MoE auxiliary loss:
    E * mean_layers( sum_e f_e * P_e ) over VALID tokens, where f_e is
    the fraction of tokens whose top-1 expert is e and P_e the mean
    router probability for e. Equals 1.0 at perfect balance and climbs
    toward E as the router collapses — the gradient pushes assignment
    back toward uniform. router_probs: [L, B, S, E] f32."""
    L, B, S, E = router_probs.shape
    mask = (jnp.arange(S)[None, :] < lengths[:, None]).astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    top1 = jnp.argmax(router_probs, axis=-1)                  # [L, B, S]
    f = jnp.sum(jax.nn.one_hot(top1, E) * mask[None, ..., None],
                axis=(1, 2)) / denom                           # [L, E]
    p = jnp.sum(router_probs * mask[None, ..., None],
                axis=(1, 2)) / denom                           # [L, E]
    return E * jnp.mean(jnp.sum(f * p, axis=-1))


def init_train_state(cfg: ModelConfig, key, mesh: Mesh,
                     optimizer: optax.GradientTransformation) -> TrainState:
    """Init params + optimizer state DIRECTLY sharded on the mesh: the init
    itself is jitted with out_shardings, so no host-side full copy of the
    model ever exists (required for 70B-class runs)."""
    build = _build_state(cfg, optimizer)
    shapes = jax.eval_shape(build, key)
    out_sh = state_shardings(shapes, mesh)
    return jax.jit(build, out_shardings=out_sh)(key)


def state_shardings(state_like: Any, mesh: Mesh) -> Any:
    """Shardings for a TrainState (or its eval_shape): optimizer moments
    mirror their parameter's spec; scalars replicate."""
    p_specs = param_specs(state_like.params)
    p_shard = shardings_for(state_like.params, mesh, p_specs)
    rep = NamedSharding(mesh, P())

    # Optax moment leaves MIRROR the param tree: an adam mu/nu leaf's tree
    # path ends with the same dict-key chain as its parameter (e.g.
    # .mu['layers']['wo']). Match by that name chain — matching by shape
    # would collide wq/wo (same shape, transposed specs).
    def names(path) -> tuple:
        return tuple(str(e.key) for e in path
                     if isinstance(e, jax.tree_util.DictKey))

    by_names: dict[tuple, Any] = {}
    for (path, _), sh in zip(
            jax.tree_util.tree_flatten_with_path(state_like.params)[0],
            jax.tree_util.tree_leaves(p_shard)):
        by_names[names(path)] = sh

    def match(path, leaf):
        key = names(path)
        # longest non-empty suffix of the opt-leaf path naming a param
        for i in range(len(key)):
            sh = by_names.get(key[i:])
            if sh is not None:
                return sh
        return rep

    opt_sh = jax.tree_util.tree_map_with_path(match, state_like.opt_state)
    return TrainState(step=rep, params=p_shard, opt_state=opt_sh)


def make_train_step(cfg: ModelConfig, optimizer: optax.GradientTransformation,
                    mesh: Mesh, *, remat: bool = True,
                    seq_parallel: str = "auto",
                    moe_aux_weight: float = 0.01,
                    n_microbatches: int | None = None) -> Callable:
    """Build the jitted sharded train step:
    step(state, tokens [B,S], lengths [B]) -> (state, metrics dict).

    ``seq_parallel``: "ring" routes attention through ring attention
    (ops.ring_attention — sequence shards pinned, K/V rotating over the
    sp axis with ppermute); "dense" keeps the fusable jnp attention;
    "auto" (default) picks ring exactly when the mesh has sp > 1, where
    GSPMD's dense partition degrades into full-rematerialization
    reshards (the spmd_partitioner warnings the dryrun notes).

    MoE configs (cfg.n_experts > 0) add ``moe_aux_weight`` times the
    load-balancing loss (reported as metrics["aux_loss"]) so the router
    cannot collapse onto a few experts.

    Meshes with pp > 1 run the forward as a GPipe microbatch conveyor
    (parallel/pipeline.py) over ``n_microbatches`` (default 2*pp; the
    batch must divide by it), MoE aux loss included. pp composes with
    dp/fsdp/ep/tp and with sp (the conveyor runs ring attention inside
    each stage for long-context pipelining); only pp + grouped MoE
    dispatch is rejected."""
    constrain = activation_constraint(mesh)
    moe = cfg.n_experts > 0
    pp = mesh.shape.get(AXIS_PP, 1)

    if pp > 1:
        from .pipeline import make_pp_loss_fn

        loss_fn = make_pp_loss_fn(cfg, mesh,
                                  n_microbatches=n_microbatches or 2 * pp,
                                  remat=remat,
                                  moe_aux_weight=moe_aux_weight)
    else:
        if n_microbatches is not None:
            # silently running a full-batch step instead of the requested
            # microbatching would change memory semantics unannounced
            raise ValueError("n_microbatches only applies to pp>1 meshes "
                             "(gradient accumulation without pp is not "
                             "implemented)")
        use_ring = (seq_parallel == "ring"
                    or (seq_parallel == "auto"
                        and mesh.shape.get(AXIS_SP, 1) > 1))
        attend_override = None
        if use_ring:
            from ..ops.ring_attention import make_ring_attention

            attend_override = make_ring_attention(
                mesh, axis_name=AXIS_SP, batch_axes=DATA_AXES)

        fwd = (jax.checkpoint(llama.forward, static_argnums=(1, 5, 6, 7))
               if remat else llama.forward)

        def loss_fn(params, tokens, lengths):
            if moe:
                logits, probs = fwd(params, cfg, tokens, lengths, None,
                                    constrain, attend_override, True)
                aux = load_balance_loss(probs, lengths)
                lm = next_token_loss(logits, tokens, lengths)
                return lm + moe_aux_weight * aux, aux
            logits = fwd(params, cfg, tokens, lengths, None, constrain,
                         attend_override, False)
            return next_token_loss(logits, tokens, lengths), jnp.zeros(())

    def step(state: TrainState, tokens, lengths):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, tokens, lengths)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new = TrainState(step=state.step + 1, params=params,
                         opt_state=opt_state)
        return new, {"loss": loss.astype(jnp.float32),
                     "grad_norm": gnorm.astype(jnp.float32),
                     "aux_loss": aux.astype(jnp.float32),
                     "step": new.step}

    def data_sharding(shape_rank2, shape_rank1):
        tok = NamedSharding(mesh, fit_spec(batch_spec(), shape_rank2, mesh))
        ln = NamedSharding(mesh, fit_spec(P(batch_spec()[0]), shape_rank1, mesh))
        return tok, ln

    compiled: dict[tuple, Callable] = {}

    def jitted(state: TrainState, tokens, lengths):
        key = (tuple(tokens.shape), tuple(lengths.shape))
        if key not in compiled:
            st_sh = state_shardings(state, mesh)
            tok_sh, len_sh = data_sharding(tokens.shape, lengths.shape)
            rep = NamedSharding(mesh, P())
            metrics_sh = {"loss": rep, "grad_norm": rep,
                          "aux_loss": rep, "step": rep}
            fn = jax.jit(step,
                         in_shardings=(st_sh, tok_sh, len_sh),
                         out_shardings=(st_sh, metrics_sh),
                         donate_argnums=(0,))
            compiled[key] = (fn, tok_sh, len_sh)
        fn, tok_sh, len_sh = compiled[key]
        return fn(state, jax.device_put(jnp.asarray(tokens), tok_sh),
                  jax.device_put(jnp.asarray(lengths), len_sh))

    return jitted


def abstract_train_state(cfg: ModelConfig, mesh: Mesh,
                         optimizer: optax.GradientTransformation) -> Any:
    """The TrainState's shape/dtype/sharding skeleton WITHOUT allocating
    anything — the restore target for checkpoint resume (and a free
    spec-validation artifact, like tests/test_70b_sharded.py uses)."""
    shapes = jax.eval_shape(_build_state(cfg, optimizer),
                            jax.random.PRNGKey(0))
    shardings = state_shardings(shapes, mesh)
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes, shardings)


def save_train_state(path: str, state: TrainState) -> None:
    """Checkpoint the FULL training state (step + params + optimizer
    moments) with orbax — the resume story the reference's migration
    ledger plays for schema (SURVEY §5 checkpoint/resume; the reference
    itself is stateless and has no analogue). Delegates to the one
    orbax save path (tpu.checkpoint.save_orbax); force=True because a
    resume loop saves back to its own output path repeatedly."""
    from ..tpu.checkpoint import save_orbax

    save_orbax(path, state, force=True)


def restore_train_state(path: str, cfg: ModelConfig, mesh: Mesh,
                        optimizer: optax.GradientTransformation) -> TrainState:
    """Restore a TrainState DIRECTLY sharded onto ``mesh`` (each leaf
    lands at its canonical NamedSharding — resuming on a different
    topology reshards at load, no host-side full copy). Delegates to the
    one orbax restore path (tpu.checkpoint.load_orbax)."""
    from ..tpu.checkpoint import load_orbax

    return load_orbax(path, target=abstract_train_state(cfg, mesh,
                                                        optimizer))
