"""Device-mesh construction: the substrate every sharded program runs on.

The reference has no parallelism layer at all (SURVEY §2: "no DP/TP/PP/SP/EP,
no collective backend" — its only distribution is HTTP/gRPC between
processes, pkg/gofr/gofr.go:108-164). Here the equivalent subsystem is
TPU-native: a named `jax.sharding.Mesh` over the slice, with XLA emitting
the collectives (all-gather/reduce-scatter/all-reduce over ICI, DCN across
hosts) from sharding annotations — nothing is hand-coded.

Axis vocabulary (the scaling-book recipe):
  dp    pure data parallelism — batch split, params replicated
  fsdp  data parallelism with parameter sharding (ZeRO-3 style): batch is
        split over (dp, fsdp) jointly; params/optimizer shard over fsdp and
        are all-gathered per layer by XLA
  pp    pipeline parallelism — the layer dim of stacked weights splits
        over pp stages; activations conveyor between stages with
        ppermute (parallel/pipeline.py). Training-only: serving meshes
        use tp/dp. Its point-to-point hops are the cheapest collective
        in the system, so pp sits right after dp (it may cross DCN).
  ep    expert parallelism — MoE expert dim split over ep; the batch also
        splits over ep (dense layers see it as one more data axis, their
        params replicate over it), so GSPMD's partition of the grouped
        dispatch scatter/gather IS the classic MoE all-to-all: tokens
        leave batch-sharded, land expert-sharded, and return
  sp    sequence/context parallelism — activation sequence axis
  tp    tensor parallelism — attention heads / FFN hidden, the innermost
        axis so its collectives ride the fastest ICI links
Axis order in the mesh is (dp, pp, fsdp, ep, sp, tp): JAX lays
consecutive devices on the innermost axes, which is where per-layer tp
collectives live; ep sits just outside sp/tp so its all-to-alls stay
on-slice.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
from jax.sharding import Mesh

AXIS_DP = "dp"
AXIS_PP = "pp"
AXIS_FSDP = "fsdp"
AXIS_EP = "ep"
AXIS_SP = "sp"
AXIS_TP = "tp"
MESH_AXES = (AXIS_DP, AXIS_PP, AXIS_FSDP, AXIS_EP, AXIS_SP, AXIS_TP)

# Axes over which the *batch* dimension of data is split. ep is a data
# axis for everything EXCEPT the expert weights (sharding.spec_for puts
# the MoE expert dim on it); dense params replicate over it, so a
# dense-model mesh with ep=1 is bit-identical to the pre-ep layout.
DATA_AXES = (AXIS_DP, AXIS_FSDP, AXIS_EP)


@dataclass(frozen=True)
class MeshPlan:
    """A validated (dp, pp, fsdp, ep, sp, tp) factorization of a device
    count. Field order matches the mesh's axis order — positional
    construction reads the same as ``describe()``."""

    dp: int = 1
    pp: int = 1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    @property
    def n_devices(self) -> int:
        return self.dp * self.pp * self.fsdp * self.ep * self.sp * self.tp

    def describe(self) -> str:
        return (f"dp={self.dp} pp={self.pp} fsdp={self.fsdp} ep={self.ep} "
                f"sp={self.sp} tp={self.tp}")


def make_mesh(plan: MeshPlan | None = None, *, dp: int = 1, fsdp: int = 1,
              sp: int = 1, tp: int = 1, ep: int = 1, pp: int = 1,
              devices=None) -> Mesh:
    """Build a named mesh from an explicit factorization.

    `devices` defaults to `jax.devices()`; the factorization must cover
    exactly that many devices. Multi-host note: `jax.devices()` is the
    *global* device list under the PJRT distributed runtime, so the same
    call shapes single-host slices and multi-host pods — DCN-crossing axes
    should be outermost (dp first), which is the order used here.
    """
    plan = plan or MeshPlan(dp=dp, fsdp=fsdp, sp=sp, tp=tp, ep=ep, pp=pp)
    devices = list(devices if devices is not None else jax.devices())
    if plan.n_devices != len(devices):
        raise ValueError(
            f"mesh plan {plan.describe()} covers {plan.n_devices} devices, "
            f"got {len(devices)}")
    import numpy as np
    arr = np.array(devices).reshape(plan.dp, plan.pp, plan.fsdp, plan.ep,
                                    plan.sp, plan.tp)
    return Mesh(arr, MESH_AXES)


def auto_plan(n_devices: int | None = None, *, model_bytes: int = 0,
              hbm_bytes_per_device: int = 16 << 30) -> MeshPlan:
    """Pick a (dp, fsdp, sp, tp) factorization for `n_devices`.

    Heuristic for serving: use the smallest tp that fits the model in HBM
    (tp collectives are per-layer, so keep tp minimal), then spend the rest
    on data parallelism. Training-oriented callers usually want fsdp
    instead — pass an explicit MeshPlan to make_mesh for that.
    """
    n = n_devices if n_devices is not None else len(jax.devices())
    tp = 1
    if model_bytes:
        # fit weights in ~60% of HBM, leaving room for KV cache + workspace
        budget = int(hbm_bytes_per_device * 0.6)
        need = max(1, math.ceil(model_bytes / budget))
        # smallest divisor of n that is >= need
        fits = [d for d in range(need, n + 1) if n % d == 0]
        if not fits:
            raise ValueError(
                f"model ({model_bytes >> 30} GiB) needs tp>={need} but only "
                f"{n} devices are available")
        tp = fits[0]
    return MeshPlan(dp=n // tp, fsdp=1, sp=1, tp=tp)


def single_device_mesh() -> Mesh:
    """A 1×1×1×1 mesh over the first device — lets every sharded code path
    run unchanged on one chip (specs all resolve to no-op shardings)."""
    return make_mesh(MeshPlan(), devices=jax.devices()[:1])


def remesh(mesh: Mesh, devices) -> Mesh:
    """Re-place a mesh onto ``devices`` after mid-serving device loss —
    the warm-recovery half of multi-chip serving (the engine re-places
    params/cache onto the result, re-settles its HBM leases, and
    rewarms from the offload tiers instead of dying).

    Same axis names; when the live count covers the original plan the
    mesh rebuilds identically (the common simulated-loss case, and a
    real loss where a hot spare joined). When devices are GONE, axes
    shrink until the plan fits — data-parallel width first (dp, then
    fsdp/ep/sp/pp), tensor parallelism LAST: tp carries the per-layer
    collectives AND decides whether the weights fit per chip at all,
    so it is the one axis a degraded mesh fights to keep. Each
    shrink halves an even axis or drops an odd one to 1 (mesh axes
    must exactly factor the device count). Raises when no devices
    remain."""
    devices = list(devices)
    if not devices:
        raise ValueError("remesh: no live devices to re-place onto")
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    n = len(devices)

    def covered() -> int:
        return math.prod(shape.values())

    for ax in (AXIS_DP, AXIS_FSDP, AXIS_EP, AXIS_SP, AXIS_PP, AXIS_TP):
        while covered() > n and shape.get(ax, 1) > 1:
            size = shape[ax]
            shape[ax] = size // 2 if size % 2 == 0 else 1
    if covered() > n:  # all axes at 1 yet still over: impossible
        raise ValueError(f"remesh: cannot fit {dict(shape)} onto "
                         f"{n} device(s)")
    import numpy as np
    arr = np.array(devices[:covered()]).reshape(
        tuple(shape[ax] for ax in mesh.axis_names))
    return Mesh(arr, mesh.axis_names)
