"""Parallelism layer: mesh, sharding specs, sharded train/serve steps.

The reference framework's only notion of "distributed" is goroutines plus
HTTP/gRPC/pub-sub between processes (SURVEY §5: no NCCL/MPI, no DP/TP/SP).
The TPU-native equivalent is this package: a named `jax.sharding.Mesh`
over the slice (ICI) or pod (DCN), PartitionSpec rules per model family,
and jitted steps whose collectives XLA derives from the specs.
"""

from .distributed import (is_coordinator, is_initialized, maybe_initialize,
                          process_count, process_index)
from .mesh import (AXIS_DP, AXIS_EP, AXIS_FSDP, AXIS_PP, AXIS_SP, AXIS_TP,
                   DATA_AXES, MESH_AXES, MeshPlan, auto_plan, make_mesh,
                   remesh, single_device_mesh)
from .pipeline import make_pp_loss_fn
from .sharding import (activation_constraint, activation_spec, batch_spec,
                       fit_spec, kv_cache_specs, kv_head_shards,
                       paged_cache_specs, param_specs, replicated,
                       shard_params, shardings_for, spec_for)
from .train import (TrainState, abstract_train_state, default_optimizer,
                    init_train_state, load_balance_loss, make_train_step,
                    next_token_loss, restore_train_state, save_train_state,
                    state_shardings)

__all__ = [
    "is_coordinator", "is_initialized", "maybe_initialize",
    "process_count", "process_index",
    "AXIS_DP", "AXIS_EP", "AXIS_FSDP", "AXIS_PP", "AXIS_SP", "AXIS_TP",
    "DATA_AXES", "MESH_AXES",
    "MeshPlan", "auto_plan", "make_mesh", "remesh", "single_device_mesh",
    "make_pp_loss_fn",
    "activation_constraint", "activation_spec", "batch_spec", "fit_spec",
    "kv_cache_specs", "kv_head_shards", "paged_cache_specs", "param_specs",
    "replicated", "shard_params", "shardings_for", "spec_for",
    "TrainState", "abstract_train_state", "default_optimizer",
    "init_train_state", "load_balance_loss", "make_train_step",
    "next_token_loss", "restore_train_state", "save_train_state",
    "state_shardings",
]
