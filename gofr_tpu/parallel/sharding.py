"""Sharding rules: map model pytrees onto the mesh by leaf name.

This is the GSPMD half of the parallelism layer (mesh.py is the substrate):
every parameter/optimizer/cache leaf gets a `PartitionSpec`, `jax.jit`
in/out shardings pin the boundaries, and XLA inserts the ICI collectives.
Nothing in the model code mentions devices — the specs here are the single
source of truth.

Rule set (Megatron-style TP + ZeRO-3-style fsdp, both expressed as specs):
  column-parallel  [L, D, out]  (wq/wk/wv/w_gate/w_up/w_in) → (None, fsdp, tp)
  row-parallel     [L, in, D]   (wo/w_down/w_out)           → (None, tp, fsdp)
  embeddings       [V, D]                                    → ((tp, fsdp), None)
  lm_head          [D, V]                                    → (fsdp, tp)
  norms/biases                                               → replicated/minor
Int8 `QuantizedLinear` leaves shard like their parent weight; the per-output
scale follows the output axis.

Any axis that does not divide a dimension is dropped (replicated) — so the
same rules serve the tiny test configs and the 70B production shapes.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import (AXIS_EP, AXIS_FSDP, AXIS_PP, AXIS_SP, AXIS_TP,
                   DATA_AXES)

# leaf name -> spec for the *full* (possibly [L, ...]-stacked) weight
_COLUMN = {"wq", "wk", "wv", "w_gate", "w_up", "w_in"}
_ROW = {"wo", "w_down", "w_out"}
_COLUMN_BIAS = {"bq", "bk", "bv", "b_in"}
_ROW_BIAS = {"bo", "b_out"}


def spec_for(name: str, ndim: int, stacked: bool = False) -> P:
    """PartitionSpec for a parameter leaf, keyed on its dict name.

    ``stacked``: the leaf lives under a per-layer stack (params["layers"])
    with a leading [L] dim — that dim shards over pp (pipeline stages own
    contiguous layer ranges; parallel/pipeline.py conveys activations
    between them). On pp=1 meshes the axis fits to nothing."""
    lead = AXIS_PP if stacked else None
    if name in _COLUMN:
        if ndim == 4:  # MoE experts [L, E, D, F]: experts over ep,
            # hidden over the dense axes (fsdp/tp) within each expert
            return P(lead, AXIS_EP, AXIS_FSDP, AXIS_TP)
        return P(lead, AXIS_FSDP, AXIS_TP) if ndim == 3 else P(AXIS_FSDP, AXIS_TP)
    if name in _ROW:
        if ndim == 4:  # MoE experts: [L, E, F, D]
            return P(lead, AXIS_EP, AXIS_TP, AXIS_FSDP)
        return P(lead, AXIS_TP, AXIS_FSDP) if ndim == 3 else P(AXIS_TP, AXIS_FSDP)
    if name in _COLUMN_BIAS:
        return P(lead, AXIS_TP) if ndim == 2 else P(AXIS_TP)
    if name in _ROW_BIAS:
        return P(lead, AXIS_FSDP) if ndim == 2 else P(AXIS_FSDP)
    if name == "embedding":
        # Vocab over (tp, fsdp), FEATURE REPLICATED. Sharding the feature
        # dim (the r1–r3 layout: P(tp, fsdp)) made every token-embedding
        # gather inherit a feature-split output that GSPMD could only
        # reshard to the (data, sp) activation layout by involuntary full
        # rematerialization — an all-gather of [B, S, D] per train step
        # (the MULTICHIP_r03 spmd_partitioner warnings). A vocab-only
        # shard partitions the gather as local-lookup + mask + psum and
        # the output is born replicated, so the activation constraint is
        # a free slice.
        return P((AXIS_TP, AXIS_FSDP), None)
    if name == "lm_head":
        return P(AXIS_FSDP, AXIS_TP)
    if name in ("pos_embedding", "patch_proj", "pooler_w", "head"):
        return P(None, AXIS_FSDP) if ndim == 2 else P(AXIS_FSDP)
    if stacked and ndim >= 1:  # per-layer norms/router: layer dim over pp
        return P(lead)
    return P()  # norms, small embeddings, cls_token: replicated


def _leaf_name(path) -> str:
    """Last dict key on the tree path (attr keys of NamedTuple leaves like
    QuantizedLinear.w/.scale are skipped so they inherit the weight's name)."""
    for entry in reversed(path):
        if isinstance(entry, jax.tree_util.DictKey):
            return str(entry.key)
    return ""


def _is_quant_scale(path) -> bool:
    last = path[-1] if path else None
    return isinstance(last, (jax.tree_util.GetAttrKey,)) and \
        getattr(last, "name", "") == "scale"


def fit_spec(spec: P, shape: tuple, mesh: Mesh) -> P:
    """Drop spec axes that don't divide the corresponding dim (replicate
    instead); pad/truncate the spec to the array rank."""
    axes = list(spec) + [None] * (len(shape) - len(spec))
    fitted = []
    for dim, ax in zip(shape, axes[: len(shape)]):
        if ax is None:
            fitted.append(None)
            continue
        names = ax if isinstance(ax, tuple) else (ax,)
        size = 1
        for n in names:
            size *= mesh.shape.get(n, 1)
        fitted.append(ax if size > 0 and dim % size == 0 else None)
    return P(*fitted)


def param_specs(params: Any) -> Any:
    """Pytree of PartitionSpec matching `params` (unfitted — see
    `shardings_for` for the mesh-aware version)."""

    def one(path, leaf):
        name = _leaf_name(path)
        stacked = any(isinstance(e, jax.tree_util.DictKey)
                      and str(e.key) == "layers" for e in path)
        spec = spec_for(name, leaf.ndim if hasattr(leaf, "ndim") else 0,
                        stacked=stacked)
        if _is_quant_scale(path):
            # per-output-channel scale [..., out]: keep only the output
            # axis's sharding, on the LAST dim (a rank-1 P(tail) on an
            # [L, E, F] expert scale would land tp on L instead of F),
            # plus the layer dim over pp for stacked leaves
            tail = spec[-1] if len(spec) else None
            nd = leaf.ndim if hasattr(leaf, "ndim") else 1
            lead = AXIS_PP if stacked and nd >= 2 else None
            spec = P(lead, *([None] * max(0, nd - 2)), tail) if nd >= 2 \
                else P(tail)
        return spec

    return jax.tree_util.tree_map_with_path(one, params)


def shardings_for(tree: Any, mesh: Mesh,
                  specs: Any | None = None) -> Any:
    """Pytree of NamedSharding for `tree` on `mesh`, with non-dividing axes
    replicated. `tree` may hold arrays or ShapeDtypeStructs."""
    specs = specs if specs is not None else param_specs(tree)

    def one(leaf, spec):
        return NamedSharding(mesh, fit_spec(spec, leaf.shape, mesh))

    return jax.tree_util.tree_map(one, tree, specs)


def shard_params(params: Any, mesh: Mesh) -> Any:
    """Place an existing (host/single-device) param tree onto the mesh."""
    return jax.device_put(params, shardings_for(params, mesh))


# -- activations and caches -------------------------------------------------

def batch_spec() -> P:
    """Tokens/labels [B, S]: batch over (dp, fsdp), sequence over sp."""
    return P(DATA_AXES, AXIS_SP)


def activation_spec(ndim: int = 3) -> P:
    """Activations [B, S, D]: batch over (dp, fsdp), sequence over sp,
    feature replicated (tp lives inside the per-layer matmuls)."""
    if ndim == 2:
        return P(DATA_AXES, AXIS_SP)
    return P(DATA_AXES, AXIS_SP, None)


def activation_constraint(mesh: Mesh) -> Callable:
    """`constrain` hook for model forwards: pins [B, S, D] activations to
    the dp/sp layout so GSPMD has a stable anchor between layers."""

    def constrain(x):
        if not hasattr(x, "ndim") or x.ndim < 2:
            return x
        spec = fit_spec(activation_spec(x.ndim), x.shape, mesh)
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

    return constrain


def kv_cache_specs(mesh: Mesh, cache) -> Any:
    """Shardings for a models.llama.KVCache: [L, B, Smax, KV, hd] — batch
    over data axes, kv-heads over tp, everything else local. Int8 caches
    carry per-vector scale planes [L, B, Smax, KV] that shard identically
    (same axes minus head_dim)."""
    kv = P(None, DATA_AXES, None, AXIS_TP, None)
    sc = P(None, DATA_AXES, None, AXIS_TP)
    ln = P(DATA_AXES)

    def fit(spec, leaf):
        return NamedSharding(mesh, fit_spec(spec, leaf.shape, mesh))

    quant = getattr(cache, "k_scale", None) is not None
    return type(cache)(
        k=fit(kv, cache.k),
        v=fit(kv, cache.v),
        lengths=fit(ln, cache.lengths),
        k_scale=fit(sc, cache.k_scale) if quant else None,
        v_scale=fit(sc, cache.v_scale) if quant else None,
    )


def paged_cache_specs(mesh: Mesh, cache) -> Any:
    """Shardings for a models.paged_llama.PagedKVCache: [L, N, T, KV,
    hd] pools shard KV-heads over tp ONLY — the block axis stays
    whole on every device because the host-owned block table (ids
    into that axis) is global dispatch data, and block scatter/gather
    index it with traced values (fine on an unsharded axis, a
    full-rematerialization hazard on a sharded one). lengths and the
    table are replicated: paged serving on a mesh is a
    tensor-parallel configuration; data axes fit to nothing."""
    kv = P(None, None, None, AXIS_TP, None)
    sc = P(None, None, None, AXIS_TP)

    def fit(spec, leaf):
        return NamedSharding(mesh, fit_spec(spec, leaf.shape, mesh))

    quant = getattr(cache, "k_scale", None) is not None
    return type(cache)(
        k=fit(kv, cache.k),
        v=fit(kv, cache.v),
        lengths=NamedSharding(mesh, P()),
        k_scale=fit(sc, cache.k_scale) if quant else None,
        v_scale=fit(sc, cache.v_scale) if quant else None,
    )


def attention_shard_axes(mesh: Mesh, batch: int, n_heads: int,
                         n_kv_heads: int) -> tuple[tuple, str | None]:
    """(batch_axes, head_axis) for shard_map'ing an attention kernel on
    ``mesh``: batch over the data axes when their product divides it,
    query/KV heads over tp when tp divides both counts. Mirrors
    fit_spec's replicate-on-non-divide rule, so the specs the ops/*_auto
    dispatchers build from this always agree with the cache placements
    kv_cache_specs / paged_cache_specs produce — a mismatch would make
    GSPMD gather the cache at the shard_map boundary. head_axis is None
    exactly when tp would split a KV head (the jnp-fallback condition,
    same predicate as kv_head_shards)."""
    nb = 1
    for ax in DATA_AXES:
        nb *= mesh.shape.get(ax, 1)
    batch_axes = DATA_AXES if nb > 1 and batch % nb == 0 else ()
    tp = mesh.shape.get(AXIS_TP, 1)
    head_axis = AXIS_TP if (tp > 1 and n_heads % tp == 0
                            and n_kv_heads % tp == 0) else None
    return batch_axes, head_axis


def kv_head_shards(mesh: Mesh, n_kv_heads: int) -> int:
    """How many tp shards the KV-head axis actually splits into on
    ``mesh`` — mirrors fit_spec's divisibility rule (a tp that does
    not divide the head count replicates instead). This is the shard
    count the per-shard offload codec frames and the T2 namespace
    key by (docs/advanced-guide/multichip-serving.md)."""
    tp = mesh.shape.get(AXIS_TP, 1)
    return tp if tp > 1 and n_kv_heads % tp == 0 else 1


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
