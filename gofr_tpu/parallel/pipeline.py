"""Pipeline parallelism: GPipe-style microbatch conveyor over the pp axis.

The reference has no parallelism at all (SURVEY §2); this is the
TPU-native pp story, built the way the rest of the parallel layer is —
named mesh axes and collectives the compiler can see:

  - The [L, ...]-stacked layer weights shard L over ``pp``
    (sharding.spec_for ``stacked=True``): stage s owns layers
    [s*L/pp, (s+1)*L/pp) as a LOCAL stack — no gathering, ever.
  - The step runs inside ``jax.shard_map`` MANUAL over pp (and sp when
    the mesh has it): dp/fsdp/ep/tp stay "auto", so GSPMD keeps
    partitioning the batch and the per-layer matmuls exactly as in the
    non-pp step. pp composes with the other axes instead of replacing
    them (Megatron-style dp x pp x tp).
  - Microbatches conveyor through stages with ``lax.ppermute``: at tick
    t, stage s works on microbatch t-s; activations AND their lengths
    ride the conveyor (the causal mask travels with its microbatch).
    The last stage computes logits+loss for each microbatch as it
    drains; a psum over pp publishes the scalar. Autodiff reverses the
    ppermutes — backward is the same conveyor in reverse, and grads
    accumulate over microbatches by construction.
  - Bubbles: the first/last pp-1 ticks compute garbage on idle stages
    (injected zeros). Their outputs are never selected into the loss,
    so correctness is unconditional; the waste is the standard GPipe
    bubble fraction (pp-1)/(n_micro+pp-1) — raise n_microbatches to
    amortize.
  - **pp x sp (long-context pipelining)**: with sp > 1 the manual set
    grows to {pp, sp} and each stage holds only its SEQUENCE SHARD of
    each microbatch ([mb, S/sp, D] rides the conveyor). Attention runs
    ``ops.ring_attention.ring_causal_attention`` DIRECTLY — the stage
    is already manual over sp, so the ring's ppermutes compose with the
    conveyor's without nesting shard_maps. Tokens stay replicated over
    sp (ids are cheap); embeddings/logits/loss are computed on the
    local shard only, and the loss shift across shard boundaries reads
    its targets from the replicated token ids.

Scope: dense decoders and dense-dispatch MoE (aux loss collected
exactly across stages — see make_pp_loss_fn). pp with grouped MoE
dispatch is rejected (XLA partitioner limitation — dense dispatch
works). Serving meshes keep pp=1 (decode wants every layer resident;
pipelining decode trades latency for nothing at batch-1 token cadence).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..models import llama
from ..models.common import ModelConfig
from .mesh import AXIS_PP, AXIS_SP, Mesh
from .train import loss_parts_local


def _manual_shard_map(f, mesh, *, in_specs, out_specs, manual):
    """shard_map manual over ``manual`` axes, auto everywhere else —
    bridging the new top-level API (axis_names/check_vma) and the
    pre-0.4.35 experimental one (auto/check_rep)."""
    try:
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, axis_names=manual,
                             check_vma=False)
    except (AttributeError, TypeError):
        # older jax: either no top-level shard_map at all, or a top-level
        # alias that still has the experimental signature (auto/check_rep
        # instead of axis_names/check_vma) and rejects the kwargs above
        from jax.experimental.shard_map import shard_map

        auto = frozenset(mesh.axis_names) - set(manual)
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, auto=auto, check_rep=False)


def _stage_apply(layers_local: Any, x: jnp.ndarray, cfg: ModelConfig,
                 cos, sin, positions, valid, attend) -> jnp.ndarray:
    """Run this stage's local layer stack over one microbatch (shard)."""

    def body(x, layer_w):
        x, _, probs = llama._layer(x, layer_w, cfg, cos, sin, positions,
                                   kv_write=lambda k, v: (k, v),
                                   attend=attend, valid=valid)
        return x, probs  # [mb, S, E] per layer for MoE, else None

    x, probs = jax.lax.scan(body, x, layers_local)
    return x, probs


def make_pp_loss_fn(cfg: ModelConfig, mesh: Mesh, *, n_microbatches: int,
                    remat: bool = True, moe_aux_weight: float = 0.01):
    """loss_fn(params, tokens [B,S], lengths [B]) -> (loss, aux) running
    the forward as a pp-stage conveyor (sequence-sharded over sp when
    the mesh has it). Differentiable; use under jax.value_and_grad
    exactly like the dense loss_fn.

    MoE aux collection under pp: each stage accumulates per-local-layer
    [E] vectors of top-1 counts and router-probability sums over the
    microbatches it actually processed (bubble ticks weighted 0), the
    balance term sums over local layers, and one psum over pp (and sp)
    rebuilds train.load_balance_loss EXACTLY — the nonlinear f·P product
    is formed per layer AFTER accumulation, never across partial
    batches or shards."""
    pp = mesh.shape[AXIS_PP]
    n_sp = mesh.shape.get(AXIS_SP, 1)
    if cfg.n_layers % pp:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by pp={pp}")
    if cfg.n_experts > 0 and cfg.moe_capacity_factor > 0:
        # XLA's SPMD partitioner CHECK-crashes (spmd_partitioner_util.cc
        # replica-group mismatch) partitioning the grouped-dispatch
        # scatter over an auto ep axis inside a manual-pp shard_map;
        # dense dispatch partitions fine. Reject rather than segfault.
        raise ValueError("pp + grouped MoE dispatch (moe_capacity_factor"
                         " > 0) is not supported; use dense dispatch "
                         "(moe_capacity_factor=0) under pp")
    n_micro = int(n_microbatches)
    perm = [(i, i + 1) for i in range(pp - 1)]  # no wraparound: stage 0
    # receives ppermute's zero-fill, immediately overwritten by injection

    def pp_body(params, tokens, lengths):
        stage = jax.lax.axis_index(AXIS_PP)
        B, S = tokens.shape
        if B % n_micro:
            raise ValueError(f"batch {B} not divisible by "
                             f"n_microbatches={n_micro}")
        if S % n_sp:
            raise ValueError(f"sequence {S} not divisible by sp={n_sp}")
        mb = B // n_micro
        sn = S // n_sp
        cos, sin = llama.get_rope_tables(cfg, S)
        if n_sp > 1:
            g0 = jax.lax.axis_index(AXIS_SP) * sn
        else:
            g0 = jnp.int32(0)
        positions = jnp.broadcast_to(
            g0 + jnp.arange(sn, dtype=jnp.int32), (mb, sn))

        # every stage embeds ITS shard (embedding + token ids replicate
        # over pp/sp; slicing before the embedding lookup keeps the
        # [*, Sn, D] activations — the memory that matters — sharded)
        toks_local = jax.lax.dynamic_slice_in_dim(tokens, g0, sn, axis=1)
        x_all = params["embedding"][toks_local].astype(cfg.jdtype)
        xs = x_all.reshape(n_micro, mb, sn, -1)
        toks_mb = tokens.reshape(n_micro, mb, S)
        lens_mb = lengths.reshape(n_micro, mb)

        def tick_compute(layers_local, x_in, lens_in):
            valid = positions < lens_in[:, None]
            if n_sp > 1:
                from ..ops.ring_attention import ring_causal_attention

                def attend(q, k, v):
                    return ring_causal_attention(q, k, v, lens_in,
                                                 axis_name=AXIS_SP)
            else:
                def attend(q, k, v):
                    return llama.causal_attention(q, k, v, mask=valid)
            return _stage_apply(layers_local, x_in, cfg, cos, sin,
                                positions, valid, attend)

        if remat:
            tick_compute = jax.checkpoint(tick_compute)

        moe = cfg.n_experts > 0
        state_x = jnp.zeros_like(xs[0])
        state_len = jnp.zeros((mb,), lengths.dtype)
        nll_sum = jnp.zeros((), jnp.float32)
        mask_sum = jnp.zeros((), jnp.float32)
        if moe:
            l_local = cfg.n_layers // pp
            cnt_sum = jnp.zeros((l_local, cfg.n_experts), jnp.float32)
            prob_sum = jnp.zeros((l_local, cfg.n_experts), jnp.float32)
        last = pp - 1
        for t in range(n_micro + pp - 1):
            j_in = min(t, n_micro - 1)     # microbatch entering stage 0
            x_in = jnp.where(stage == 0, xs[j_in], state_x)
            lens_in = jnp.where(stage == 0, lens_mb[j_in], state_len)
            y, probs = tick_compute(params["layers"], x_in, lens_in)
            if moe:
                # this tick is real work iff a microbatch is at this stage
                # (bubble outputs are finite — masked attention uses a
                # finite NEG_INF — so a 0-weight cleanly removes them)
                in_range = ((t - stage >= 0) & (t - stage < n_micro)
                            ).astype(jnp.float32)
                vmask = (positions < lens_in[:, None]
                         ).astype(jnp.float32)[None, ..., None]
                top1 = jax.nn.one_hot(jnp.argmax(probs, axis=-1),
                                      cfg.n_experts)  # [l, mb, Sn, E]
                cnt_sum = cnt_sum + in_range * jnp.sum(
                    top1 * vmask, axis=(1, 2))
                prob_sum = prob_sum + in_range * jnp.sum(
                    probs * vmask, axis=(1, 2))
            j_out = t - last               # microbatch draining at the
            if 0 <= j_out < n_micro:       # last stage this tick (static)
                logits = llama._logits(params, cfg, y)  # final_norm inside
                n, m = loss_parts_local(logits, toks_mb[j_out], lens_in,
                                        g0, S)
                on_last = (stage == last).astype(jnp.float32)
                nll_sum = nll_sum + n * on_last
                mask_sum = mask_sum + m * on_last
            state_x = jax.lax.ppermute(y, AXIS_PP, perm)
            state_len = jax.lax.ppermute(lens_in, AXIS_PP, perm)
        # only the last stage accumulated; sp shards each hold partial
        # sums: psum over both manual axes publishes the global scalars
        nll_sum = jax.lax.psum(nll_sum, AXIS_PP)
        mask_sum = jax.lax.psum(mask_sum, AXIS_PP)
        if n_sp > 1:
            nll_sum = jax.lax.psum(nll_sum, AXIS_SP)
            mask_sum = jax.lax.psum(mask_sum, AXIS_SP)
        lm = nll_sum / jnp.maximum(mask_sum, 1.0)
        if not moe:
            return lm, jnp.zeros(())
        # per-layer f·P AFTER full accumulation (train.load_balance_loss
        # shape: E * mean_layers(sum_e f_e P_e) over valid tokens)
        total = jnp.maximum(
            jnp.sum(jnp.minimum(lengths, S).astype(jnp.float32)), 1.0)
        cnt_g = jax.lax.psum(cnt_sum, AXIS_SP) if n_sp > 1 else cnt_sum
        prob_g = jax.lax.psum(prob_sum, AXIS_SP) if n_sp > 1 else prob_sum
        local = jnp.sum((cnt_g / total) * (prob_g / total))
        aux = cfg.n_experts * jax.lax.psum(local, AXIS_PP) / cfg.n_layers
        return lm + moe_aux_weight * aux, aux

    def loss_fn(params, tokens, lengths):
        # manual over pp (+ sp): layer stacks enter stage-local
        # ([L/pp]); everything else replicates over the manual axes.
        # dp/fsdp/ep/tp stay auto — GSPMD partitions inside the stages
        # as usual. in_specs is a prefix pytree: one spec per top-level
        # param entry.
        param_specs = {k: (P(AXIS_PP) if k == "layers" else P())
                       for k in params}
        manual = {AXIS_PP} | ({AXIS_SP} if n_sp > 1 else set())
        fn = _manual_shard_map(pp_body, mesh,
                               in_specs=(param_specs, P(), P()),
                               out_specs=(P(), P()), manual=manual)
        return fn(params, tokens, lengths)

    return loss_fn
