"""Multi-host bootstrap: the DCN half of the distributed backend.

The reference's whole deployment model is multi-process services talking
over HTTP/gRPC (pkg/gofr/gofr.go:108-164); its TPU-native equivalent is
the PJRT distributed runtime: process 0 runs the coordinator, every
process connects to it, and `jax.devices()` becomes the GLOBAL device
list — after which the mesh/sharding layer (parallel.mesh/sharding) works
unchanged, with XLA routing collectives over ICI within a slice and DCN
across hosts. Nothing else in the framework knows about hosts.

Config keys (read by `maybe_initialize`, wired in App startup BEFORE any
datasource touches the backend):

  TPU_COORDINATOR     "host:port" of process 0's coordinator service.
                      Unset => single-process (no-op).
  TPU_PROCESS_ID      this process's rank (0..N-1). Defaults to 0.
  TPU_NUM_PROCESSES   world size N. Defaults to 1.
  TPU_COORDINATOR_TIMEOUT_S  seconds to wait for the coordinator
                      (default 60).

On TPU pods the three values come from the deployment layer (one process
per host); the same keys drive multi-process CPU testing
(tests/test_distributed.py spawns two local processes against a
127.0.0.1 coordinator).
"""

from __future__ import annotations

import jax

_initialized = False  # set by maybe_initialize; survives jax-internal moves


def is_initialized() -> bool:
    """True once this process joined a distributed runtime."""
    if _initialized:
        return True
    try:  # best-effort probe (private API — the module flag above is the
        # durable signal; this catches out-of-band jax.distributed use)
        from jax._src import distributed

        return distributed.global_state.client is not None
    except Exception:
        return False


def maybe_initialize(cfg, logger=None) -> bool:
    """Join the PJRT distributed runtime if TPU_COORDINATOR is configured.

    Returns True when this call initialized (or a prior call already had);
    False for the single-process default. Safe to call more than once.
    Must run before the first backend use in the process — jax backends
    initialized pre-join would see only local devices.
    """
    coordinator = (cfg.get("TPU_COORDINATOR") or "").strip()
    if not coordinator:
        return False
    if is_initialized():
        return True
    process_id = cfg.get_int("TPU_PROCESS_ID", 0)
    num_processes = cfg.get_int("TPU_NUM_PROCESSES", 1)
    timeout_s = cfg.get_int("TPU_COORDINATOR_TIMEOUT_S", 60)
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        initialization_timeout=timeout_s,
    )
    global _initialized
    _initialized = True
    if logger is not None:
        logger.info({
            "event": "distributed runtime joined",
            "coordinator": coordinator,
            "process_id": process_id,
            "num_processes": num_processes,
            "global_devices": jax.device_count(),
            "local_devices": jax.local_device_count(),
        })
    return True


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_coordinator() -> bool:
    """Process 0 owns singleton side effects (metrics export, ledger
    writes, checkpoint manifests) in multi-host serving."""
    return jax.process_index() == 0


def shutdown() -> None:
    """Leave the distributed runtime (test teardown; production processes
    exit instead)."""
    global _initialized
    if is_initialized():
        jax.distributed.shutdown()
    _initialized = False
