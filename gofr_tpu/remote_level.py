"""Remote log-level polling.

Reference: pkg/gofr/logging/dynamicLevelLogger.go:17-97 — a wrapper polls
REMOTE_LOG_URL every REMOTE_LOG_FETCH_INTERVAL (default 15s) and calls the
logger's private changeLevel. Here the poller mutates the shared Logger
directly (levels are a single int read; no wrapper indirection needed).
"""

from __future__ import annotations

import json
import threading
import urllib.request

from .glog import Logger, LogLevel


def _extract_level(payload: dict) -> str | None:
    """Accept common shapes: {"data":{"logLevel": X}} / {"data":{"LOG_LEVEL": X}}
    / {"level": X} (reference fetchAndUpdateLogLevel parses a service-config
    envelope, dynamicLevelLogger.go:65-97)."""
    if not isinstance(payload, dict):
        return None
    data = payload.get("data", payload)
    if isinstance(data, list) and data:
        data = data[0]
    if isinstance(data, dict):
        for key in ("logLevel", "LOG_LEVEL", "level"):
            v = data.get(key)
            if isinstance(v, str):
                return v
            if isinstance(v, dict) and isinstance(v.get("value"), str):
                return v["value"]
    return None


class RemoteLevelPoller:
    def __init__(self, logger: Logger, url: str, interval: float = 15.0, http_get=None):
        self.logger = logger
        self.url = url
        self.interval = interval
        self._http_get = http_get or self._default_get
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True, name="remote-log-level")
        self._thread.start()

    @staticmethod
    def _default_get(url: str) -> bytes:
        with urllib.request.urlopen(url, timeout=5) as r:
            return r.read()

    def poll_once(self) -> None:
        try:
            payload = json.loads(self._http_get(self.url))
        except Exception:
            return
        level = _extract_level(payload)
        if level is None:
            return
        try:
            self.logger.change_level(LogLevel[level.strip().upper()])
        except KeyError:
            pass

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.poll_once()

    def stop(self) -> None:
        self._stop.set()
