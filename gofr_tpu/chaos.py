"""Fault-injection harness: seeded chaos at the serving stack's seams.

No reference equivalent. Resilience claims (deadlines drop expired work,
the admission gate sheds instead of queueing, the generation loop
recovers from device loss, the breaker+retry client survives flapping
backends) are only true if something keeps proving them — this module
is that something, wired into CI (``tools/chaos_bench.py --smoke`` and
the ``chaos`` pytest marker).

Model: a ``ChaosSchedule`` holds per-seam rules (latency, injected
errors, or both); production code calls ``chaos.fire(SEAM)`` at a fixed
set of seams, which is a single ``None`` check when no schedule is
installed — the hot path pays one attribute read. Decisions are
DETERMINISTIC: every firing is derived from ``(seed, seam, call_index)``
only, so the same schedule driven by the same call counts makes the
same injections — the property the CI smoke asserts by digesting the
decision stream twice (two consecutive runs must agree).

Seams (grep for ``chaos.fire``):

  ==================  =====================================================
  BATCHER_DISPATCH    tpu/batcher._run_one, before the runner executes —
                      models slow/failing device dispatch for ``predict``
  GENERATOR_PREFILL   tpu/generator._start, before the prefill dispatch —
                      a raised error fails ONE stream (admission error path)
  GENERATOR_CHUNK     tpu/generator._chunk_lattice, before EACH mid-chunk
                      dispatch of a chunked prefill — indexing by chunk
                      lets a schedule kill chunk N of a long admission
                      specifically (mid-chunk DeviceLost recovery)
  GENERATOR_STEP      tpu/generator._loop, before a decode tick — a raised
                      ``DeviceLost`` exercises the full loop-recovery path
                      (cache reallocation, waiter fail-fast)
  GATEWAY_PICK        gateway/router.pick, before each replica-pick
                      decision — injected latency widens the
                      pick/drain race deterministically; an injected
                      error fails THAT pick (typed 503 to the client,
                      never a gateway crash)
  GATEWAY_RELAY       gateway/relay, before EACH forward attempt —
                      an injected error is treated as that attempt's
                      transport loss, driving the pre-first-token
                      failover path on attempt N exactly (``every=N``)
  GATEWAY_MIDSTREAM   gateway relay loop, before EACH relayed token
                      line AFTER the first — an injected error is that
                      line's transport loss, driving the POST-commit
                      auto-resume path after exactly N relayed tokens
                      (``every=N, limit=1``)
  GENERATOR_MIDKILL   tpu/generator._deliver, after EACH delivered
                      token — an injected error kills THAT stream
                      after exactly N emitted tokens (``every=N,
                      limit=1``), the in-process stand-in for a
                      replica SIGKILL mid-stream; the typed error line
                      carries a resume token
  GRPC_STREAM         grpcx/server._handle_stream, before dispatch —
                      transport-level latency/errors per RPC
  HBM_ALLOC           tpu/hbm lease points (lease/alloc/check) — an
                      injected ``ResourceExhausted`` models a device
                      allocation failure that survived reclaim+retry:
                      the arbiter sheds that request (429/
                      RESOURCE_EXHAUSTED + Retry-After) and the
                      process keeps serving. ``every=N`` kills
                      allocation N deterministically
  HTTP_REQUEST        http/server._handle, before routing
  PD_INGEST           pd/ingest._on_kv, before each received KV frame
                      is validated/assembled — an injected error is
                      THAT transfer's fault: the ingest server rejects
                      the one request typed (502 KVTransferError over
                      the wire) and the reader loop keeps serving
                      every other stream on the connection
  SERVICE_REQUEST     service/client._do, before the network hop —
                      feeds the retry/breaker composition tests
  ==================  =====================================================

Socket-level faults don't need a seam: ``slow_loris`` (dribble an
incomplete HTTP request) and ``slow_h2_preface`` (dribble a partial
HTTP/2 client preface) attack a live listener from the outside, proving
one stuck peer can't wedge the accept path.
"""

from __future__ import annotations

import contextlib
import hashlib
import random
import socket
import threading
import time

__all__ = [
    "BATCHER_DISPATCH", "GATEWAY_MIDSTREAM", "GATEWAY_PICK",
    "GATEWAY_RELAY", "GENERATOR_CHUNK", "GENERATOR_MIDKILL",
    "GENERATOR_PREFILL", "GENERATOR_STEP",
    "GRPC_STREAM", "HBM_ALLOC", "HTTP_REQUEST", "PD_INGEST",
    "SERVICE_REQUEST", "SEAMS",
    "ChaosSchedule", "DeviceLost", "ResourceExhausted", "Rule",
    "active", "fire", "install", "scope", "slow_h2_preface", "slow_loris",
    "uninstall",
]

BATCHER_DISPATCH = "batcher.dispatch"
GATEWAY_MIDSTREAM = "gateway.midstream"
GATEWAY_PICK = "gateway.pick"
GATEWAY_RELAY = "gateway.relay"
GENERATOR_CHUNK = "generator.chunk"
GENERATOR_MIDKILL = "generator.midkill"
GENERATOR_PREFILL = "generator.prefill"
GENERATOR_STEP = "generator.step"
GRPC_STREAM = "grpc.stream"
HBM_ALLOC = "hbm.alloc"
HTTP_REQUEST = "http.request"
PD_INGEST = "pd.ingest"
SERVICE_REQUEST = "service.request"

SEAMS = (BATCHER_DISPATCH, GATEWAY_MIDSTREAM, GATEWAY_PICK, GATEWAY_RELAY,
         GENERATOR_CHUNK, GENERATOR_MIDKILL, GENERATOR_PREFILL,
         GENERATOR_STEP, GRPC_STREAM, HBM_ALLOC,
         HTTP_REQUEST, PD_INGEST, SERVICE_REQUEST)


class DeviceLost(RuntimeError):
    """Injected stand-in for an accelerator runtime failure (the class
    of error a real XLA dispatch surfaces when a chip drops off the
    tunnel). Raised at GENERATOR_STEP / BATCHER_DISPATCH it takes the
    same except-paths real device loss takes."""


class ResourceExhausted(RuntimeError):
    """Injected stand-in for a device allocation failure (the
    RESOURCE_EXHAUSTED ``XlaRuntimeError`` a real OOM surfaces).
    Raised at HBM_ALLOC it takes the arbiter's shed path; raised at
    BATCHER_DISPATCH it exercises the batcher's reclaim-then-retry.
    The message carries the marker ``tpu/hbm.is_oom_error`` keys on,
    so the classifier treats injected and real OOMs identically."""

    def __init__(self, msg: str = "injected RESOURCE_EXHAUSTED: device "
                                  "memory exhausted (chaos)"):
        super().__init__(msg)


class Rule:
    """One seam's injection policy.

    latency/jitter: every call sleeps ``latency + U[0, jitter)`` seconds
      (the uniform draw is deterministic per call index).
    error: exception INSTANCE, class, or zero-arg factory raised on
      firing calls.
    every: fire on every Nth call (deterministic cadence), OR
    p: fire with probability ``p`` per call (deterministic per-index
      Bernoulli draw from the schedule's seed).
    limit: stop firing errors after this many (0 = unlimited); latency
      keeps applying.
    """

    __slots__ = ("latency", "jitter", "error", "every", "p", "limit")

    def __init__(self, latency: float = 0.0, jitter: float = 0.0,
                 error=None, every: int = 0, p: float = 0.0,
                 limit: int = 0):
        if every and p:
            raise ValueError("rule takes every= OR p=, not both")
        self.latency = float(latency)
        self.jitter = float(jitter)
        self.error = error
        self.every = int(every)
        self.p = float(p)
        self.limit = int(limit)

    def _make_error(self) -> BaseException:
        err = self.error
        if isinstance(err, BaseException):
            return err
        return err()  # class or factory

    def decide(self, seed: int, seam: str, idx: int) -> tuple[bool, float]:
        """(fire_error, sleep_s) for call ``idx`` — a pure function of
        (seed, seam, idx), which is what makes schedules replayable."""
        rng = random.Random(f"{seed}:{seam}:{idx}")
        sleep_s = self.latency + (rng.random() * self.jitter
                                  if self.jitter > 0 else 0.0)
        fire = False
        if self.error is not None:
            if self.every > 0:
                fire = (idx % self.every) == self.every - 1
            elif self.p > 0:
                fire = rng.random() < self.p
        return fire, sleep_s


class ChaosSchedule:
    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._rules: dict[str, Rule] = {}
        self._lock = threading.Lock()
        self._calls: dict[str, int] = {}
        self._fired: dict[str, int] = {}
        self._slept: dict[str, float] = {}

    def on(self, seam: str, *, latency: float = 0.0, jitter: float = 0.0,
           error=None, every: int = 0, p: float = 0.0,
           limit: int = 0) -> "ChaosSchedule":
        """Attach a rule to a seam; chainable. Unknown seam names are
        allowed (tests may define private seams) but the canonical set
        is ``SEAMS``."""
        self._rules[seam] = Rule(latency=latency, jitter=jitter, error=error,
                                 every=every, p=p, limit=limit)
        return self

    # -- the injection point --------------------------------------------------
    def fire(self, seam: str) -> None:
        rule = self._rules.get(seam)
        if rule is None:
            return
        with self._lock:
            idx = self._calls.get(seam, 0)
            self._calls[seam] = idx + 1
        fire_error, sleep_s = rule.decide(self.seed, seam, idx)
        if sleep_s > 0:
            with self._lock:
                self._slept[seam] = self._slept.get(seam, 0.0) + sleep_s
            time.sleep(sleep_s)
        if fire_error:
            with self._lock:
                fired = self._fired.get(seam, 0)
                if rule.limit and fired >= rule.limit:
                    return
                self._fired[seam] = fired + 1
            raise rule._make_error()

    # -- introspection --------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {"seed": self.seed,
                    "calls": dict(self._calls),
                    "errors_fired": dict(self._fired),
                    "injected_sleep_s": {k: round(v, 6)
                                         for k, v in self._slept.items()}}

    def decisions(self, seam: str, n: int) -> list[tuple[bool, float]]:
        """The first ``n`` decisions a seam WILL make — pure replay, no
        state touched. The determinism oracle for tests and the smoke
        digest."""
        rule = self._rules.get(seam)
        if rule is None:
            return [(False, 0.0)] * n
        return [rule.decide(self.seed, seam, i) for i in range(n)]

    def digest(self, calls_per_seam: int = 256) -> str:
        """Hex digest of the full decision stream over every configured
        seam: two runs of the same seeded schedule MUST produce the
        same digest (the CI determinism gate diffs exactly this)."""
        h = hashlib.sha256()
        for seam in sorted(self._rules):
            for fire, sleep_s in self.decisions(seam, calls_per_seam):
                h.update(f"{seam}|{int(fire)}|{sleep_s:.9f};".encode())
        return h.hexdigest()


# -- module-level installation (what the seams consult) -----------------------
_ACTIVE: ChaosSchedule | None = None


def install(schedule: ChaosSchedule) -> ChaosSchedule:
    global _ACTIVE
    _ACTIVE = schedule
    return schedule


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


def active() -> ChaosSchedule | None:
    return _ACTIVE


def fire(seam: str) -> None:
    """Called by production code at each seam. One None-check when no
    chaos is installed — safe on hot paths."""
    s = _ACTIVE
    if s is not None:
        s.fire(seam)


@contextlib.contextmanager
def scope(schedule: ChaosSchedule):
    """Install for the duration of a with-block (tests/bench phases)."""
    install(schedule)
    try:
        yield schedule
    finally:
        uninstall()


# -- socket-level faults (no seam needed: they attack a live listener) --------
def slow_loris(host: str, port: int, *, path: str = "/",
               duration: float = 1.0, interval: float = 0.05) -> int:
    """Hold a connection open dribbling an incomplete HTTP request one
    byte per ``interval`` for ``duration`` seconds, then drop it without
    ever finishing the headers. Returns bytes sent. A healthy threaded
    server serves other clients throughout (one handler thread is tied
    up, nothing else)."""
    payload = (f"GET {path} HTTP/1.1\r\nHost: {host}\r\n"
               "X-Slow: loris\r\n").encode()
    sent = 0
    deadline = time.monotonic() + duration
    with socket.create_connection((host, port), timeout=5.0) as s:
        for b in payload:
            if time.monotonic() >= deadline:
                break
            try:
                s.send(bytes([b]))
                sent += 1
            except OSError:
                break  # server gave up on us first — also a pass
            time.sleep(interval)
    return sent


def slow_h2_preface(host: str, port: int, *, duration: float = 1.0,
                    interval: float = 0.05) -> int:
    """The gRPC flavor: dribble a PARTIAL HTTP/2 client preface, then
    hang up. The connection thread must stay parked in its preface read
    without consuming a stream or blocking the accept loop."""
    preface = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"[:-4]  # never completes
    sent = 0
    deadline = time.monotonic() + duration
    with socket.create_connection((host, port), timeout=5.0) as s:
        for b in preface:
            if time.monotonic() >= deadline:
                break
            try:
                s.send(bytes([b]))
                sent += 1
            except OSError:
                break
            time.sleep(interval)
    return sent
