"""gofr_tpu — a TPU-native microservice framework.

A brand-new framework with the capabilities of GoFr (the Go reference at
/root/reference: one ``App`` running HTTP/gRPC/metrics servers, pub/sub
subscribers and CLI commands behind a single context-based handler signature,
with a dependency container, datasources, inter-service clients, migrations
and out-of-the-box observability — see reference pkg/gofr/gofr.go:29-46)
PLUS a first-class TPU inference path: JAX/XLA models, request-coalescing
continuous batching, Pallas kernels and ICI-sharded serving via
``jax.sharding``.

Handler signature (reference pkg/gofr/handler.go:12 uses
``func(c *Context) (interface{}, error)``; the Pythonic equivalent):

    @app.get("/greet")
    def greet(ctx):
        return {"hello": ctx.request.param("name")}

Errors are raised, not returned: raise ``gofr_tpu.errors.HTTPError`` (or a
subclass) to control the response status.
"""

from .version import __version__, FRAMEWORK
from .errors import (
    GofrError,
    HTTPError,
    BadRequest,
    Unauthorized,
    Forbidden,
    NotFound,
    EntityNotFound,
    InternalServerError,
)
from .errors import DeadlineExceeded, ServiceUnavailable, TooManyRequests
from .config import Config, EnvConfig, MapConfig
from .glog import Logger, LogLevel, new_logger
from .context import Context
from .container import Container
from .app import App, new_app, new_cmd
from .resilience import AdmissionGate, Deadline, current_deadline, deadline_scope

__all__ = [
    "__version__",
    "FRAMEWORK",
    "App",
    "new_app",
    "new_cmd",
    "Context",
    "Container",
    "Config",
    "EnvConfig",
    "MapConfig",
    "Logger",
    "LogLevel",
    "new_logger",
    "GofrError",
    "HTTPError",
    "BadRequest",
    "Unauthorized",
    "Forbidden",
    "NotFound",
    "EntityNotFound",
    "InternalServerError",
    "ServiceUnavailable",
    "TooManyRequests",
    "DeadlineExceeded",
    "AdmissionGate",
    "Deadline",
    "current_deadline",
    "deadline_scope",
]
