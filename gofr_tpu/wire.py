"""Transport fast-path primitives shared by grpcx and the HTTP streamer.

Three building blocks behind the own-wire TTFT fix (ISSUE 2; the pure-
Python transport added ~142 ms on top of the engine path in the last
hardware capture):

  SocketWriter — vectored (``sendmsg``) frame writes with an ordered
      backlog, so a producer thread can hand bytes to the wire WITHOUT
      ever blocking on the socket or on another writer. One syscall
      carries many frames; partial/contended writes park in the backlog
      and ride out with the next write.

  Outbox — an ordered multi-producer send queue drained by whichever
      thread is available (thread-combining), never by a dedicated
      flusher thread. This is the write scheduler: bursts (a fused
      decode block delivering K tokens back-to-back) coalesce into one
      vectored write instead of K wakeups and K syscalls.

  PushStream / MappedStream — a queue-backed item stream with an
      optional zero-handoff *sink*: when a consumer registers one, the
      producing thread delivers items straight into the consumer's send
      path instead of waking a reader thread. GenStream (tpu/generator)
      extends PushStream, which is how first-token bytes go from the
      engine loop's ``_deliver`` to the socket without an intermediate
      thread.

Everything here is stdlib-only and transport-agnostic; grpcx frames and
HTTP chunked encoding both sit on top.
"""

from __future__ import annotations

import collections
import queue
import socket
import threading
import time

from .errors import ConnectionLost

# sendmsg buffer-list cap per syscall — far below any platform IOV_MAX
# (Linux: 1024) while keeping per-call bookkeeping bounded
_IOV_CAP = 64


class SocketWriter:
    """Vectored, backlog-capable socket writer.

    Guarantees:
      - wire byte order equals commit order: a write's bytes are
        committed (to the socket or the backlog) under the internal
        locks before the call returns;
      - ``write(..., block=False)`` NEVER blocks on the socket or on a
        concurrent writer — bytes that cannot leave immediately park in
        the backlog;
      - every blocking write drains the backlog ahead of its own bytes,
        so any stream that *ends* with a blocking write (gRPC trailers,
        the terminal HTTP chunk) leaves the wire fully flushed.
    """

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self._lock = threading.Lock()   # serializes actual socket sends
        self._blk = threading.Lock()    # guards _backlog and _closed
        self._backlog = bytearray()
        self._closed = False
        self.syscalls = 0     # sendmsg calls issued (incl. EAGAIN probes)
        self.bytes_sent = 0
        self.deferred = 0     # nonblocking writes parked without a syscall

    # -- internals -----------------------------------------------------------
    def _take(self, bufs) -> list[memoryview]:
        """Swap out the backlog and append ``bufs`` — the commit point."""
        with self._blk:
            if self._closed:
                raise ConnectionLost("connection closed")
            views: list[memoryview] = []
            if self._backlog:
                views.append(memoryview(bytes(self._backlog)))
                self._backlog.clear()
            views.extend(memoryview(b) for b in bufs if len(b))
            return views

    def _send_vec(self, views: list[memoryview], flags: int) -> int:
        """One bounded sendmsg; returns bytes sent (0 on would-block)."""
        self.syscalls += 1
        try:
            n = self.sock.sendmsg(views[:_IOV_CAP], [], flags)
        except (BlockingIOError, InterruptedError):
            return 0
        self.bytes_sent += n
        return n

    def _drain(self, views: list[memoryview], flags: int) -> int:
        """Send as much of ``views`` as the socket takes; returns bytes
        sent. With ``flags=0`` this blocks until everything is out."""
        total = sum(len(v) for v in views)
        sent = 0
        while sent < total:
            # advance past fully-sent buffers; slice the partial one
            while views and len(views[0]) == 0:
                views.pop(0)
            n = self._send_vec(views, flags)
            if n == 0 and flags:
                return sent
            sent += n
            while n and views:
                if n >= len(views[0]):
                    n -= len(views.pop(0))
                else:
                    views[0] = views[0][n:]
                    n = 0
        return sent

    # -- API -----------------------------------------------------------------
    def write(self, bufs, block: bool = True) -> bool:
        """Write ``bufs`` (an iterable of bytes-likes, or one bytes-like)
        in order. ``block=False`` returns immediately: contended or
        would-block bytes park in the backlog and are flushed by the
        next write on this connection.

        Returns True when everything (backlog included) reached the
        socket, False when bytes were parked — a nonblocking caller
        that gets False must arrange for SOME later write/flush on the
        connection, or the parked bytes sit until the next traffic."""
        if isinstance(bufs, (bytes, bytearray, memoryview)):
            bufs = [bufs]
        if block:
            with self._lock:
                views = self._take(bufs)
                self._drain(views, 0)
            return True
        if not self._lock.acquire(blocking=False):
            # a writer holds the socket: it already swapped the backlog
            # out, so parking here lands AFTER its bytes — commit order
            # is preserved. The next write on the connection flushes.
            with self._blk:
                if self._closed:
                    raise ConnectionLost("connection closed")
                for b in bufs:
                    self._backlog += b
                self.deferred += 1
            return False
        try:
            views = self._take(bufs)
            total = sum(len(v) for v in views)
            sent = self._drain(views, socket.MSG_DONTWAIT)
            if sent < total:
                # _drain advanced ``views`` in place: what remains is
                # exactly the unsent tail
                rest = b"".join(views)
                with self._blk:
                    # unsent tail goes back to the FRONT: bytes parked by
                    # other threads during this send came later
                    self._backlog[:0] = rest
                    self.deferred += 1
                return False
            return True
        finally:
            self._lock.release()

    def flush(self) -> None:
        """Blocking drain of any backlog left by nonblocking writes."""
        self.write([], block=True)

    @property
    def backlog_bytes(self) -> int:
        """Bytes parked by nonblocking writes and not yet on the wire —
        the flow-control signal windowed producers (the PD KV-ship
        path) bound themselves against instead of letting the backlog
        grow without limit on a stalled peer."""
        with self._blk:
            return len(self._backlog)

    def close(self) -> None:
        with self._blk:
            self._closed = True
            self._backlog.clear()
        try:
            # shutdown BEFORE close: it wakes a writer blocked in sendmsg
            # (close alone would deadlock behind the in-progress syscall)
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


def observe_backlog(metrics, backlog_bytes: int, **labels) -> None:
    """Export one outbox-backlog sample (``app_tpu_wire_backlog_bytes``,
    labeled by caller role): the flow-control signal ``backlog_bytes``
    already tracks, made scrapeable so a stalled peer shows up on a
    dashboard before it shows up as a deadline storm. Swallows every
    failure — telemetry must never take a send path down."""
    if metrics is None:
        return
    try:
        metrics.set_gauge("app_tpu_wire_backlog_bytes",
                          float(backlog_bytes), **labels)
    except Exception:
        pass


class Outbox:
    """Ordered send queue with thread-combining flush.

    Producers ``append()`` then ``pump(block=False)`` — which never
    blocks the producer; whichever thread wins the flusher role drains
    everything pending (its own items plus anything other threads
    appended meanwhile) in FIFO order. The owning worker thread calls
    ``pump(block=True)`` to clear stalls and at end-of-stream.

    ``drain(batch, block)`` is the send callback: it consumes a PREFIX
    of ``batch`` and returns how many items it consumed. A blocking
    drain must consume the whole batch; a nonblocking drain may stop
    early (no flow-control credit), which sets ``stalled`` so the
    producer can stop fast-pathing.
    """

    def __init__(self, drain):
        self._drain_cb = drain
        self._items: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._flushing = False
        self.stalled = False

    def append(self, item) -> None:
        with self._lock:
            self._items.append(item)

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def pump(self, block: bool = False) -> None:
        while True:
            with self._lock:
                if self._flushing:
                    if not block:
                        return   # the active flusher will see our items
                    busy = True
                else:
                    if not self._items:
                        return
                    self._flushing = True
                    busy = False
            if busy:
                # a nonblocking flusher is mid-drain; it is brief — yield
                # once and retake (only blocking pumps ever spin here)
                time.sleep(0)
                continue
            try:
                while True:
                    with self._lock:
                        batch = list(self._items)
                    if not batch:
                        break
                    n = self._drain_cb(batch, block)
                    with self._lock:
                        for _ in range(n):
                            self._items.popleft()
                    if n < len(batch):
                        self.stalled = True
                        return
                    self.stalled = False
            finally:
                with self._lock:
                    self._flushing = False
            # items appended between the final emptiness check and the
            # flag clear are picked up by looping (no lost wakeup)


# sentinel a producer-side sink can enqueue (PushStream.wake) to rouse
# the consuming worker without delivering an item — e.g. "the outbox
# stalled with your bytes in it, come flush". Iterating consumers that
# never call wake() never see it.
WAKE = object()


class PushStream:
    """Queue-backed item stream with an optional zero-handoff sink.

    Producer side calls ``_push(item)``; ``None`` ends the stream and a
    queued ``BaseException`` re-raises in the consumer. When a consumer
    registers a sink, items are handed to it ON THE PRODUCING THREAD;
    the sink returns True to consume or False to fall back to the queue
    (the consumer's iterator). Terminal items always go to the queue so
    the consuming thread observes the end.

    A decline is PERMANENT: the first False detaches the sink and every
    later item rides the queue. This is what makes the ordering
    guarantee structural — if a sink could decline item N and accept
    item N+1, the producing thread would write N+1 to the wire while N
    waited for the consumer thread. (In-tree sinks downgrade themselves
    on any obstacle anyway; the detach enforces it for everyone.)

    The sink MUST be non-blocking and exception-free in spirit: a sink
    that raises is dropped (the stream falls back to queue delivery)
    rather than killing the producer.
    """

    def __init__(self):
        self._q: queue.Queue = queue.Queue()
        self._sink = None
        self._sink_lock = threading.Lock()

    def _sink_try(self, sink, item) -> bool:
        try:
            return bool(sink(item))
        except Exception:
            self._sink = None
            return False

    def _push(self, item) -> None:
        with self._sink_lock:
            sink = self._sink
            if (sink is not None and item is not None
                    and not isinstance(item, BaseException)):
                if self._sink_try(sink, item):
                    return
                self._sink = None  # declines are permanent (see class doc)
            self._q.put(item)

    def set_sink(self, sink) -> None:
        """Register ``sink`` and drain already-queued items through it
        under the delivery lock, so delivery order is preserved across
        the registration boundary. Terminal items (and everything after
        a declined item) stay queued for the iterator."""
        with self._sink_lock:
            pending = []
            while True:
                try:
                    pending.append(self._q.get_nowait())
                except queue.Empty:
                    break
            self._sink = sink
            for idx, item in enumerate(pending):
                if (item is None or isinstance(item, BaseException)
                        or not self._sink_try(sink, item)):
                    if item is not None and not isinstance(item,
                                                           BaseException):
                        self._sink = None  # declined: permanent fallback
                    for rest in pending[idx:]:
                        self._q.put(rest)
                    break

    def clear_sink(self) -> None:
        with self._sink_lock:
            self._sink = None

    def wake(self) -> None:
        """Rouse the consuming thread with a WAKE marker. Safe from
        inside a sink callback (no locks taken)."""
        self._q.put(WAKE)

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            if isinstance(item, BaseException):
                raise item
            yield item

    def map(self, fn) -> "MappedStream":
        return MappedStream(self, fn)


class MappedStream:
    """A PushStream view with a per-item transform — lets one source
    serve different consumers (gRPC messages, HTTP ndjson chunks)
    while keeping the zero-handoff sink protocol intact."""

    def __init__(self, source, fn):
        self._source = source
        self._fn = fn

    def set_sink(self, sink) -> None:
        fn = self._fn
        self._source.set_sink(lambda item: sink(fn(item)))

    def clear_sink(self) -> None:
        cs = getattr(self._source, "clear_sink", None)
        if cs is not None:
            cs()

    def __iter__(self):
        for item in self._source:
            yield item if item is WAKE else self._fn(item)

    def map(self, fn) -> "MappedStream":
        return MappedStream(self, fn)

    def wake(self) -> None:
        w = getattr(self._source, "wake", None)
        if w is not None:
            w()

    def cancel(self) -> None:
        c = getattr(self._source, "cancel", None)
        if c is not None:
            c()

    @property
    def trace(self):
        """TTFT decomposition stamps of the underlying source (GenStream
        sets ``first_put``), for the transport's grpc.handoff span."""
        return getattr(self._source, "trace", None)
