"""Pallas TPU flash-decode attention: stream the int8 KV cache once.

Decode attention is the least XLA-friendly part of the serving step: the
cache slice [B, S, KV, hd] is int8 with per-vector scales, and the jnp
path (ops.attention.decode_attention_appended) leaves it to the compiler
to keep the int8->bf16 upcast fused into the einsums. When XLA instead
materializes dequantized copies, decode pays the cache stream ~3x
(int8 read + bf16 write + bf16 read) — at 8B/batch-64 shapes that is
~20 ms/step of avoidable HBM traffic (see PERF.md roofline).

This kernel makes the single-pass guarantee structural: a
(B, S/BLOCK_S) grid streams each [BLOCK_S, KV, hd] cache tile from HBM
into VMEM exactly once (int8 on the wire, upcast in-register), runs the
online-softmax recurrence, and emits UNNORMALIZED (acc, m, l) running
stats. The current token's k/v — not yet written to the cache
(llama.decode_step defers the write to one post-scan scatter) — folds
in afterwards with the standard flash combination, in jnp:

    m_t = max(m_c, s_new);  l_t = l_c*e^(m_c-m_t) + e^(s_new-m_t)
    out = (acc_c*e^(m_c-m_t) + e^(s_new-m_t) * v_new) / l_t

which is exact, costs O(B*H*D), and cleanly handles empty slots
(length 0 => l_c = 0 => out = v_new's softmax of one element).

GQA geometry (the v2 redesign): with H=32 query heads over KV=8 heads,
the naive per-kv-head loop does G=4-row matmuls and 4-sublane
read-modify-writes — both far below the MXU's 128x128 / the VPU's
8-sublane granule, and the r03 A/B measured it ~1.8x SLOWER than the
XLA path it was meant to beat. Instead the query block is expanded
host-side into a BLOCK-DIAGONAL [H, KV*D] matrix (q_bd[h, kv*D+d] = 0
unless kv == kv(h)), so each tile does ONE dense [H, KV*D] @ [KV*D, BS]
MXU matmul for the scores and one [H, BS] @ [BS, KV*D] for the values —
8x the MACs, all of them free next to the cache stream (8.6 GFLOP/step
vs ~5.5 ms of int8 HBM traffic at 8B dims), and zero sub-granule
slicing inside the kernel. The [H, KV*D] accumulator's kv(h) slice is
selected after the kernel, again in O(B*H*D) jnp.

Sharding (same as ops.flash): a pallas_call is opaque to the GSPMD
partitioner, so on a mesh ``decode_attention_auto`` wraps the kernel in
``shard_map`` over the tp (and data) axes — every device streams only
its local [KV/tp] head shard of the cache, no collectives inside
attention (flash_decode_sharded). The jnp reference remains the
fallback when tp would split a KV head. Dispatch via
``decode_attention_auto``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_compat import CompilerParams

from .attention import NEG_INF, decode_attention_appended

_LANES = 128


def _decode_kernel(lengths_ref, qbd_ref, k_ref, v_ref, ks_ref, vs_ref,
                   acc_ref, m_ref, l_ref, *,
                   block_s: int, n_kv: int, quant: bool):
    """One (batch, s-block) step. Scratchless: acc/m/l ARE the outputs,
    revisited across the sequential s dimension (the output block index
    map ignores si, so the tiles stay resident in VMEM until the last
    s-block flushes them)."""
    si = pl.program_id(1)
    length = lengths_ref[pl.program_id(0)]
    h = qbd_ref.shape[1]
    g = h // n_kv

    @pl.when(si == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # blocks entirely past the valid prefix skip compute (the runtime
    # still streams them; skipping the math is the available win)
    @pl.when(si * block_s < length)
    def _compute():
        qbd = qbd_ref[0]                                   # [H, KV*D]
        k_flat = k_ref[0].reshape(block_s, -1)             # [BS, KV*D]
        v_flat = v_ref[0].reshape(block_s, -1)
        # scores: block-diagonal q rows zero out every kv plane but kv(h),
        # so the dense contraction equals the per-head dot
        s = jax.lax.dot_general(
            qbd, k_flat.astype(qbd.dtype),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # [H, BS]
        if quant:
            ks = ks_ref[0]                                  # [KV, BS]
            ks_h = jnp.broadcast_to(ks[:, None, :],
                                    (n_kv, g, block_s)).reshape(h, block_s)
            s = s * ks_h
        pos = si * block_s + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_s), 1)                     # [1, BS]
        s = jnp.where(pos < length, s, NEG_INF)

        m_prev = m_ref[0, :, :1]                            # [H, 1]
        l_prev = l_ref[0, :, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                              # [H, BS]
        # fully-masked blocks never reach here (pl.when), and within a
        # reached block masked positions give exp(NEG_INF - m) = 0
        corr = jnp.exp(m_prev - m_new)                      # [H, 1]
        l_ref[0] = jnp.broadcast_to(
            l_prev * corr + jnp.sum(p, axis=-1, keepdims=True), (h, _LANES))
        m_ref[0] = jnp.broadcast_to(m_new, (h, _LANES))
        if quant:
            vs = vs_ref[0]                                  # [KV, BS]
            vs_h = jnp.broadcast_to(vs[:, None, :],
                                    (n_kv, g, block_s)).reshape(h, block_s)
            p = p * vs_h
        # pv contraction in q's dtype (bf16 in serving, f32 in the
        # numerics tests) — matches decode_attention_appended's vdt.
        # acc is [H, KV*D]; only the kv(h) slice is meaningful per row
        # (selected after the kernel), the rest is harmless extra MACs.
        acc_ref[0] = acc_ref[0] * corr + jax.lax.dot_general(
            p.astype(qbd.dtype), v_flat.astype(qbd.dtype),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [H, KV*D]


@functools.partial(jax.jit, static_argnames=("block_s", "interpret"))
def _flash_decode_cache(q, k_cache, v_cache, lengths, k_scale, v_scale,
                        *, block_s: int = 128, interpret: bool = False):
    """Cache-side running stats: returns (acc [B,H,D] f32 unnormalized,
    m [B,H,LANES] f32, l [B,H,LANES] f32) over valid cache positions.

    q: [B, H, D]; k_cache/v_cache: [B, S, KV, D] (int8 with scales
    [B, S, KV], or dense); lengths: [B] int32 valid entries."""
    b, h, d = q.shape
    smax, n_kv = k_cache.shape[1], k_cache.shape[2]
    g = h // n_kv
    if smax % block_s:
        raise ValueError(f"S={smax} not divisible by block_s={block_s}")
    quant = k_scale is not None
    if not quant:  # uniform kernel signature: dummy scale planes
        k_scale = jnp.ones((b, smax, n_kv), jnp.float32)
        v_scale = jnp.ones((b, smax, n_kv), jnp.float32)
    # [B, S, KV] -> [B, KV, S]: tiny (scales), and inside the kernel the
    # [KV, BS] tile broadcasts to [H, BS] along sublanes for free
    ks_t = jnp.swapaxes(k_scale, 1, 2).astype(jnp.float32)
    vs_t = jnp.swapaxes(v_scale, 1, 2).astype(jnp.float32)
    # block-diagonal query expansion (see module docstring): scale folded
    # in here so the kernel never touches q again
    qh = (q * (d ** -0.5)).reshape(b, n_kv, g, d)
    eye = jnp.eye(n_kv, dtype=q.dtype)
    q_bd = jnp.einsum("bkgd,kK->bgkKd", qh, eye,
                      preferred_element_type=q.dtype)
    q_bd = jnp.swapaxes(q_bd, 1, 2).reshape(b, h, n_kv * d)
    grid = (b, smax // block_s)

    def clamp(si, lens, bi):
        # v3: clamp past-the-end s-blocks to the slot's LAST live block.
        # Grid steps whose index map repeats the previous step's indices
        # skip their DMA (the same trick ops.paged_attention uses via
        # clamped table rows), so per-slot HBM traffic tracks the LIVE
        # length instead of Smax — the jnp path always streams the full
        # padded cache. The compute guard stays keyed on the TRUE si,
        # so revisited tiles are never folded in twice.
        last = jax.lax.max((lens[bi] + block_s - 1) // block_s - 1, 0)
        return jax.lax.min(si, last)

    kernel = functools.partial(_decode_kernel, block_s=block_s,
                               n_kv=n_kv, quant=quant)
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,  # lengths
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, h, n_kv * d), lambda bi, si, lens: (bi, 0, 0)),
                pl.BlockSpec((1, block_s, n_kv, d),
                             lambda bi, si, lens: (bi, clamp(si, lens, bi),
                                                   0, 0)),
                pl.BlockSpec((1, block_s, n_kv, d),
                             lambda bi, si, lens: (bi, clamp(si, lens, bi),
                                                   0, 0)),
                pl.BlockSpec((1, n_kv, block_s),
                             lambda bi, si, lens: (bi, 0,
                                                   clamp(si, lens, bi))),
                pl.BlockSpec((1, n_kv, block_s),
                             lambda bi, si, lens: (bi, 0,
                                                   clamp(si, lens, bi))),
            ],
            out_specs=[
                pl.BlockSpec((1, h, n_kv * d), lambda bi, si, lens: (bi, 0, 0)),
                pl.BlockSpec((1, h, _LANES), lambda bi, si, lens: (bi, 0, 0)),
                pl.BlockSpec((1, h, _LANES), lambda bi, si, lens: (bi, 0, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, h, n_kv * d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((b, h, _LANES), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(lengths.astype(jnp.int32), q_bd, k_cache, v_cache, ks_t, vs_t)
    # select each row's own kv(h) slice out of the dense accumulator
    acc = acc.reshape(b, n_kv, g, n_kv, d)
    acc = jnp.einsum("bkgKd,kK->bkgd", acc,
                     jnp.eye(n_kv, dtype=acc.dtype)).reshape(b, h, d)
    return acc, m, l


def flash_decode_appended(q, k_cache, v_cache, k_new, v_new, lengths,
                          k_scale=None, v_scale=None, *,
                          block_s: int = 128,
                          interpret: bool = False) -> jnp.ndarray:
    """Drop-in for ops.attention.decode_attention_appended on TPU.

    q: [B, 1, H, D]; k_cache/v_cache: [B, Smax, KV, D];
    k_new/v_new: [B, 1, KV, D] (bf16, fresh this step); lengths [B]
    EXCLUDING the current token. Returns [B, 1, H, D] in q.dtype.
    """
    b, _, h, d = q.shape
    n_kv = k_cache.shape[2]
    g = h // n_kv
    acc, m, l = _flash_decode_cache(
        q[:, 0], k_cache, v_cache, lengths, k_scale, v_scale,
        block_s=block_s, interpret=interpret)
    m = m[..., 0]                                           # [B, H]
    l = l[..., 0]

    # fold the appended token (exact flash combination, O(B*H*D) jnp)
    qh = (q[:, 0] * (d ** -0.5)).reshape(b, n_kv, g, d)
    s_new = jnp.einsum("bkgd,bkd->bkg", qh,
                       k_new[:, 0].astype(qh.dtype),
                       preferred_element_type=jnp.float32).reshape(b, h)
    m_t = jnp.maximum(m, s_new)
    alpha = jnp.exp(m - m_t)                                # [B, H]
    beta = jnp.exp(s_new - m_t)
    l_t = l * alpha + beta
    v_rep = jnp.repeat(v_new[:, 0], g, axis=1)              # [B, H, D]
    out = (acc * alpha[..., None]
           + beta[..., None] * v_rep.astype(jnp.float32)) / l_t[..., None]
    return out.astype(q.dtype).reshape(b, 1, h, d)


def flash_decode_sharded(q, k_cache, v_cache, k_new, v_new, lengths,
                         k_scale=None, v_scale=None, *, mesh,
                         batch_axes=(), head_axis=None,
                         block_s: int = 128,
                         interpret: bool = False) -> jnp.ndarray:
    """shard_map'd flash_decode_appended: each device runs the
    single-device kernel (including the appended-token fold) on its
    local [KV/tp] head shard — and its local batch shard on
    data-parallel meshes. The specs mirror parallel.kv_cache_specs so
    GSPMD never gathers the cache at the shard_map boundary; no
    collectives inside attention (the o-proj psum downstream is
    unchanged). check_rep off: pallas_call has no replication rule."""
    from jax.sharding import PartitionSpec as P

    from .flash import shard_map

    bax = tuple(batch_axes) or None
    qspec = P(bax, None, head_axis, None)      # q/k_new/v_new [B,1,·,D]
    cspec = P(bax, None, head_axis, None)      # caches [B,Smax,KV,D]
    sspec = P(bax, None, head_axis)            # scales [B,Smax,KV]
    lspec = P(bax)
    if k_scale is not None:
        def run(q, kc, vc, kn, vn, ln, ks, vs):
            return flash_decode_appended(q, kc, vc, kn, vn, ln, ks, vs,
                                         block_s=block_s,
                                         interpret=interpret)

        fn = shard_map(run, mesh=mesh,
                       in_specs=(qspec, cspec, cspec, cspec, cspec, lspec,
                                 sspec, sspec),
                       out_specs=qspec, check_rep=False)
        return fn(q, k_cache, v_cache, k_new, v_new, lengths,
                  k_scale, v_scale)

    def run(q, kc, vc, kn, vn, ln):
        return flash_decode_appended(q, kc, vc, kn, vn, ln,
                                     block_s=block_s, interpret=interpret)

    fn = shard_map(run, mesh=mesh,
                   in_specs=(qspec, cspec, cspec, cspec, cspec, lspec),
                   out_specs=qspec, check_rep=False)
    return fn(q, k_cache, v_cache, k_new, v_new, lengths)


def _kernel_gate(q, k_cache, block_s: int) -> str | None:
    """None when the Pallas kernel can run; otherwise the NAME of the
    first failing gate. Single source of truth for dispatch AND for the
    GOFR_FLASH_BLOCK_S diagnostics — the warn path must know whether
    block_s is what disqualified the kernel, and a second copy of this
    predicate would silently diverge as gates are added."""
    from .flash import tpu_backend_ok

    _, _, h, d = q.shape
    smax, n_kv = k_cache.shape[1], k_cache.shape[2]
    if d % _LANES:
        return "head_dim"
    if h % n_kv:
        return "gqa_ratio"
    if not tpu_backend_ok():
        return "backend"
    # checked LAST: "block_s" means every gate the env var cannot fix
    # passed, so the warn path can blame GOFR_FLASH_BLOCK_S truthfully
    if smax % block_s or smax < block_s:
        return "block_s"
    return None


def _kernel_ok(q, k_cache, block_s: int) -> bool:
    return _kernel_gate(q, k_cache, block_s) is None


_block_s_warned: set[str] = set()


def _warn_block_s_once(kind: str, msg: str) -> None:
    """Once-per-kind warning when an operator-set GOFR_FLASH_BLOCK_S is
    ignored or disqualifies the flash kernel — the silent jnp fallback
    would otherwise make a bad tuning value read as 'flash got slower'.
    Keyed per diagnostic kind: the env var is re-read every call, so an
    invalid-value warning must not suppress a later kernel-disabled one
    (or vice versa) after the operator changes the value."""
    if kind in _block_s_warned:
        return
    _block_s_warned.add(kind)
    import warnings

    warnings.warn(msg, RuntimeWarning, stacklevel=3)


def decode_attention_auto(q, k_cache, v_cache, k_new, v_new, lengths,
                          k_scale=None, v_scale=None, *,
                          block_s: int | None = None,
                          interpret: bool = False,
                          mesh=None) -> jnp.ndarray:
    """Flash-decode kernel when backend+shapes allow, jnp reference
    otherwise. Same contract as decode_attention_appended.
    ``block_s`` defaults from GOFR_FLASH_BLOCK_S (128): larger blocks
    amortize per-grid-step overhead, at (block_s/S)-granular DMA skip.
    With ``mesh``, the kernel runs under shard_map per head/batch shard
    (flash_decode_sharded); the reference — GSPMD-partitionable on its
    own — remains the fallback when tp would split a KV head."""
    from .flash import fit_block, interpret_env

    interpret = interpret or interpret_env()
    explicit = False
    if block_s is not None and block_s <= 0:
        # explicit caller value, same ZeroDivision hazard as the env
        # path below (smax % block_s inside _kernel_gate) — clamp to
        # the default rather than crash, and say so once
        _warn_block_s_once(
            "invalid", f"block_s={block_s!r} is not a positive integer; "
            "using the default block_s=128")
        block_s = 128
    if block_s is None:
        import os

        raw = os.environ.get("GOFR_FLASH_BLOCK_S")
        explicit = raw is not None
        try:
            block_s = int(raw) if explicit else 128
        except ValueError:
            block_s = 0
        if block_s <= 0:  # 0 would ZeroDivide inside _kernel_gate
            if explicit:
                # the set value is unusable and silently becomes the
                # default — say so, naming what the operator actually set
                _warn_block_s_once(
                    "invalid", f"GOFR_FLASH_BLOCK_S={raw!r} is not a "
                    f"positive integer; using the default block_s=128")
                explicit = False  # don't blame the env var for 128's gates
            block_s = 128
    if interpret:
        # interpret mode runs anywhere — clamp the block to the cache
        # length instead of gating (tiny test buckets never divide 128)
        block_s = fit_block(k_cache.shape[1], block_s)
    gate = None if interpret else _kernel_gate(q, k_cache, block_s)
    if gate == "block_s" and explicit:
        # every gate the env var cannot fix passed; only the operator's
        # block size disqualified the kernel
        smax = k_cache.shape[1]
        reason = (f"exceeds the cache length {smax}" if smax < block_s
                  else f"does not divide the cache length {smax}")
        _warn_block_s_once(
            "rejected", f"GOFR_FLASH_BLOCK_S={block_s} {reason}; the "
            f"flash-decode kernel is DISABLED and attention falls "
            f"back to the jnp reference path")
    if mesh is not None:
        from ..parallel.sharding import attention_shard_axes

        batch_axes, head_axis = attention_shard_axes(
            mesh, q.shape[0], q.shape[2], k_cache.shape[2])
        if gate is None and (head_axis is not None or batch_axes):
            return flash_decode_sharded(
                q, k_cache, v_cache, k_new, v_new, lengths,
                k_scale, v_scale, mesh=mesh, batch_axes=batch_axes,
                head_axis=head_axis, block_s=block_s, interpret=interpret)
        return decode_attention_appended(q, k_cache, v_cache, k_new, v_new,
                                         lengths, k_scale, v_scale)
    if gate is None:
        return flash_decode_appended(q, k_cache, v_cache, k_new, v_new,
                                     lengths, k_scale, v_scale,
                                     block_s=block_s, interpret=interpret)
    return decode_attention_appended(q, k_cache, v_cache, k_new, v_new,
                                     lengths, k_scale, v_scale)
