"""Ring attention: causal attention with the SEQUENCE dimension sharded.

Long-context prefill/training above single-chip HBM needs the sequence
axis distributed (SURVEY §5 "long-context / sequence parallelism"; the
task's first-class long-context requirement). GSPMD's automatic answer
to a sequence-sharded attention is poor — resharding the [S, S] score
space triggers "involuntary full rematerialization" (the warning the
dryrun notes suppress by keeping sp=1). Ring attention sidesteps GSPMD
entirely: under ``shard_map`` each device keeps its Q shard pinned and
the K/V shards ROTATE around the ``sp`` axis with ``ppermute`` — n-1
neighbor exchanges over ICI, each overlapping the previous block's
compute, never an all-gather and never a full [S, S] anything:

    peak memory per device: O(S/n * S/n) scores + 2 K/V shards
    comm per layer: 2 * (n-1) * |KV shard| point-to-point (ICI ring)

The online-softmax recurrence (same math as ops.flash) makes the
rotation exact: each incoming K/V block folds into running (m, l, acc).

Causality with contiguous shards in axis order: block t on device i
holds shard j = (i - t) mod n; j > i blocks are fully masked (their
compute is wasted ring slack — the standard causal-ring imbalance),
j == i is the intra-shard causal triangle, j < i is fully visible.
Right-padded batches mask by GLOBAL ``lengths`` exactly like
ops.attention.causal_attention.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .attention import NEG_INF, causal_attention

try:  # jax >= 0.4.35 exposes shard_map at the top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map


def ring_causal_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                          lengths: jnp.ndarray | None, *,
                          axis_name: str) -> jnp.ndarray:
    """Per-device body — call under shard_map with the sequence dim of
    q/k/v sharded over ``axis_name`` (contiguous shards in axis-index
    order).

    q: [B, Ss, H, D] local shard; k/v: [B, Ss, KV, D]; lengths: [B]
    GLOBAL valid lengths (replicated), None = all valid.
    Returns the local output shard [B, Ss, H, D] in q.dtype.
    """
    b, ss, h, d = q.shape
    n_kv = k.shape[2]
    g = h // n_kv
    scale = d ** -0.5
    idx = jax.lax.axis_index(axis_name)
    n = jax.lax.psum(1, axis_name)

    qg = (q * scale).reshape(b, ss, n_kv, g, d)
    q_pos = idx * ss + jnp.arange(ss, dtype=jnp.int32)        # [Ss]

    # derive the running-stat carries from qg so they carry the same
    # shard_map varying-axes type as the loop outputs (plain constants
    # are "unvarying" and the fori_loop carry types would not match)
    zero = qg.astype(jnp.float32) * 0.0                       # [B,Ss,KV,G,D]
    m0 = zero[..., 0] + NEG_INF
    l0 = zero[..., 0]
    acc0 = zero
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(t, carry):
        k_t, v_t, m, l, acc = carry
        src = (idx - t) % n                                   # shard held
        k_pos = src * ss + jnp.arange(ss, dtype=jnp.int32)    # [Ss]

        s = jnp.einsum("bskgd,btkd->bskgt", qg,
                       k_t.astype(qg.dtype),
                       preferred_element_type=jnp.float32)    # [B,Ss,KV,G,St]
        mask = k_pos[None, :] <= q_pos[:, None]               # [Ss, St]
        if lengths is not None:
            mask = mask[None] & (k_pos[None, None, :]
                                 < lengths[:, None, None])    # [B,Ss,St]
            s = jnp.where(mask[:, :, None, None, :], s, NEG_INF)
        else:
            s = jnp.where(mask[None, :, None, None, :], s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bskgt,btkd->bskgd", p.astype(v_t.dtype), v_t,
            preferred_element_type=jnp.float32)
        # rotate K/V one hop: after the exchange this device holds shard
        # (idx - t - 1) mod n. The last iteration's rotation returns the
        # shards to their owners (harmless; keeps the loop uniform).
        k_t = jax.lax.ppermute(k_t, axis_name, perm)
        v_t = jax.lax.ppermute(v_t, axis_name, perm)
        return k_t, v_t, m_new, l_new, acc_new

    _, _, m, l, acc = jax.lax.fori_loop(0, n, body, (k, v, m0, l0, acc0))
    out = acc / jnp.where(l == 0.0, 1.0, l)[..., None]
    return out.reshape(b, ss, h, d).astype(q.dtype)


def make_ring_attention(mesh, *, axis_name: str = "sp",
                        batch_axes=("dp", "fsdp", "ep"),
                        head_axis: str = "tp"):
    """shard_map-wrapped ring attention over ``mesh``.

    Returns attend(q [B,S,H,D], k, v [B,S,KV,D], lengths [B] | None)
    with batch sharded over ``batch_axes``, sequence over ``axis_name``,
    and — when both H and KV divide it — heads over ``head_axis``, so a
    tp>1 mesh keeps its head sharding instead of all-gathering q/k/v and
    computing attention redundantly per tp device. Collectives ride the
    mesh's ``axis_name`` ring (ICI when the mesh is laid out that way).

    Shapes that don't divide the mesh axes (ragged batch, odd sequence)
    fall back to the dense reference at trace time — layout is never
    allowed to turn into a shape crash."""
    batch = tuple(a for a in batch_axes if a in mesh.shape)
    bspec = batch if batch else None
    nb = 1
    for a in batch:
        nb *= mesh.shape[a]
    nsp = mesh.shape.get(axis_name, 1)
    ntp = mesh.shape.get(head_axis, 1)

    def attend(q, k, v, lengths=None):
        b, s, h, d = q.shape
        n_kv = k.shape[2]
        if b % nb or s % nsp:
            mask = None
            if lengths is not None:
                mask = (jnp.arange(s, dtype=jnp.int32)[None, :]
                        < lengths[:, None])
            return causal_attention(q, k, v, mask=mask)
        heads_shard = (ntp > 1 and h % ntp == 0 and n_kv % ntp == 0)
        hax = head_axis if heads_shard else None
        qspec = P(bspec, axis_name, hax, None)
        inner = functools.partial(ring_causal_attention,
                                  axis_name=axis_name)
        if lengths is None:
            fn = shard_map(lambda q_, k_, v_: inner(q_, k_, v_, None),
                           mesh=mesh, in_specs=(qspec, qspec, qspec),
                           out_specs=qspec)
            return fn(q, k, v)
        fn = shard_map(inner, mesh=mesh,
                       in_specs=(qspec, qspec, qspec, P(bspec)),
                       out_specs=qspec)
        return fn(q, k, v, lengths)

    return attend
