"""Normalization ops.

Computed in float32 regardless of input dtype (TPU VPU-friendly; bf16
accumulation of variances loses too much precision), cast back on exit so
surrounding matmuls stay bf16 on the MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """RMSNorm (Llama-style): x * w / rms(x)."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray,
               eps: float = 1e-12) -> jnp.ndarray:
    """LayerNorm (BERT/ViT-style)."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    normed = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)
