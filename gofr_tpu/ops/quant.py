"""Int8 weight-only quantization.

Decode-phase LLM serving is HBM-bandwidth-bound: every step streams the full
weight set through the MXU. Per-output-channel int8 storage halves that
traffic vs bf16 at negligible quality cost. XLA fuses the int8->bf16 convert
and the scale multiply into the matmul, so the MXU still sees one dense
contraction.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class QuantizedLinear(NamedTuple):
    """Per-output-channel symmetric int8 weight. ``w``: [in, out] int8,
    ``scale``: [out] float32 with  w_true ≈ w * scale."""

    w: jnp.ndarray
    scale: jnp.ndarray


def quantize_int8(w: jnp.ndarray, axis: int = 0) -> QuantizedLinear:
    """Quantize a [in, out] weight per output channel (reduce over ``axis``)."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = jnp.maximum(absmax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127).astype(jnp.int8)
    return QuantizedLinear(w=q, scale=scale.squeeze(axis).astype(jnp.float32))


def qmatmul(x: jnp.ndarray, qw: "QuantizedLinear | jnp.ndarray") -> jnp.ndarray:
    """x @ w for quantized or plain weights.

    x: [..., in]; returns [..., out] in x.dtype. For QuantizedLinear the
    int8 tensor is upcast in-register (fused by XLA) and scaled after the
    contraction, keeping the accumulation in f32.
    """
    if isinstance(qw, QuantizedLinear):
        y = jax.lax.dot_general(
            x, qw.w.astype(x.dtype),
            dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return (y * qw.scale).astype(x.dtype)
    return jnp.dot(x, qw, preferred_element_type=jnp.float32).astype(x.dtype)


def dequantize(qw: QuantizedLinear, dtype=jnp.bfloat16) -> jnp.ndarray:
    return (qw.w.astype(jnp.float32) * qw.scale).astype(dtype)


def quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-vector symmetric int8 over the LAST axis (the head_dim of a
    K/V tensor): x [..., hd] -> (int8 [..., hd], f32 scale [...]).

    This is the KV-cache quantizer: decode attention streams the whole
    valid cache every step, so int8 storage halves that HBM traffic. One
    scale per (position, head) vector keeps the dequant a cheap rank-1
    broadcast that XLA fuses into the attention einsum — scores and
    weighted sums apply the scale AFTER the contraction (it is constant
    over the contracted head_dim axis), so the MXU sees int8 data upcast
    in-register, never a materialized bf16 copy of the cache.
    """
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(absmax / 127.0, 1e-10)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_kv(q: jnp.ndarray, scale: jnp.ndarray,
                  dtype=jnp.bfloat16) -> jnp.ndarray:
    """Inverse of quantize_kv (test oracle / slow path)."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def maybe_quantize_tree(params, quantize: bool, *, min_size: int = 1 << 16):
    """Quantize projection-weight leaves: plain [in, out] 2-D mats and
    stacked [L, in, out] 3-D layer mats (reduce over the ``in`` axis either
    way, so a ``lax.scan`` slice yields a valid per-layer QuantizedLinear).
    Embedding tables and norms stay bf16 (quantizing embeddings hurts;
    norms are tiny).

    Works on the nested-dict param pytrees produced by gofr_tpu.models.
    """
    if not quantize:
        return params

    def is_proj_weight(k: str, v) -> bool:
        # Projection weights only: stacked [L, in, out] or plain [in, out]
        # mats whose key marks them as weights, plus 4-D [L, E, in, out]
        # MoE expert stacks (the contraction axis is ndim-2 in every
        # case, so one quantize call covers all ranks). Biases ([L, F] —
        # also 2-D!), norms and embeddings must stay dense: a stacked
        # bias quantized as a 2-D weight would break the lax.scan
        # leading-axis contract.
        if not isinstance(v, jnp.ndarray) or v.size < min_size:
            return False
        named_weight = k.startswith("w") or k in ("lm_head", "head",
                                                  "patch_proj", "pooler_w")
        return named_weight and v.ndim in (2, 3, 4)

    def visit(d):
        if isinstance(d, dict):
            out = {}
            for k, v in d.items():
                if is_proj_weight(k, v):
                    out[k] = quantize_int8(v, axis=v.ndim - 2)
                else:
                    out[k] = visit(v)
            return out
        return d

    return visit(params)
