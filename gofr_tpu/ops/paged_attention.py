"""Paged decode attention over a block-pool KV cache.

The contiguous serving cache allocates [B, Smax] KV rows per slot — at
batch 128 x 1024 that is ~9.7 GB of int8 KV + f32 scales on top of the
8 GB weight stream, which does not fit a v5e chip. Paging replaces the
per-slot rows with a shared pool of fixed [T]-token blocks plus a
per-slot block TABLE (vLLM's design, rebuilt TPU-first): shapes stay
static, the pool is sized to the expected TOTAL live tokens instead of
batch x max_seq, and slots grow/free blocks host-side.

The kernel is ops.flash_decode's v2 kernel (block-diagonal GQA, online
softmax, int8 tiles upcast in-register) with ONE change: the K/V/scale
index maps look the next tile up in a scalar-prefetched block table
instead of walking the sequence linearly. Two properties the engine's
host side maintains make this fast and safe:

  - table rows are CLAMPED: entries past a slot's last live block repeat
    the last live block. Pallas skips the DMA when consecutive grid
    steps map to the same block, so a slot's HBM stream is proportional
    to its LIVE length, not the grid's max — and the in-kernel
    ``pl.when(si * T < length)`` skips the compute.
  - retired slots' rows point at block 0, a reserved trash block no live
    slot ever owns, so their frozen-cursor garbage writes land nowhere.

The jnp reference (``paged_attention_reference``) gathers each slot's
blocks into a dense view and calls the exact reference attention — the
numerics oracle for interpret-mode tests and the CPU fallback.

Sharding: on a mesh the ``*_auto`` dispatchers wrap the kernel in
``shard_map`` over the tp axis — the pool shards KV-heads over tp
(parallel.paged_cache_specs), the block table and lengths ride
replicated, and every device streams only its local [KV/tp] pane of
each block. No dense gather, no collectives inside attention. The
dense-gather reference remains the fallback only when tp would split
a KV head.

Reference provenance: the reference (GoFr) is a pure-Go microservice
framework with no ML/serving code at all — this module has NO reference
counterpart. It implements the TPU-inference rows SURVEY.md §2 adds to
the component inventory (the "to build — native" rows), with the design
cross-checked against the public PagedAttention idea, rebuilt for
static shapes + Mosaic (static block lattice + scalar-prefetch index
maps instead of pointer indirection).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_compat import CompilerParams

from .attention import NEG_INF, decode_attention_appended
from .flash_decode import _LANES, _decode_kernel


@functools.partial(jax.jit, static_argnames=("interpret",))
def _paged_decode_cache(q, k_pool, v_pool, table, lengths, k_scale, v_scale,
                        *, interpret: bool = False):
    """Pool-side running stats: (acc [B,H,KV*D] f32 unnormalized,
    m [B,H,LANES], l [B,H,LANES]) over each slot's valid positions.

    q: [B, H, D]; k_pool/v_pool: [N, T, KV, D] (int8 with scales
    [N, T, KV], or dense); table: [B, MB] int32 CLAMPED block ids;
    lengths: [B] int32 valid tokens per slot."""
    b, h, d = q.shape
    n_blocks, block_t, n_kv = k_pool.shape[0], k_pool.shape[1], k_pool.shape[2]
    mb = table.shape[1]
    g = h // n_kv
    quant = k_scale is not None
    if not quant:
        k_scale = jnp.ones((n_blocks, block_t, n_kv), jnp.float32)
        v_scale = jnp.ones((n_blocks, block_t, n_kv), jnp.float32)
    # [N, T, KV] -> [N, KV, T]: the [KV, T] tile broadcasts to [H, T]
    # along sublanes for free inside the kernel
    ks_t = jnp.swapaxes(k_scale, 1, 2).astype(jnp.float32)
    vs_t = jnp.swapaxes(v_scale, 1, 2).astype(jnp.float32)
    # block-diagonal query expansion (see ops.flash_decode docstring)
    qh = (q * (d ** -0.5)).reshape(b, n_kv, g, d)
    eye = jnp.eye(n_kv, dtype=q.dtype)
    q_bd = jnp.einsum("bkgd,kK->bgkKd", qh, eye,
                      preferred_element_type=q.dtype)
    q_bd = jnp.swapaxes(q_bd, 1, 2).reshape(b, h, n_kv * d)

    def kernel(lengths_ref, table_ref, *refs):
        # the table is consumed by the index maps only; the compute body
        # is EXACTLY the flash-decode kernel (si is the logical block
        # index either way, so its position masking carries over)
        del table_ref
        _decode_kernel(lengths_ref, *refs, block_s=block_t, n_kv=n_kv,
                       quant=quant)
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # lengths, table
            grid=(b, mb),
            in_specs=[
                pl.BlockSpec((1, h, n_kv * d),
                             lambda bi, si, lens, tab: (bi, 0, 0)),
                # the paged difference: the next K/V/scale tile is
                # table[bi, si], not si — clamped rows repeat their last
                # block so Pallas skips the DMA past a slot's live length
                pl.BlockSpec((1, block_t, n_kv, d),
                             lambda bi, si, lens, tab: (tab[bi, si], 0, 0, 0)),
                pl.BlockSpec((1, block_t, n_kv, d),
                             lambda bi, si, lens, tab: (tab[bi, si], 0, 0, 0)),
                pl.BlockSpec((1, n_kv, block_t),
                             lambda bi, si, lens, tab: (tab[bi, si], 0, 0)),
                pl.BlockSpec((1, n_kv, block_t),
                             lambda bi, si, lens, tab: (tab[bi, si], 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, h, n_kv * d),
                             lambda bi, si, lens, tab: (bi, 0, 0)),
                pl.BlockSpec((1, h, _LANES),
                             lambda bi, si, lens, tab: (bi, 0, 0)),
                pl.BlockSpec((1, h, _LANES),
                             lambda bi, si, lens, tab: (bi, 0, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, h, n_kv * d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((b, h, _LANES), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(lengths.astype(jnp.int32), table.astype(jnp.int32),
      q_bd, k_pool, v_pool, ks_t, vs_t)
    acc = acc.reshape(b, n_kv, g, n_kv, d)
    acc = jnp.einsum("bkgKd,kK->bkgd", acc,
                     jnp.eye(n_kv, dtype=acc.dtype)).reshape(b, h, d)
    return acc, m, l


def paged_decode_attention(q, k_pool, v_pool, k_new, v_new, table, lengths,
                           k_scale=None, v_scale=None, *,
                           interpret: bool = False) -> jnp.ndarray:
    """Single-token decode attention against a paged pool.

    q: [B, 1, H, D]; k_pool/v_pool: [N, T, KV, D]; k_new/v_new:
    [B, 1, KV, D] (bf16, this step's fresh KV — not yet in the pool);
    table [B, MB] clamped block ids; lengths [B] EXCLUDING the current
    token. Returns [B, 1, H, D] in q.dtype."""
    b, _, h, d = q.shape
    n_kv = k_pool.shape[2]
    g = h // n_kv
    acc, m, l = _paged_decode_cache(q[:, 0], k_pool, v_pool, table, lengths,
                                    k_scale, v_scale, interpret=interpret)
    m = m[..., 0]
    l = l[..., 0]
    # fold the appended token (exact flash combination; see flash_decode)
    qh = (q[:, 0] * (d ** -0.5)).reshape(b, n_kv, g, d)
    s_new = jnp.einsum("bkgd,bkd->bkg", qh,
                       k_new[:, 0].astype(qh.dtype),
                       preferred_element_type=jnp.float32).reshape(b, h)
    m_t = jnp.maximum(m, s_new)
    alpha = jnp.exp(m - m_t)
    beta = jnp.exp(s_new - m_t)
    l_t = l * alpha + beta
    v_rep = jnp.repeat(v_new[:, 0], g, axis=1)
    out = (acc * alpha[..., None]
           + beta[..., None] * v_rep.astype(jnp.float32)) / l_t[..., None]
    return out.astype(q.dtype).reshape(b, 1, h, d)


def _paged_sharded(inner, mesh, head_axis, args, scales):
    """shard_map a paged kernel entry point over the tp axis: pool and
    q/k_new/v_new shard KV-heads (the paged mesh layout is tp-only —
    parallel.paged_cache_specs replicates batch, table, and lengths).
    Each device streams its local [KV/tp] pane of every block; no dense
    gather, no collectives. check_rep off: pallas_call has no
    replication rule."""
    from jax.sharding import PartitionSpec as P

    from .flash import shard_map

    hspec = P(None, None, head_axis, None)   # q/k_new/v_new and pools
    sspec = P(None, None, head_axis)         # pool scales [N, T, KV]
    in_specs = (hspec,) * 5 + (P(), P())     # q, pools, new kv, table, lens
    if scales is not None:
        in_specs = in_specs + (sspec, sspec)
        args = args + scales
    fn = shard_map(inner, mesh=mesh, in_specs=in_specs, out_specs=hspec,
                   check_rep=False)
    return fn(*args)


def paged_decode_sharded(q, k_pool, v_pool, k_new, v_new, table, lengths,
                         k_scale=None, v_scale=None, *, mesh,
                         head_axis=None,
                         interpret: bool = False) -> jnp.ndarray:
    """shard_map'd paged_decode_attention — see _paged_sharded."""
    if k_scale is not None:
        def run(q, kp, vp, kn, vn, tab, ln, ks, vs):
            return paged_decode_attention(q, kp, vp, kn, vn, tab, ln,
                                          ks, vs, interpret=interpret)
    else:
        def run(q, kp, vp, kn, vn, tab, ln):
            return paged_decode_attention(q, kp, vp, kn, vn, tab, ln,
                                          interpret=interpret)
    scales = (k_scale, v_scale) if k_scale is not None else None
    return _paged_sharded(run, mesh, head_axis,
                          (q, k_pool, v_pool, k_new, v_new, table, lengths),
                          scales)


def paged_window_sharded(q, k_pool, v_pool, k_new, v_new, table, lengths,
                         k_scale=None, v_scale=None, *, mesh,
                         head_axis=None,
                         interpret: bool = False) -> jnp.ndarray:
    """shard_map'd paged_window_attention (speculative verify) — the
    kv-major row flattening is per-KV-head, so it holds unchanged on
    each device's local [KV/tp] shard."""
    if k_scale is not None:
        def run(q, kp, vp, kn, vn, tab, ln, ks, vs):
            return paged_window_attention(q, kp, vp, kn, vn, tab, ln,
                                          ks, vs, interpret=interpret)
    else:
        def run(q, kp, vp, kn, vn, tab, ln):
            return paged_window_attention(q, kp, vp, kn, vn, tab, ln,
                                          interpret=interpret)
    scales = (k_scale, v_scale) if k_scale is not None else None
    return _paged_sharded(run, mesh, head_axis,
                          (q, k_pool, v_pool, k_new, v_new, table, lengths),
                          scales)


def gather_blocks(pool, table):
    """Dense per-slot view of a paged buffer: [N, T, ...] gathered by
    table [B, MB] -> [B, MB*T, ...]. Materializes the full dense cache —
    the reference/FALLBACK path only (numerics oracles, CPU backends);
    on TPU both the decode and the verify-window kernels stream blocks
    directly and never gather."""
    g = pool[table]                       # [B, MB, T, ...]
    return g.reshape((g.shape[0], g.shape[1] * g.shape[2]) + g.shape[3:])


def paged_attention_reference(q, k_pool, v_pool, k_new, v_new, table,
                              lengths, k_scale=None, v_scale=None):
    """Numerics oracle: gather the table's dense view, run the exact
    reference decode attention."""
    k_dense = gather_blocks(k_pool, table)
    v_dense = gather_blocks(v_pool, table)
    ks = gather_blocks(k_scale, table) if k_scale is not None else None
    vs = gather_blocks(v_scale, table) if v_scale is not None else None
    return decode_attention_appended(q, k_dense, v_dense, k_new, v_new,
                                     lengths, ks, vs)


def paged_window_attention(q, k_pool, v_pool, k_new, v_new, table,
                           lengths, k_scale=None, v_scale=None, *,
                           interpret: bool = False) -> jnp.ndarray:
    """ops.attention.window_attention_appended over the paged pool —
    the speculative-decoding verify pass WITHOUT the dense gather: the
    cache side streams through the same scalar-prefetch kernel as
    decode (every (w, h) query row attends positions < lengths[b], so
    the W*H rows flatten kv-major and ride the block-diagonal matmul
    unchanged), and the W x W in-window causal part folds in afterwards
    with the exact flash combination.

    q: [B, W, H, D]; k_pool/v_pool: [N, T, KV, D]; k_new/v_new:
    [B, W, KV, D] (bf16, the window's fresh KV — not yet in the pool);
    table [B, MB] clamped block ids; lengths [B] EXCLUDING the window.
    Returns [B, W, H, D] in q.dtype."""
    b, w, h, d = q.shape
    n_kv = k_pool.shape[2]
    g = h // n_kv
    # rows kv-major so _paged_decode_cache's [n_kv, g'] reshape holds
    # with g' = W*G: [B, W, KV, G, D] -> [B, KV, W, G, D] -> [B, H', D]
    q_rows = q.reshape(b, w, n_kv, g, d).transpose(0, 2, 1, 3, 4) \
        .reshape(b, n_kv * w * g, d)
    acc, m, l = _paged_decode_cache(q_rows, k_pool, v_pool, table,
                                    lengths, k_scale, v_scale,
                                    interpret=interpret)
    # back to [B, W, H(=KV*G), ...]
    def unrows(x):
        x = x.reshape((b, n_kv, w, g) + x.shape[2:])
        return jnp.swapaxes(x, 1, 2).reshape((b, w, h) + x.shape[4:])

    acc = unrows(acc)                                   # [B, W, H, D]
    m = unrows(m[..., 0])                               # [B, W, H]
    l = unrows(l[..., 0])

    # in-window causal scores: query row w attends window positions <= w
    qg = (q * (d ** -0.5)).reshape(b, w, n_kv, g, d)
    s_s = jnp.einsum("bwkgd,btkd->bwkgt", qg,
                     k_new.astype(qg.dtype),
                     preferred_element_type=jnp.float32)  # [B,W,KV,G,Wt]
    s_s = s_s.reshape(b, w, h, w)
    causal = jnp.tril(jnp.ones((w, w), bool))             # [W, Wt]
    s_s = jnp.where(causal[None, :, None, :], s_s, NEG_INF)
    s_max = jnp.max(s_s, axis=-1)                         # [B, W, H]
    m_t = jnp.maximum(m, s_max)
    p = jnp.where(causal[None, :, None, :],
                  jnp.exp(s_s - m_t[..., None]), 0.0)     # [B, W, H, Wt]
    alpha = jnp.exp(m - m_t)                              # [B, W, H]
    l_t = l * alpha + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bwkgt,btkd->bwkgd",
                    p.reshape(b, w, n_kv, g, w).astype(v_new.dtype),
                    v_new).reshape(b, w, h, d)
    out = (acc * alpha[..., None] + pv) / l_t[..., None]
    return out.astype(q.dtype)


def paged_window_auto(q, k_pool, v_pool, k_new, v_new, table, lengths,
                      k_scale=None, v_scale=None, *,
                      interpret: bool = False, mesh=None) -> jnp.ndarray:
    """Window kernel when backend+shapes allow, dense-gather reference
    (paged_window_reference) otherwise. With ``mesh``, the kernel runs
    under shard_map per tp head shard (paged_window_sharded); the
    reference remains the fallback when tp would split a KV head."""
    from .flash import interpret_env

    interpret = interpret or interpret_env()
    b, w, h, d = q.shape
    probe = jax.ShapeDtypeStruct((b, 1, h * w, d), q.dtype)
    if mesh is not None:
        head_axis = _mesh_head_axis(mesh, h, k_pool.shape[2])
        if head_axis is not None and (interpret or _kernel_ok(probe, k_pool)):
            return paged_window_sharded(q, k_pool, v_pool, k_new, v_new,
                                        table, lengths, k_scale, v_scale,
                                        mesh=mesh, head_axis=head_axis,
                                        interpret=interpret)
        return paged_window_reference(q, k_pool, v_pool, k_new, v_new,
                                      table, lengths, k_scale, v_scale)
    if interpret or _kernel_ok(probe, k_pool):
        return paged_window_attention(q, k_pool, v_pool, k_new, v_new,
                                      table, lengths, k_scale, v_scale,
                                      interpret=interpret)
    return paged_window_reference(q, k_pool, v_pool, k_new, v_new,
                                  table, lengths, k_scale, v_scale)


def paged_window_reference(q, k_pool, v_pool, k_new, v_new, table, lengths,
                           k_scale=None, v_scale=None) -> jnp.ndarray:
    """Dense-gather reference for the window path: the table's blocks
    gathered into contiguous views, then window_attention_appended.
    paged_window_auto's off-kernel fallback, and the path mesh engines
    FORCE (``flash=False`` in paged_llama) — a pallas_call is opaque
    to the GSPMD partitioner."""
    from .attention import window_attention_appended

    ks = gather_blocks(k_scale, table) if k_scale is not None else None
    vs = gather_blocks(v_scale, table) if v_scale is not None else None
    return window_attention_appended(q, gather_blocks(k_pool, table),
                                     gather_blocks(v_pool, table),
                                     k_new, v_new, lengths, ks, vs)


def _kernel_ok(q, k_pool) -> bool:
    from .flash import tpu_backend_ok

    b, _, h, d = q.shape
    block_t, n_kv = k_pool.shape[1], k_pool.shape[2]
    if d % _LANES or h % n_kv or block_t % 8:
        return False
    return tpu_backend_ok()


def _mesh_head_axis(mesh, n_heads: int, n_kv_heads: int):
    """tp axis name when it divides both head counts (the shard_map'able
    condition), else None — the head-splitting-tp jnp fallback."""
    from ..parallel.sharding import attention_shard_axes

    _, head_axis = attention_shard_axes(mesh, 0, n_heads, n_kv_heads)
    return head_axis


def paged_attention_auto(q, k_pool, v_pool, k_new, v_new, table, lengths,
                         k_scale=None, v_scale=None, *,
                         interpret: bool = False, mesh=None) -> jnp.ndarray:
    """Kernel when backend+shapes allow, dense-gather reference
    otherwise. With ``mesh``, the kernel runs under shard_map per tp
    head shard (paged_decode_sharded) — the mesh serving path never
    gathers a dense pool view; the reference remains the fallback when
    tp would split a KV head."""
    from .flash import interpret_env

    interpret = interpret or interpret_env()
    if mesh is not None:
        head_axis = _mesh_head_axis(mesh, q.shape[2], k_pool.shape[2])
        if head_axis is not None and (interpret or _kernel_ok(q, k_pool)):
            return paged_decode_sharded(q, k_pool, v_pool, k_new, v_new,
                                        table, lengths, k_scale, v_scale,
                                        mesh=mesh, head_axis=head_axis,
                                        interpret=interpret)
        return paged_attention_reference(q, k_pool, v_pool, k_new, v_new,
                                         table, lengths, k_scale, v_scale)
    if interpret or _kernel_ok(q, k_pool):
        return paged_decode_attention(q, k_pool, v_pool, k_new, v_new,
                                      table, lengths, k_scale, v_scale,
                                      interpret=interpret)
    return paged_attention_reference(q, k_pool, v_pool, k_new, v_new,
                                     table, lengths, k_scale, v_scale)
