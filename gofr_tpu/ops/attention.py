"""Attention: causal prefill and single-step decode against a KV cache.

Reference-free (GoFr has no compute layer). Designed for the TPU:
  - GQA handled by reshaping Q to [.., kv_heads, group, ..] so the einsum
    stays a large MXU matmul instead of head-looped small ones.
  - Softmax in float32, matmuls in bf16.
  - Decode masks by per-sequence cache length (continuous batching: every
    batch slot has its own cursor).
These jnp paths are the portable baseline (XLA already fuses them well);
they also serve as the numerics reference that the Pallas TPU kernels are
tested against once ``ops.flash`` lands (planned kernel set: flash prefill,
decode attention, quantized matmul).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _repeat_kv_shape(q: jnp.ndarray, n_kv: int) -> jnp.ndarray:
    """[B, S, H, D] -> [B, S, n_kv, group, D] without copying."""
    b, s, h, d = q.shape
    return q.reshape(b, s, n_kv, h // n_kv, d)


def causal_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                     mask: jnp.ndarray | None = None) -> jnp.ndarray:
    """Causal self-attention for prefill.

    q: [B, S, H, D]; k, v: [B, S, KV, D] (KV may divide H for GQA).
    mask: optional [B, S] validity mask (1 = real token, 0 = padding).
    Returns [B, S, H, D].
    """
    b, s, h, d = q.shape
    n_kv = k.shape[2]
    scale = d ** -0.5

    qg = _repeat_kv_shape(q * scale, n_kv)  # [B,S,KV,G,D]
    # scores: [B, KV, G, S, S]
    scores = jnp.einsum("bskgd,btkd->bkgst", qg, k,
                        preferred_element_type=jnp.float32)
    causal = jnp.tril(jnp.ones((s, s), dtype=bool))
    scores = jnp.where(causal[None, None, None], scores, NEG_INF)
    if mask is not None:
        scores = jnp.where(mask[:, None, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgst,btkd->bskgd", probs, v)
    return out.reshape(b, s, h, d)


def decode_attention(q: jnp.ndarray, k_cache: jnp.ndarray, v_cache: jnp.ndarray,
                     lengths: jnp.ndarray) -> jnp.ndarray:
    """Single-token decode attention against a preallocated KV cache.

    q: [B, 1, H, D]; k_cache, v_cache: [B, Smax, KV, D];
    lengths: [B] int32 — number of valid cache entries per sequence
    (INCLUDING the token being decoded, already written to the cache).
    Returns [B, 1, H, D].
    """
    b, _, h, d = q.shape
    smax = k_cache.shape[1]
    n_kv = k_cache.shape[2]
    scale = d ** -0.5

    qg = _repeat_kv_shape(q * scale, n_kv)[:, 0]  # [B,KV,G,D]
    scores = jnp.einsum("bkgd,btkd->bkgt", qg, k_cache,
                        preferred_element_type=jnp.float32)  # [B,KV,G,Smax]
    valid = jnp.arange(smax)[None, :] < lengths[:, None]  # [B,Smax]
    scores = jnp.where(valid[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(v_cache.dtype)
    out = jnp.einsum("bkgt,btkd->bkgd", probs, v_cache)
    return out.reshape(b, 1, h, d)


def decode_attention_appended(q: jnp.ndarray, k_cache: jnp.ndarray,
                              v_cache: jnp.ndarray, k_new: jnp.ndarray,
                              v_new: jnp.ndarray, lengths: jnp.ndarray,
                              k_scale: jnp.ndarray | None = None,
                              v_scale: jnp.ndarray | None = None) -> jnp.ndarray:
    """Decode attention over the cache PLUS the current token's k/v, before
    that token has been written back.

    Mathematically identical to writing the token at position ``lengths``
    and calling ``decode_attention`` with lengths+1, but lets the serving
    step keep the cache read-only inside the layer scan (XLA slices it per
    layer with zero copies) and defer all writes to one post-scan scatter
    on the donated buffer — the difference between ~roofline decode and
    rewriting the whole cache every token.

    q: [B, 1, H, D]; k_cache/v_cache: [B, Smax, KV, D];
    k_new/v_new: [B, 1, KV, D]; lengths: [B] valid entries (EXCLUDING the
    current token). Returns [B, 1, H, D].

    INT8 cache: when ``k_scale``/``v_scale`` [B, Smax, KV] are given the
    cache tensors are per-vector int8 (ops.quant.quantize_kv). The scale is
    constant over the contracted head_dim, so it is applied to the SCORES
    (k side) and folded into the probabilities (v side) — both tiny
    [B,KV,G,Smax] tensors — and the int8->bf16 upcast fuses into the
    einsum: the cache is never materialized in bf16, halving decode's
    dominant HBM stream. k_new/v_new stay bf16 (fresh this step).
    """
    b, _, h, d = q.shape
    smax = k_cache.shape[1]
    n_kv = k_cache.shape[2]
    scale = d ** -0.5

    qg = _repeat_kv_shape(q * scale, n_kv)[:, 0]  # [B,KV,G,D]
    scores_c = jnp.einsum("bkgd,btkd->bkgt", qg, k_cache.astype(qg.dtype),
                          preferred_element_type=jnp.float32)
    if k_scale is not None:
        # k_scale [B,Smax,KV] -> [B,KV,1,Smax] to match scores [B,KV,G,Smax]
        scores_c = scores_c * jnp.transpose(k_scale, (0, 2, 1))[:, :, None, :]
    valid = jnp.arange(smax)[None, :] < lengths[:, None]
    scores_c = jnp.where(valid[:, None, None, :], scores_c, NEG_INF)
    scores_s = jnp.einsum("bkgd,btkd->bkgt", qg, k_new,
                          preferred_element_type=jnp.float32)  # [B,KV,G,1]
    probs = jax.nn.softmax(jnp.concatenate([scores_c, scores_s], axis=-1),
                           axis=-1)
    probs_c = probs[..., :smax]
    if v_scale is not None:
        probs_c = probs_c * jnp.transpose(v_scale, (0, 2, 1))[:, :, None, :]
    vdt = q.dtype if v_scale is not None else v_cache.dtype
    out = (jnp.einsum("bkgt,btkd->bkgd", probs_c.astype(vdt),
                      v_cache.astype(vdt))
           + jnp.einsum("bkgt,btkd->bkgd", probs[..., smax:].astype(v_new.dtype),
                        v_new))
    return out.reshape(b, 1, h, d)


def window_attention_appended(q: jnp.ndarray, k_cache: jnp.ndarray,
                              v_cache: jnp.ndarray, k_new: jnp.ndarray,
                              v_new: jnp.ndarray, lengths: jnp.ndarray,
                              k_scale: jnp.ndarray | None = None,
                              v_scale: jnp.ndarray | None = None) -> jnp.ndarray:
    """decode_attention_appended generalized to a W-token window — the
    speculative-decoding verify pass: window query j attends the cache
    prefix (positions < lengths[b], per slot) plus window positions <= j,
    before any of the window's KV is written back. W=1 reduces exactly to
    the appended decode step; unlike chunk_attention the prefix boundary
    is PER ROW (every slot sits at its own cursor).

    q: [B, W, H, D]; k_cache/v_cache: [B, Smax, KV, D];
    k_new/v_new: [B, W, KV, D]; lengths: [B] valid cache entries
    (EXCLUDING the window). Returns [B, W, H, D]. Int8 cache scales are
    applied score/prob-side exactly as in decode_attention_appended.
    """
    b, w, h, d = q.shape
    smax = k_cache.shape[1]
    n_kv = k_cache.shape[2]
    scale = d ** -0.5

    qg = _repeat_kv_shape(q * scale, n_kv)  # [B,W,KV,G,D]
    scores_c = jnp.einsum("bwkgd,btkd->bkgwt", qg, k_cache.astype(qg.dtype),
                          preferred_element_type=jnp.float32)
    if k_scale is not None:
        scores_c = scores_c * jnp.transpose(
            k_scale, (0, 2, 1))[:, :, None, None, :]
    valid = jnp.arange(smax)[None, :] < lengths[:, None]     # [B, Smax]
    scores_c = jnp.where(valid[:, None, None, None, :], scores_c, NEG_INF)
    scores_s = jnp.einsum("bwkgd,btkd->bkgwt", qg, k_new,
                          preferred_element_type=jnp.float32)  # [B,KV,G,W,W]
    causal = jnp.tril(jnp.ones((w, w), bool))
    scores_s = jnp.where(causal[None, None, None], scores_s, NEG_INF)
    probs = jax.nn.softmax(jnp.concatenate([scores_c, scores_s], axis=-1),
                           axis=-1)
    probs_c = probs[..., :smax]
    if v_scale is not None:
        probs_c = probs_c * jnp.transpose(
            v_scale, (0, 2, 1))[:, :, None, None, :]
    vdt = q.dtype if v_scale is not None else v_cache.dtype
    out = (jnp.einsum("bkgwt,btkd->bwkgd", probs_c.astype(vdt),
                      v_cache.astype(vdt))
           + jnp.einsum("bkgwt,btkd->bwkgd",
                        probs[..., smax:].astype(v_new.dtype), v_new))
    return out.reshape(b, w, h, d)


def chunk_attention(q: jnp.ndarray, k_cache: jnp.ndarray, v_cache: jnp.ndarray,
                    k_new: jnp.ndarray, v_new: jnp.ndarray,
                    start: jnp.ndarray,
                    k_scale: jnp.ndarray | None = None,
                    v_scale: jnp.ndarray | None = None) -> jnp.ndarray:
    """Chunked-prefill attention: a block of C new tokens at positions
    [start, start+C) attends to the cache prefix (positions < start) plus
    causally within the chunk — the long-prompt path, processing prompts in
    fixed-size chunks so arbitrary prompt lengths serve from a small
    lattice of compiled shapes.

    q: [B, C, H, D]; k_cache/v_cache: [B, Smax, KV, D];
    k_new/v_new: [B, C, KV, D]; start: scalar int32.
    ``k_scale``/``v_scale`` [B, Smax, KV]: per-vector scales for int8
    caches (see decode_attention_appended — same fused-dequant scheme).
    Trailing padding inside the chunk is harmless: causality means padded
    positions are never attended BY valid ones. Returns [B, C, H, D].
    """
    b, c, h, d = q.shape
    smax = k_cache.shape[1]
    n_kv = k_cache.shape[2]
    scale = d ** -0.5

    qg = _repeat_kv_shape(q * scale, n_kv)  # [B,C,KV,G,D]
    scores_c = jnp.einsum("bskgd,btkd->bkgst", qg, k_cache.astype(qg.dtype),
                          preferred_element_type=jnp.float32)  # [B,KV,G,C,Smax]
    if k_scale is not None:
        scores_c = scores_c * jnp.transpose(k_scale, (0, 2, 1))[:, :, None, None, :]
    in_prefix = jnp.arange(smax)[None, :] < start  # [1,Smax]
    scores_c = jnp.where(in_prefix[None, None, None], scores_c, NEG_INF)
    scores_n = jnp.einsum("bskgd,btkd->bkgst", qg, k_new,
                          preferred_element_type=jnp.float32)  # [B,KV,G,C,C]
    causal = jnp.tril(jnp.ones((c, c), dtype=bool))
    scores_n = jnp.where(causal[None, None, None], scores_n, NEG_INF)
    probs = jax.nn.softmax(
        jnp.concatenate([scores_c, scores_n], axis=-1), axis=-1)
    probs_c = probs[..., :smax]
    if v_scale is not None:
        probs_c = probs_c * jnp.transpose(v_scale, (0, 2, 1))[:, :, None, None, :]
    vdt = q.dtype if v_scale is not None else v_cache.dtype
    out = (jnp.einsum("bkgst,btkd->bskgd",
                      probs_c.astype(vdt), v_cache.astype(vdt))
           + jnp.einsum("bkgst,btkd->bskgd",
                        probs[..., smax:].astype(v_new.dtype), v_new))
    return out.reshape(b, c, h, d)


def full_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mask: jnp.ndarray | None = None) -> jnp.ndarray:
    """Bidirectional attention (BERT/ViT encoders). Shapes as causal_attention."""
    b, s, h, d = q.shape
    n_kv = k.shape[2]
    scale = d ** -0.5
    qg = _repeat_kv_shape(q * scale, n_kv)
    scores = jnp.einsum("bskgd,btkd->bkgst", qg, k,
                        preferred_element_type=jnp.float32)
    if mask is not None:
        scores = jnp.where(mask[:, None, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgst,btkd->bskgd", probs, v)
    return out.reshape(b, s, h, d)
