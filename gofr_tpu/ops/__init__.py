"""TPU-native compute ops: norms, rotary embeddings, attention, quantization.

Pure-JAX paths, shape-static and jit/vmap/shard_map compatible; Pallas TPU
kernels for the hot ops land in ``ops.flash`` (these jnp versions stay as
the portable fallback and numerics reference). No reference equivalent —
the reference (GoFr) has no compute layer; this is the TPU graft's core.
"""

from .norms import rms_norm, layer_norm
from .rope import rope_frequencies, apply_rope
from .attention import causal_attention, decode_attention
from .quant import quantize_int8, QuantizedLinear, qmatmul
from .ring_attention import make_ring_attention, ring_causal_attention

__all__ = [
    "rms_norm",
    "layer_norm",
    "rope_frequencies",
    "apply_rope",
    "causal_attention",
    "decode_attention",
    "quantize_int8",
    "QuantizedLinear",
    "qmatmul",
    "make_ring_attention",
    "ring_causal_attention",
]
