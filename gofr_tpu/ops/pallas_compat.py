"""Cross-version Pallas TPU aliases.

jax renamed ``pltpu.TPUCompilerParams`` to ``pltpu.CompilerParams``;
resolve the name once here, locally, instead of monkeypatching the
upstream module (which would silently change behavior for any other
code importing pallas in the same process).
"""

from __future__ import annotations

from jax.experimental.pallas import tpu as _pltpu

CompilerParams = getattr(_pltpu, "CompilerParams", None) \
    or _pltpu.TPUCompilerParams
