"""Pallas TPU flash attention for causal prefill.

The jnp reference (ops.attention.causal_attention) materializes
[B, KV, G, S, S] f32 scores — fine at S=512, hostile to long-context
prefill and the TTFT target at larger prompt buckets (VERDICT r1 weak
#6). This kernel runs the online-softmax recurrence over a
(B, H, S/BLOCK_Q, S/BLOCK_K) grid: Pallas pipelines one [BLOCK_K, D]
K/V block at a time from HBM into VMEM (double-buffered by the runtime),
the scores tile [BLOCK_Q, BLOCK_K] never leaves VMEM, and the running
(max, sum, acc) state lives in VMEM scratch that persists across the
innermost grid dimension — peak VMEM is O(BLOCK_Q * D), independent of
sequence length.

  GQA: the kv head for query head h is h * KV // H — the index map picks
  the right K/V pane per program, no host-side repeat.
  Causality: k blocks fully above the diagonal skip their compute (the
  runtime still streams them; the compute skip is the win — matching the
  stock Pallas flash pattern).
  Ragged batches: a per-sequence ``lengths`` vector masks keys past the
  true prompt end, and fully-padded query rows emit zeros.

``causal_attention_auto`` dispatches: kernel on TPU backends for aligned
shapes, jnp reference otherwise (CPU tests, tiny buckets, odd dims).
The reference stays the numerics oracle — tests/test_flash.py asserts
allclose between the two on CPU via Pallas interpret mode.

Sharding: a pallas_call is a custom call — opaque to the GSPMD
partitioner — so flash must not be traced BARE inside a mesh-sharded
jit. On a mesh, ``causal_attention_auto`` instead wraps the kernel in
``shard_map`` over the tp (and data) axes: every device runs this
single-device kernel on its local [KV/tp] head shard, with no
collectives inside attention (the o-proj psum downstream is
unchanged). The jnp reference remains the fallback when tp would
split a KV head (parallel.sharding.attention_shard_axes).

Backward: flash is an inference-path kernel here (prefill admission);
the custom VJP recomputes attention with the jnp reference so code that
differentiates through a flash-enabled forward still works.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_compat import CompilerParams

from .attention import causal_attention

try:  # jax >= 0.4.35 exposes shard_map at the top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

NEG_INF = -1e30
_LANES = 128  # VMEM scratch minor dim (min f32 tile is 8 x 128)


def interpret_env() -> bool:
    """GOFR_FLASH_INTERPRET=1 forces Pallas interpret mode through every
    ops/*_auto dispatcher — the CPU escape hatch that lets engine-level
    tests and the mesh A/B bench exercise the kernels without a TPU.
    Re-read every call so tests can flip it per-case."""
    return os.environ.get("GOFR_FLASH_INTERPRET") == "1"


def fit_block(n: int, block: int) -> int:
    """Shrink ``block`` until it divides ``n``: clamp to n, then halve
    (1 in the worst case — everything divides by 1). Interpret mode
    only: on device the Mosaic tile constraints make sub-8 blocks
    unloweable, so the non-interpret dispatchers gate instead of
    clamping."""
    block = min(block, n) if n else block
    while block > 1 and n % block:
        block //= 2
    return max(block, 1)


def _flash_kernel(lengths_ref, q_ref, k_ref, v_ref, o_ref,
                  m_ref, l_ref, acc_ref, *,
                  block_q: int, block_k: int, scale: float):
    """One (batch, head, q-block, k-block) step of the online softmax.

    m/l/acc scratch persists across the innermost (k-block) grid dim:
    initialized at the first k block, folded every in-diagonal block,
    normalized and written out at the last one.
    """
    qi, ki = pl.program_id(2), pl.program_id(3)
    n_k = pl.num_programs(3)
    length = lengths_ref[pl.program_id(0)]

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # causal skip: this k block participates only if its first row is at
    # or below the q block's last row
    @pl.when(ki * block_k < (qi + 1) * block_q)
    def _compute():
        q = q_ref[0, 0, :, :] * scale                       # [BQ, D]
        k_blk = k_ref[0, 0, :, :]                           # [BK, D]
        v_blk = v_ref[0, 0, :, :]
        s = jax.lax.dot_general(
            q, k_blk, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # [BQ, BK]
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where((k_pos <= q_pos) & (k_pos < length), s, NEG_INF)

        m_prev = m_ref[:, :1]                               # [BQ, 1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                              # [BQ, BK]
        corr = jnp.exp(m_prev - m_new)                      # [BQ, 1]
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _finalize():
        l = l_ref[:, :1]
        out = acc_ref[:] / jnp.where(l == 0.0, 1.0, l)
        # fully-padded query rows (q_pos >= length) emit zeros
        q_rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)
        out = jnp.where(q_rows < length, out, 0.0)
        o_ref[0, 0, :, :] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_q", "block_k",
                                             "interpret"))
def flash_causal_prefill(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                         lengths: jnp.ndarray, *, block_q: int = 128,
                         block_k: int = 128,
                         interpret: bool = False) -> jnp.ndarray:
    """Causal prefill attention without S² materialization.

    q: [B, S, H, D]; k, v: [B, S, KV, D] (KV divides H); lengths: [B]
    int32 true prompt lengths (keys past a row's length are masked;
    query rows past it produce zeros). Requires S divisible by both
    blocks (callers dispatch through causal_attention_auto, which falls
    back to the jnp reference otherwise).
    Returns [B, S, H, D] in q.dtype.
    """
    b, s, h, d = q.shape
    kv = k.shape[2]
    if s % block_q or s % block_k:
        raise ValueError(f"S={s} not divisible by blocks "
                         f"({block_q}, {block_k})")
    scale = d ** -0.5
    grid = (b, h, s // block_q, s // block_k)

    # Mosaic requires the last two BLOCK dims divisible by (8, 128) or
    # equal to the array dims. In [B, S, H, D] layout the natural block
    # (1, block_q, 1, d) ends in (1, d) — unloweable (VERDICT r2 weak
    # #3). Transpose to [B, H, S, D] so blocks end in (block_q, d); the
    # transposes are plain XLA copies fused around the custom call.
    qt = q.transpose(0, 2, 1, 3)                            # [B, H, S, D]
    kt = k.transpose(0, 2, 1, 3)                            # [B, KV, S, D]
    vt = v.transpose(0, 2, 1, 3)

    kernel = functools.partial(_flash_kernel, block_q=block_q,
                               block_k=block_k, scale=scale)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,  # lengths
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, block_q, d),
                             lambda bi, hi, qi, ki, lens: (bi, hi, qi, 0)),
                pl.BlockSpec((1, 1, block_k, d),
                             lambda bi, hi, qi, ki, lens:
                             (bi, hi * kv // h, ki, 0)),
                pl.BlockSpec((1, 1, block_k, d),
                             lambda bi, hi, qi, ki, lens:
                             (bi, hi * kv // h, ki, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, block_q, d),
                                   lambda bi, hi, qi, ki, lens:
                                   (bi, hi, qi, 0)),
            scratch_shapes=[
                pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max
                pltpu.VMEM((block_q, _LANES), jnp.float32),  # running sum
                pltpu.VMEM((block_q, d), jnp.float32),       # accumulator
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(lengths.astype(jnp.int32), qt, kt, vt)
    return out.transpose(0, 2, 1, 3)                        # [B, S, H, D]


def tpu_backend_ok() -> bool:
    """Shared Mosaic-target gate for all Pallas kernels in ops/:
    GOFR_DISABLE_FLASH kills every kernel path; the ALLOWLIST covers
    "tpu" proper and the axon PJRT plugin — GPU/other backends cannot
    lower these kernels."""
    if os.environ.get("GOFR_DISABLE_FLASH"):
        return False
    try:
        platform = jax.devices()[0].platform
    except Exception:
        return False
    return platform in ("tpu", "axon")


def _kernel_ok(q: jnp.ndarray, block_q: int, block_k: int) -> bool:
    b, s, h, d = q.shape
    if d % 128 or s < 2 * block_q or s % block_q or s % block_k:
        return False
    return tpu_backend_ok()


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_diffable(q, k, v, lengths, interpret, block_q=128, block_k=128):
    return flash_causal_prefill(q, k, v, lengths, block_q=block_q,
                                block_k=block_k, interpret=interpret)


def _flash_fwd(q, k, v, lengths, interpret, block_q=128, block_k=128):
    return (_flash_diffable(q, k, v, lengths, interpret, block_q, block_k),
            (q, k, v, lengths))


def _flash_bwd(interpret, block_q, block_k, res, g):
    # Inference kernel; gradients recompute via the jnp oracle so a
    # flash-enabled forward stays differentiable (training keeps the
    # reference path anyway).
    q, k, v, lengths = res
    s = q.shape[1]
    mask = jax.lax.broadcasted_iota(
        jnp.int32, (q.shape[0], s), 1) < lengths[:, None]
    _, vjp = jax.vjp(lambda q_, k_, v_: causal_attention(q_, k_, v_, mask),
                     q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


_flash_diffable.defvjp(_flash_fwd, _flash_bwd)


def flash_prefill_sharded(q, k, v, lengths, *, mesh, batch_axes=(),
                          head_axis=None, block_q: int = 128,
                          block_k: int = 128,
                          interpret: bool = False) -> jnp.ndarray:
    """shard_map'd flash prefill: every device runs the single-device
    kernel on its local head shard (heads over ``head_axis``, batch over
    ``batch_axes`` when set — parallel.sharding.attention_shard_axes
    picks both). Lengths ride replicated unless batch shards. No
    collectives inside attention; check_rep is off because a
    pallas_call has no replication rule."""
    from jax.sharding import PartitionSpec as P

    bax = tuple(batch_axes) or None
    qspec = P(bax, None, head_axis, None)
    def run(q, k, v, lengths):
        return _flash_diffable(q, k, v, lengths, interpret, block_q, block_k)

    fn = shard_map(run, mesh=mesh,
                   in_specs=(qspec, qspec, qspec, P(bax)),
                   out_specs=qspec, check_rep=False)
    return fn(q, k, v, lengths)


def causal_attention_auto(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                          lengths: jnp.ndarray | None = None,
                          mask: jnp.ndarray | None = None, *,
                          block_q: int = 128, block_k: int = 128,
                          interpret: bool = False,
                          mesh=None) -> jnp.ndarray:
    """Flash kernel when the backend+shapes allow, jnp reference otherwise.

    Accepts ``lengths`` [B] or a PREFIX validity ``mask`` [B, S]
    (right-padded prompts — the only mask shape the model layer
    produces). A non-prefix mask is honored only by the reference
    fallback; the kernel path derives lengths as mask.sum(-1), which is
    equivalent for prefix masks alone.

    With ``mesh``, the kernel is wrapped in shard_map over the tp/data
    axes (flash_prefill_sharded); the reference — which GSPMD partitions
    fine on its own — remains the fallback when tp would split a KV head
    or the shapes fail the kernel gate.
    """
    interpret = interpret or interpret_env()
    if lengths is None and mask is not None:
        lengths = mask.astype(jnp.int32).sum(axis=-1)
    if lengths is not None and mask is None:
        s = q.shape[1]
        mask = jax.lax.broadcasted_iota(
            jnp.int32, (q.shape[0], s), 1) < lengths[:, None]
    if lengths is None:
        lengths = jnp.full((q.shape[0],), q.shape[1], jnp.int32)
        mask = None
    if interpret:
        block_q = fit_block(q.shape[1], block_q)
        block_k = fit_block(q.shape[1], block_k)
    if mesh is not None:
        from ..parallel.sharding import attention_shard_axes

        batch_axes, head_axis = attention_shard_axes(
            mesh, q.shape[0], q.shape[2], k.shape[2])
        if (head_axis is not None or batch_axes) and \
                (interpret or _kernel_ok(q, block_q, block_k)):
            return flash_prefill_sharded(
                q, k, v, lengths.astype(jnp.int32), mesh=mesh,
                batch_axes=batch_axes, head_axis=head_axis,
                block_q=block_q, block_k=block_k, interpret=interpret)
        return causal_attention(q, k, v, mask=mask)
    if interpret or _kernel_ok(q, block_q, block_k):
        return _flash_diffable(q, k, v, lengths.astype(jnp.int32), interpret,
                               block_q, block_k)
    return causal_attention(q, k, v, mask=mask)
