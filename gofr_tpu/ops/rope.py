"""Rotary position embeddings (RoPE), Llama-3 style with NTK scaling hook.

Frequencies are precomputed once per model (static shapes — nothing here
re-traces per step); application is a fused elementwise op that XLA folds
into the surrounding attention computation.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_seq: int, theta: float = 500000.0,
                     scaling: dict | None = None) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Precompute (cos, sin) tables of shape [max_seq, head_dim//2].

    ``scaling`` supports the Llama-3 frequency-scaling dict
    {factor, low_freq_factor, high_freq_factor, original_max_position}.
    """
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    if scaling:
        factor = scaling.get("factor", 8.0)
        low = scaling.get("low_freq_factor", 1.0)
        high = scaling.get("high_freq_factor", 4.0)
        orig = scaling.get("original_max_position", 8192)
        wavelen = 2.0 * jnp.pi / inv_freq
        ratio = orig / wavelen
        smooth = jnp.clip((ratio - low) / (high - low), 0.0, 1.0)
        inv_freq = jnp.where(
            wavelen > orig / low,  # long wavelengths: fully scaled
            inv_freq / factor,
            inv_freq * smooth + (inv_freq / factor) * (1.0 - smooth),
        )
    t = jnp.arange(max_seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # [max_seq, head_dim//2]
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray,
               positions: jnp.ndarray | None) -> jnp.ndarray:
    """Rotate ``x`` [..., seq, heads, head_dim] by per-token positions.

    ``positions`` is [..., seq] int32 — explicit positions (not an offset)
    so continuous batching can give every sequence its own cursor.

    ``positions=None`` means ``cos``/``sin`` are already per-token
    ([..., seq, hd/2], i.e. pre-gathered by the caller). Sharded forwards
    use this to gather ONCE outside the layer scan under an activation
    sharding constraint — gathering inside each layer let GSPMD pick a
    feature-dim sharding for the [B, S, hd/2] result and then
    involuntarily full-rematerialize it back to the (data, sp) layout
    every step (the MULTICHIP_r03 spmd_partitioner warnings).
    """
    dtype = x.dtype
    if positions is None:
        c = cos[..., :, None, :]             # [..., seq, 1, hd/2]
        s = sin[..., :, None, :]
    else:
        c = cos[positions][..., :, None, :]  # [..., seq, 1, hd/2]
        s = sin[positions][..., :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(dtype)
