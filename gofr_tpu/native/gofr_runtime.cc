// gofr_tpu native runtime: coalescing scheduler + lock-free telemetry.
//
// The reference framework's runtime is the Go scheduler + net/http
// (SURVEY §2: all components pure Go); this framework's Python control
// plane gets its hot-path primitives from this library instead:
//
//   gq_*    coalescing batch queue — the serving scheduler. Handler
//           threads push request ids; one dispatcher blocks HERE (outside
//           the GIL) until a batch is ready: full batch -> immediate
//           flush, else flush when the oldest item has waited max_delay.
//
//   hist_*  fixed-bucket histograms with atomic counters — per-op
//           observability on the µs-scale device path (SURVEY §7 hard
//           part (d)) without a Python-level lock per record.
//
// Pure C ABI for ctypes (no pybind11 in the image). Thread-safety:
// gq is MPMC-safe; hist_record is wait-free (relaxed atomics), snapshots
// are eventually consistent which is all Prometheus scrapes need.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <vector>

using Clock = std::chrono::steady_clock;

namespace {

struct Item {
  uint64_t id;
  Clock::time_point enqueued;
};

struct GQueue {
  std::mutex mu;
  std::condition_variable cv;
  std::deque<Item> items;
  int max_batch;
  std::chrono::duration<double> max_delay;
  bool closed = false;
};

struct Histogram {
  std::vector<double> bounds;                       // ascending
  std::vector<std::atomic<uint64_t>> counts;        // bounds.size()+1 (+inf)
  std::atomic<uint64_t> count{0};
  std::atomic<uint64_t> sum_bits{0};                // double bits, CAS-accumulated

  explicit Histogram(const double* b, int n)
      : bounds(b, b + n), counts(n + 1) {}

  void record(double v) {
    // linear scan: bucket lists are short (<=20) and branch-predictable
    size_t i = 0;
    while (i < bounds.size() && v > bounds[i]) ++i;
    counts[i].fetch_add(1, std::memory_order_relaxed);
    count.fetch_add(1, std::memory_order_relaxed);
    uint64_t old = sum_bits.load(std::memory_order_relaxed);
    double next;
    uint64_t next_bits;
    do {
      double cur;
      std::memcpy(&cur, &old, sizeof cur);
      next = cur + v;
      std::memcpy(&next_bits, &next, sizeof next_bits);
    } while (!sum_bits.compare_exchange_weak(old, next_bits,
                                             std::memory_order_relaxed));
  }
};

}  // namespace

extern "C" {

// ---- coalescing queue ------------------------------------------------------

void* gq_new(int max_batch, double max_delay_s) {
  auto* q = new GQueue();
  q->max_batch = max_batch < 1 ? 1 : max_batch;
  q->max_delay = std::chrono::duration<double>(max_delay_s);
  return q;
}

void gq_free(void* h) { delete static_cast<GQueue*>(h); }

int gq_push(void* h, uint64_t id) {
  auto* q = static_cast<GQueue*>(h);
  {
    std::lock_guard<std::mutex> lk(q->mu);
    if (q->closed) return -1;
    q->items.push_back({id, Clock::now()});
  }
  q->cv.notify_one();
  return 0;
}

// Blocks until a flush condition holds, then pops up to `cap` ids into
// `out` and stores the oldest item's wait in seconds. Returns the batch
// size, or 0 when the queue is closed and drained.
int gq_pop_batch(void* h, uint64_t* out, int cap, double* oldest_wait_s) {
  auto* q = static_cast<GQueue*>(h);
  std::unique_lock<std::mutex> lk(q->mu);
  for (;;) {
    if (!q->items.empty()) {
      auto now = Clock::now();
      auto oldest = now - q->items.front().enqueued;
      if (static_cast<int>(q->items.size()) >= q->max_batch ||
          oldest >= q->max_delay || q->closed) {
        int n = 0;
        int limit = cap < q->max_batch ? cap : q->max_batch;
        while (n < limit && !q->items.empty()) {
          out[n++] = q->items.front().id;
          q->items.pop_front();
        }
        if (oldest_wait_s)
          *oldest_wait_s = std::chrono::duration<double>(oldest).count();
        return n;
      }
      q->cv.wait_for(lk, q->max_delay - oldest);
    } else if (q->closed) {
      return 0;
    } else {
      q->cv.wait(lk);
    }
  }
}

void gq_close(void* h) {
  auto* q = static_cast<GQueue*>(h);
  {
    std::lock_guard<std::mutex> lk(q->mu);
    q->closed = true;
  }
  q->cv.notify_all();
}

int gq_size(void* h) {
  auto* q = static_cast<GQueue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  return static_cast<int>(q->items.size());
}

// ---- histograms ------------------------------------------------------------

void* hist_new(const double* bounds, int n) {
  return new Histogram(bounds, n);
}

void hist_free(void* h) { delete static_cast<Histogram*>(h); }

void hist_record(void* h, double v) {
  static_cast<Histogram*>(h)->record(v);
}

// counts must have room for n_bounds+1 entries (last = +inf bucket).
void hist_snapshot(void* h, uint64_t* counts, double* sum, uint64_t* count) {
  auto* hist = static_cast<Histogram*>(h);
  for (size_t i = 0; i < hist->counts.size(); ++i)
    counts[i] = hist->counts[i].load(std::memory_order_relaxed);
  uint64_t bits = hist->sum_bits.load(std::memory_order_relaxed);
  std::memcpy(sum, &bits, sizeof *sum);
  *count = hist->count.load(std::memory_order_relaxed);
}

}  // extern "C"
