"""ctypes loader for the native runtime (gofr_runtime.cc).

Build model: the shared library is compiled on first import (g++ -O2
-shared, ~1s) and cached next to the source; environments without a
toolchain fall back to pure-Python equivalents — every native consumer
(batcher, metrics) keeps a fallback path, mirroring how the reference
degrades gracefully when a datasource is absent
(container/container.go:55-126).

Set GOFR_NATIVE=0 to force the Python paths (useful for debugging).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "gofr_runtime.cc")
_SO = os.path.join(_DIR, "libgofr_runtime.so")

_lib = None
_load_lock = threading.Lock()
_load_attempted = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
             "-o", _SO, _SRC],
            check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u64 = ctypes.c_uint64
    lib.gq_new.restype = ctypes.c_void_p
    lib.gq_new.argtypes = [ctypes.c_int, ctypes.c_double]
    lib.gq_free.argtypes = [ctypes.c_void_p]
    lib.gq_push.restype = ctypes.c_int
    lib.gq_push.argtypes = [ctypes.c_void_p, u64]
    lib.gq_pop_batch.restype = ctypes.c_int
    lib.gq_pop_batch.argtypes = [ctypes.c_void_p, ctypes.POINTER(u64),
                                 ctypes.c_int, ctypes.POINTER(ctypes.c_double)]
    lib.gq_close.argtypes = [ctypes.c_void_p]
    lib.gq_size.restype = ctypes.c_int
    lib.gq_size.argtypes = [ctypes.c_void_p]
    lib.hist_new.restype = ctypes.c_void_p
    lib.hist_new.argtypes = [ctypes.POINTER(ctypes.c_double), ctypes.c_int]
    lib.hist_free.argtypes = [ctypes.c_void_p]
    lib.hist_record.argtypes = [ctypes.c_void_p, ctypes.c_double]
    lib.hist_snapshot.argtypes = [ctypes.c_void_p, ctypes.POINTER(u64),
                                  ctypes.POINTER(ctypes.c_double),
                                  ctypes.POINTER(u64)]
    return lib


def load():
    """The native library, or None when unavailable."""
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    with _load_lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        if os.environ.get("GOFR_NATIVE", "1") == "0":
            return None
        try:
            if not os.path.exists(_SO) or (
                    os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                if not _build():
                    return None
            _lib = _bind(ctypes.CDLL(_SO))
        except OSError:
            _lib = None
        return _lib


def available() -> bool:
    return load() is not None


class NativeBatchQueue:
    """MPMC coalescing id queue; pop blocks in C with the GIL released."""

    def __init__(self, max_batch: int, max_delay: float):
        lib = load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._q = lib.gq_new(max_batch, max_delay)
        self.max_batch = max_batch
        self._out = (ctypes.c_uint64 * max_batch)()
        self._wait = ctypes.c_double()

    def push(self, item_id: int) -> bool:
        return self._lib.gq_push(self._q, item_id) == 0

    def pop_batch(self) -> tuple[list[int], float]:
        """Block until a batch is ready; ([], 0.0) means closed+drained."""
        n = self._lib.gq_pop_batch(self._q, self._out, self.max_batch,
                                   ctypes.byref(self._wait))
        return list(self._out[:n]), self._wait.value

    def close(self) -> None:
        self._lib.gq_close(self._q)

    def __len__(self) -> int:
        return self._lib.gq_size(self._q)

    def __del__(self):
        try:
            if self._q:
                self._lib.gq_close(self._q)
                self._lib.gq_free(self._q)
                self._q = None
        except Exception:
            pass


class NativeHistogram:
    """Wait-free fixed-bucket histogram (record is one C call, no lock)."""

    def __init__(self, bounds):
        lib = load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self.bounds = tuple(bounds)
        arr = (ctypes.c_double * len(bounds))(*bounds)
        self._h = lib.hist_new(arr, len(bounds))

    def record(self, value: float) -> None:
        self._lib.hist_record(self._h, value)

    def snapshot(self) -> tuple[list[int], float, int]:
        """(per-bucket counts [len(bounds)+1], sum, count). Buffers are
        allocated per call: concurrent scrape threads must not share them."""
        counts = (ctypes.c_uint64 * (len(self.bounds) + 1))()
        total = ctypes.c_double()
        count = ctypes.c_uint64()
        self._lib.hist_snapshot(self._h, counts, ctypes.byref(total),
                                ctypes.byref(count))
        return list(counts), total.value, count.value

    def __del__(self):
        try:
            if self._h:
                self._lib.hist_free(self._h)
                self._h = None
        except Exception:
            pass
