"""Subscription manager: one worker per topic, commit-on-success.

Reference: pkg/gofr/subscriber.go:11-46 — topic->handler map, one goroutine
per topic started from App.Run (gofr.go:154-161), infinite loop Subscribe ->
build Context from Message -> run handler -> Commit on nil error
(at-least-once). Here each topic gets a daemon thread with a stop event so
tests and graceful shutdown work.
"""

from __future__ import annotations

import threading
from typing import Callable

from .container import Container
from .context import Context


class SubscriptionManager:
    def __init__(self, container: Container):
        self.container = container
        self.subscriptions: dict[str, Callable] = {}
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()

    def register(self, topic: str, handler: Callable) -> None:
        self.subscriptions[topic] = handler

    def start(self) -> None:
        for topic, handler in self.subscriptions.items():
            t = threading.Thread(
                target=self._consume_loop, args=(topic, handler),
                daemon=True, name=f"subscriber-{topic}",
            )
            t.start()
            self._threads.append(t)

    def _consume_loop(self, topic: str, handler: Callable) -> None:
        c = self.container
        log = c.logger
        while not self._stop.is_set():
            sub = c.get_subscriber()
            if sub is None:
                log.error({"event": "no subscriber configured", "topic": topic})
                return
            try:
                msg = sub.subscribe(topic, timeout=0.5)
            except Exception as e:
                log.error({"event": "subscribe error", "topic": topic, "error": repr(e)})
                self._stop.wait(0.5)  # backoff: a down broker must not busy-loop
                continue
            if msg is None:  # timeout — loop to re-check stop flag
                continue
            c.metrics.increment_counter("app_pubsub_subscribe_total_count", topic=topic)
            ctx = Context(request=msg, container=c)
            try:
                handler(ctx)
            except Exception as e:
                log.error({"event": "subscriber handler error", "topic": topic, "error": repr(e)})
                continue  # no commit -> redelivery (at-least-once)
            try:
                msg.commit()
            except Exception as e:
                log.error({"event": "commit failed", "topic": topic, "error": repr(e)})
                continue
            c.metrics.increment_counter("app_pubsub_subscribe_success_count", topic=topic)

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=timeout)
        self._threads.clear()
