"""CLI runtime: regex sub-command routes with flag binding.

Reference: pkg/gofr/cmd.go:27-63 — non-flag args are joined into a command
string matched against regex route patterns; pkg/gofr/cmd/request.go:25-67
parses ``-k``, ``--k`` and ``-k=v`` flags; the responder prints data to
stdout and errors to stderr (cmd/responder.go:10-19).
"""

from __future__ import annotations

import re
import sys
from typing import Any, Iterable

from .context import Context


class CmdRequest:
    """Implements the framework Request surface over argv flags."""

    def __init__(self, args: list[str], flags: dict[str, str]):
        self.args = args
        self.flags = flags
        self.path_params: dict[str, str] = {}

    def param(self, key: str, default: str = "") -> str:
        return self.flags.get(key, default)

    def path_param(self, key: str, default: str = "") -> str:
        return self.flags.get(key, self.path_params.get(key, default))

    def bind(self, into: type | None = None) -> Any:
        """Bind flags into a dataclass (reference cmd/request.go:89-118
        reflection-binds string/bool/int fields)."""
        if into is None:
            return dict(self.flags)
        import dataclasses

        if dataclasses.is_dataclass(into):
            kwargs = {}
            for f in dataclasses.fields(into):
                if f.name not in self.flags:
                    continue
                raw = self.flags[f.name]
                if f.type in (int, "int"):
                    kwargs[f.name] = int(raw)
                elif f.type in (bool, "bool"):
                    kwargs[f.name] = raw.lower() in ("", "1", "true", "yes")
                else:
                    kwargs[f.name] = raw
            return into(**kwargs)
        return into(dict(self.flags))

    def header(self, key: str, default: str = "") -> str:
        return default

    def host_name(self) -> str:
        return "cli"


def parse_args(argv: list[str]) -> tuple[list[str], dict[str, str]]:
    """Split argv into positional args and flags (cmd/request.go:25-67)."""
    args: list[str] = []
    flags: dict[str, str] = {}
    i = 0
    while i < len(argv):
        a = argv[i]
        if a.startswith("-"):
            key = a.lstrip("-")
            if "=" in key:
                k, _, v = key.partition("=")
                flags[k] = v
            elif i + 1 < len(argv) and not argv[i + 1].startswith("-"):
                flags[key] = argv[i + 1]
                i += 1
            else:
                flags[key] = "true"
        else:
            args.append(a)
        i += 1
    return args, flags


def run_cmd(app, argv: Iterable[str] | None = None) -> int:
    """Match the joined args against registered sub-command patterns and run
    the handler (reference cmd.go:31-52). Returns a process exit code."""
    argv = list(argv if argv is not None else sys.argv[1:])
    args, flags = parse_args(argv)
    command = " ".join(args)

    for pattern, handler, _desc in app._cmd_routes:
        m = re.fullmatch(pattern, command)
        if m is None:
            continue
        req = CmdRequest(args, flags)
        req.path_params.update(m.groupdict())
        ctx = Context(request=req, container=app.container)
        try:
            data = handler(ctx)
        except Exception as e:
            print(str(e), file=sys.stderr)  # noqa: T201 — command output
            return 1
        if data is not None:
            print(data if isinstance(data, str)  # noqa: T201 — command output
                  else _render(data))
        return 0

    if app._cmd_routes:
        print("No Command Found!", file=sys.stderr)  # noqa: T201 — command output
        _print_help(app)
    return 1


def _render(data: Any) -> str:
    import json

    try:
        return json.dumps(data, indent=2, default=str)
    except TypeError:
        return str(data)


def _print_help(app) -> None:
    for pattern, _h, desc in app._cmd_routes:
        print(f"  {pattern:<30} {desc}", file=sys.stderr)  # noqa: T201 — command output
