"""Datasource layer: shared health types and the reduced logger surface.

Reference: pkg/gofr/datasource/health.go:3-11 (Health type + status consts)
and datasource/logger.go:10-16 (reduced Logger interface so datasources do
not depend on the full logging package).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Protocol, runtime_checkable

STATUS_UP = "UP"
STATUS_DOWN = "DOWN"
STATUS_DEGRADED = "DEGRADED"


@dataclass
class Health:
    status: str = STATUS_DOWN
    details: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {"status": self.status, "details": self.details}


@runtime_checkable
class DSLogger(Protocol):
    def debug(self, *args: Any) -> None: ...
    def info(self, *args: Any) -> None: ...
    def warn(self, *args: Any) -> None: ...
    def error(self, *args: Any) -> None: ...
