"""SQL datasource: observable DB-API wrapper with dataclass row mapping.

Reference: pkg/gofr/datasource/sql/ —
  - DB wrapper logging every Query/Exec/Tx op with µs duration into the
    ``app_sql_stats`` histogram (db.go:15-148, logQuery db.go:30)
  - reflection-based ``Select`` into struct/slice with snake-case field
    mapping (db.go:179-279)
  - dialect/connection handling (sql.go:29-92) with graceful degradation
  - conn-pool gauges (sql.go:94-105) and health with pool stats
    (health.go:26-65)

Dialects: ``sqlite`` (stdlib, default — the hermetic test seam, playing the
role go-sqlmock plays in the reference), ``mysql``/``postgres`` gated behind
optional driver imports. Queries use ``?`` placeholders; they are translated
to the driver's paramstyle.
"""

from __future__ import annotations

import dataclasses
import re
import threading
import time
from typing import Any, Sequence

from . import DSLogger, Health, STATUS_DOWN, STATUS_UP


def to_snake_case(name: str) -> str:
    """CamelCase/mixedCase -> snake_case (reference db.go:279 ToSnakeCase)."""
    s = re.sub(r"(.)([A-Z][a-z]+)", r"\1_\2", name)
    return re.sub(r"([a-z0-9])([A-Z])", r"\1_\2", s).lower()


def _translate_placeholders(query: str, paramstyle: str) -> str:
    """Rewrite ``?`` placeholders for the driver's paramstyle, skipping
    string literals ('...', "...") so a '?' inside SQL text survives, and
    escaping literal '%' for format-style drivers (which would otherwise
    treat it as a directive)."""
    if paramstyle == "qmark":
        return query
    out: list[str] = []
    quote: str | None = None
    n = 0
    for ch in query:
        if quote is not None:
            if ch == "%" and paramstyle in ("format", "pyformat"):
                out.append("%%")
                continue
            out.append(ch)
            if ch == quote:
                quote = None
            continue
        if ch in ("'", '"'):
            quote = ch
            out.append(ch)
        elif ch == "?":
            n += 1
            out.append("%s" if paramstyle in ("format", "pyformat") else f":{n}")
        elif ch == "%" and paramstyle in ("format", "pyformat"):
            out.append("%%")
        else:
            out.append(ch)
    return "".join(out)


class Tx:
    """Transaction facade (reference db.go wraps sql.Tx the same way)."""

    def __init__(self, db: "DB"):
        self._db = db
        self._done = False
        if db._explicit_begin:
            # sqlite runs in autocommit (isolation_level=None) so DDL is
            # transactional too — we issue BEGIN/COMMIT ourselves
            db._execute_no_commit("BEGIN")

    def query(self, query: str, *args) -> list[dict[str, Any]]:
        return self._db.query(query, *args)

    def execute(self, query: str, *args) -> int:
        return self._db._execute_no_commit(query, *args)

    def commit(self) -> None:
        self._done = True
        self._db._observed("COMMIT", self._db._conn.commit)

    def rollback(self) -> None:
        self._done = True
        self._db._observed("ROLLBACK", self._db._conn.rollback)

    def __enter__(self) -> "Tx":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        if self._done:
            return
        if exc_type is not None:
            self.rollback()
        else:
            self.commit()


class DB:
    """The SQL datasource carried on the container (``ctx.sql``)."""

    def __init__(self, conn, dialect: str, logger: DSLogger | None = None,
                 metrics=None, host: str = "", database: str = "",
                 paramstyle: str = "qmark"):
        self._conn = conn
        self.dialect = dialect
        self.logger = logger
        self.metrics = metrics
        self.host = host
        self.database = database
        self.paramstyle = paramstyle
        self._lock = threading.RLock()  # DB-API conns are not thread-safe
        self._open = True
        self._in_use = 0
        self._explicit_begin = dialect == "sqlite"

    # -- observation (reference db.go:30-49 logQuery + metrics) --------------
    def _record(self, query: str, dur_us: float) -> None:
        if self.metrics is not None:
            try:
                self.metrics.record_histogram(
                    "app_sql_stats", dur_us,
                    type=query.split(None, 1)[0].upper() if query else "")
                self.metrics.set_gauge("app_sql_open_connections",
                                       1.0 if self._open else 0.0)
                self.metrics.set_gauge("app_sql_inUse_connections",
                                       float(self._in_use))
            except Exception:
                pass
        if self.logger is not None:
            self.logger.debug({"event": "sql query", "query": query,
                               "duration_us": int(dur_us)})

    def _observed(self, label: str, fn, *args):
        start = time.perf_counter()
        self._in_use += 1
        try:
            return fn(*args)
        finally:
            self._in_use -= 1
            self._record(label, (time.perf_counter() - start) * 1e6)

    # -- core ops (reference db.go:51-148) -----------------------------------
    def _cursor_exec(self, query: str, args: Sequence) :
        cur = self._conn.cursor()
        cur.execute(_translate_placeholders(query, self.paramstyle), tuple(args))
        return cur

    def query(self, query: str, *args) -> list[dict[str, Any]]:
        """Rows as dicts keyed by column name."""
        with self._lock:
            def run():
                cur = self._cursor_exec(query, args)
                cols = [d[0] for d in cur.description] if cur.description else []
                rows = [dict(zip(cols, r)) for r in cur.fetchall()]
                cur.close()
                return rows
            return self._observed(query, run)

    def query_row(self, query: str, *args) -> dict[str, Any] | None:
        rows = self.query(query, *args)
        return rows[0] if rows else None

    def execute(self, query: str, *args) -> int:
        """Run DML/DDL and commit; returns affected row count."""
        with self._lock:
            def run():
                cur = self._cursor_exec(query, args)
                n = cur.rowcount
                cur.close()
                self._conn.commit()
                return n
            return self._observed(query, run)

    def _execute_no_commit(self, query: str, *args) -> int:
        with self._lock:
            def run():
                cur = self._cursor_exec(query, args)
                n = cur.rowcount
                cur.close()
                return n
            return self._observed(query, run)

    def begin(self) -> Tx:
        """Start a transaction (reference db.go Begin); use as a context
        manager: commits on success, rolls back on exception."""
        return Tx(self)

    # -- select into dataclasses (reference db.go:179-279) -------------------
    def select(self, into: type, query: str, *args) -> list[Any]:
        """Map rows into dataclass instances. Column matching: exact field
        name, else the field's snake_case form (reference db tag / snake-case
        fallback, db.go:233-277)."""
        if not dataclasses.is_dataclass(into):
            raise TypeError(f"select target must be a dataclass, got {into!r}")
        rows = self.query(query, *args)
        fields = dataclasses.fields(into)
        out = []
        for row in rows:
            kw = {}
            lower_row = {k.lower(): v for k, v in row.items()}
            for f in fields:
                col = f.metadata.get("db") if f.metadata else None
                for candidate in (col, f.name, to_snake_case(f.name)):
                    if candidate and candidate.lower() in lower_row:
                        kw[f.name] = lower_row[candidate.lower()]
                        break
            out.append(into(**kw))
        return out

    # -- health (reference health.go:26-65) ----------------------------------
    def health_check(self) -> Health:
        try:
            with self._lock:
                cur = self._conn.cursor()
                cur.execute("SELECT 1")
                cur.fetchall()
                cur.close()
            return Health(status=STATUS_UP, details={
                "dialect": self.dialect, "host": self.host,
                "database": self.database, "open_connections": 1,
                "in_use": self._in_use})
        except Exception as e:
            return Health(status=STATUS_DOWN, details={
                "dialect": self.dialect, "host": self.host, "error": repr(e)})

    def close(self) -> None:
        with self._lock:
            self._open = False
            try:
                self._conn.close()
            except Exception:
                pass


def new_sql(cfg, logger: DSLogger | None = None, metrics=None) -> DB:
    """Wire a DB from config (reference sql.go:29-92).

    Keys: DB_DIALECT (sqlite|mysql|postgres, default sqlite), DB_HOST,
    DB_PORT, DB_USER, DB_PASSWORD, DB_NAME.
    """
    dialect = (cfg.get("DB_DIALECT") or "sqlite").lower()
    name = cfg.get_or_default("DB_NAME", ":memory:")
    host = cfg.get_or_default("DB_HOST", "localhost")

    if dialect in ("sqlite", "sqlite3"):
        import sqlite3

        conn = sqlite3.connect(name, check_same_thread=False)
        # autocommit mode: the DB layer controls transactions explicitly, so
        # DDL participates in Tx rollback (python sqlite3's legacy implicit
        # transactions autocommit DDL, which would leak half-applied
        # migrations)
        conn.isolation_level = None
        return DB(conn, "sqlite", logger, metrics, host="local",
                  database=name, paramstyle="qmark")

    if dialect == "mysql":
        try:
            import pymysql  # gated: not in the base image
        except ImportError as e:
            raise RuntimeError("mysql dialect requires the pymysql driver") from e
        conn = pymysql.connect(
            host=host, port=cfg.get_int("DB_PORT", 3306),
            user=cfg.get("DB_USER"), password=cfg.get("DB_PASSWORD"),
            database=name)
        return DB(conn, "mysql", logger, metrics, host=host, database=name,
                  paramstyle="format")

    if dialect in ("postgres", "postgresql"):
        try:
            import psycopg2  # gated: not in the base image
        except ImportError as e:
            raise RuntimeError("postgres dialect requires psycopg2") from e
        conn = psycopg2.connect(
            host=host, port=cfg.get_int("DB_PORT", 5432),
            user=cfg.get("DB_USER"), password=cfg.get("DB_PASSWORD"),
            dbname=name)
        return DB(conn, "postgres", logger, metrics, host=host, database=name,
                  paramstyle="format")

    raise ValueError(f"unsupported DB_DIALECT {dialect!r}")
