"""Google Cloud Pub/Sub driver (gated: requires ``google-cloud-pubsub``).

Reference: pkg/gofr/datasource/pubsub/google/google.go —
  - auto-creates the topic and a ``<sub>-<topic>`` subscription
    (getTopic/getSubscription, google.go:135-172)
  - blocking single-message receive with cancel (google.go:93-133)
  - health lists topics/subscriptions (health.go:12-30)
"""

from __future__ import annotations

import queue
from typing import Optional

from .. import Health, STATUS_DOWN, STATUS_UP
from . import Message


class GooglePubSubClient:
    """Seam: ``publisher``/``subscriber`` are injectable objects exposing
    the narrow client surface this driver uses (topic_path, create_topic,
    publish, subscription_path, create_subscription, subscribe,
    delete_topic, list_topics, close) — the reference tests its google
    driver against exactly such mock clients (google/mock_interfaces.go).
    Default: the real google-cloud-pubsub clients (gated import)."""

    def __init__(self, project_id: str, subscription_name: str = "gofr-sub",
                 logger=None, publisher=None, subscriber=None):
        if not project_id:
            raise ValueError("GOOGLE_PROJECT_ID is required")
        if publisher is None or subscriber is None:
            try:
                from google.cloud import pubsub_v1  # gated import
            except ImportError as e:
                raise RuntimeError(
                    "GOOGLE backend requires the google-cloud-pubsub "
                    "package") from e
            publisher = publisher or pubsub_v1.PublisherClient()
            subscriber = subscriber or pubsub_v1.SubscriberClient()
        self.project_id = project_id
        self.subscription_name = subscription_name
        self.logger = logger
        self._publisher = publisher
        self._subscriber = subscriber
        self._known_topics: set[str] = set()
        self._known_subs: set[str] = set()

    def _topic_path(self, topic: str) -> str:
        return self._publisher.topic_path(self.project_id, topic)

    def _sub_path(self, topic: str) -> str:
        # reference google.go:155: subscription named "<sub>-<topic>"
        return self._subscriber.subscription_path(
            self.project_id, f"{self.subscription_name}-{topic}")

    @staticmethod
    def _is_already_exists(e: Exception) -> bool:
        try:
            from google.api_core.exceptions import AlreadyExists

            return isinstance(e, AlreadyExists)
        except ImportError:
            return "AlreadyExists" in type(e).__name__

    def _ensure_topic(self, topic: str) -> str:
        path = self._topic_path(topic)
        if topic not in self._known_topics:
            try:
                self._publisher.create_topic(name=path)
            except Exception as e:
                if not self._is_already_exists(e):
                    # permission/connectivity errors must surface — caching
                    # the topic as known would hide the real cause behind
                    # NotFound on every later publish
                    raise
            self._known_topics.add(topic)
        return path

    def _ensure_subscription(self, topic: str) -> str:
        sub = self._sub_path(topic)
        if sub not in self._known_subs:
            try:
                self._subscriber.create_subscription(
                    name=sub, topic=self._ensure_topic(topic))
            except Exception as e:
                if not self._is_already_exists(e):
                    raise
            self._known_subs.add(sub)
        return sub

    def publish(self, topic: str, message: bytes) -> None:
        self._publisher.publish(self._ensure_topic(topic), message).result(timeout=30)

    def subscribe(self, topic: str, timeout: Optional[float] = None) -> Message | None:
        """Blocking single-message receive then cancel
        (reference google.go:93-133)."""
        sub_path = self._ensure_subscription(topic)
        q: queue.Queue = queue.Queue(maxsize=1)

        def on_message(received):
            try:
                q.put_nowait(received)
            except queue.Full:
                received.nack()

        future = self._subscriber.subscribe(sub_path, callback=on_message)
        try:
            received = q.get(timeout=timeout if timeout is not None else 30.0)
        except queue.Empty:
            return None
        finally:
            future.cancel()
        return Message(topic, received.data,
                       metadata=dict(received.attributes or {}),
                       committer=received.ack)

    def create_topic(self, name: str) -> None:
        self._ensure_topic(name)

    def delete_topic(self, name: str) -> None:
        try:
            self._publisher.delete_topic(topic=self._topic_path(name))
        except Exception:
            pass
        self._known_topics.discard(name)

    def health_check(self) -> Health:
        try:
            project = f"projects/{self.project_id}"
            topics = [t.name for t in self._publisher.list_topics(
                project=project, timeout=0.5)]
            return Health(status=STATUS_UP,
                          details={"backend": "GOOGLE", "topics": topics})
        except Exception as e:
            return Health(status=STATUS_DOWN,
                          details={"backend": "GOOGLE", "error": repr(e)})

    def close(self) -> None:
        try:
            self._subscriber.close()
        except Exception:
            pass
